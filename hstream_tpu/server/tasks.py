"""Managed continuous-query tasks.

The reference runs each continuous query as a forked green thread: a
checkpointed reader polls the source stream(s), every record walks the
processor DAG, and sink processors append results downstream
(runTaskWrapper, Handler/Common.hs:169-180; runTask, Processor.hs:99-144).

Here a task is one daemon thread per query driving the batched engine:
read a chunk from the checkpointed reader -> decode JSON records ->
executor.process (the jitted lattice step) -> emit rows to the sink
callback -> checkpoint.

Checkpointing improves on the reference (which checkpoints readers only
— operator state is in-memory, so its restarts undercount every window
spanning them, Codegen.hs:374-385): read positions are committed ONLY
paired with an operator-state snapshot, in one atomic meta-KV write
(engine.snapshot). Resume restores the state and continues from the
paired LSNs — exact, modulo at-least-once re-emission of rows sunk
after the last snapshot.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import traceback
from typing import Any, Callable

import numpy as np

from hstream_tpu.common import columnar, jsondec, locktrace
from hstream_tpu.common import records as rec
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.tracing import QueryTracer, trace_span
from hstream_tpu.engine.pipeline import IngestPipeline
from hstream_tpu.engine.snapshot import (
    capture_executor,
    open_blob,
    restore_executor,
    seal_blob,
    serialize_capture,
)
from hstream_tpu.server.context import (
    DEFAULT_ENCODE_WORKERS,
    DEFAULT_PIPELINE_DEPTH,
)
from hstream_tpu.server.persistence import QueryInfo, TaskStatus
from hstream_tpu.store.api import LSN_MIN, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("tasks")

SinkFn = Callable[[list[dict[str, Any]]], None]

READ_CHUNK = 2048
POLL_TIMEOUT_MS = 50
PREFETCH_BATCHES = 2  # read-ahead depth of the reader prefetch thread


def snapshot_key(query_id: str) -> str:
    """Meta-KV key holding a query's operator-state snapshot: either a
    legacy raw npz blob (pre-ISSUE 8 servers) or a pointer to the
    current slot of the two-slot rotation."""
    return f"qsnap/{query_id}"


def snapshot_slot_key(query_id: str, slot: int) -> str:
    """One slot of the two-slot last-good snapshot rotation."""
    return f"qsnap/{query_id}@{slot}"


# pointer payload: magic + JSON {"slot": 0|1}. Written AFTER the slot
# blob, so a crash (or torn write) between the two leaves the pointer
# at the previous good slot.
SNAP_PTR_MAGIC = b"HSPTR1"


def parse_snapshot_pointer(raw: bytes) -> int | None:
    """Slot named by a two-slot rotation pointer, or None when ``raw``
    is not a pointer (legacy direct blob). A corrupt pointer parses to
    slot 0 — restore walks both slots anyway. The ONE place pointer
    bytes are interpreted: restore and the admin `snapshots` verb must
    never disagree on which slot is current."""
    if not raw.startswith(SNAP_PTR_MAGIC):
        return None
    try:
        return int(json.loads(raw[len(SNAP_PTR_MAGIC):])["slot"]) & 1
    except (ValueError, KeyError, TypeError):
        return 0


class QueryTask(threading.Thread):
    """One continuous query: source stream(s) -> executor -> sink rows."""

    # state snapshot + checkpoint cadence; tests lower it
    snapshot_interval_ms: int = 1000

    def __init__(self, ctx, info: QueryInfo, plan, sink: SinkFn, *,
                 from_beginning: bool = True):
        super().__init__(name=f"query-{info.query_id}", daemon=True)
        self.ctx = ctx
        self.info = info
        self.plan = plan
        self.from_beginning = from_beginning
        # per-context override wins over the class default (main.serve)
        ctx_iv = getattr(ctx, "snapshot_interval_ms", None)
        if ctx_iv is not None:
            self.snapshot_interval_ms = ctx_iv
        self.executor = None
        self.error: BaseException | None = None
        # serializes executor state mutation (this thread) against pull
        # queries peeking live state from gRPC threads (views.snapshot).
        # Named + traced (ISSUE 14): this is the busiest cross-object
        # lock in the server — the canonical order (tasks.state before
        # views.materialization / pipeline internals) is what the
        # armed witness certifies
        self.state_lock = locktrace.rlock("tasks.state")
        # optional sink-side state riding in the snapshot (a view's
        # closed-row materialization survives restarts this way)
        self.sink_dump: Callable[[], Any] | None = None
        self.sink_load: Callable[[Any], None] | None = None
        self._stop_ev = threading.Event()
        # readiness: set once the reader is attached to every source at
        # its start LSN — tests and callers wait on this instead of
        # sleeping (the notification mechanism the reference's test tier
        # lacks: "FIXME: requires a notification mechanism",
        # RunSQLSpec.hs:54)
        self.attached = threading.Event()
        self.attached_lsns: dict[int, int] = {}  # logid -> start LSN
        self._sources: dict[int, str] = {}  # logid -> stream name
        for name in self.source_streams():
            self._sources[ctx.streams.get_logid(name)] = name
        self._reader: CheckpointedReader | None = None
        # overlapped ingest: wire-encode + upload on a pool of worker
        # threads while this thread dispatches earlier batches' steps
        # in order (engine.pipeline); created lazily for executors with
        # a staged columnar path (plain aggregates — joins/sessions
        # stay on the row path)
        self._pipe: IngestPipeline | None = None
        self.pipeline_depth = int(getattr(ctx, "pipeline_depth",
                                          DEFAULT_PIPELINE_DEPTH))
        self.encode_workers = int(getattr(ctx, "encode_workers",
                                          DEFAULT_ENCODE_WORKERS))
        # reader prefetch (the HStreamDB layer-0/1 producer/consumer
        # split): a read-ahead thread polls the store so JSON decode +
        # encode of chunk N+1 overlaps the device work of chunk N
        self._read_q: queue.Queue = queue.Queue(maxsize=PREFETCH_BATCHES)
        self._read_thread: threading.Thread | None = None
        # always-on per-stage timing rings (SURVEY §5.1); every span
        # also lands in the holder's stage_latency_ms histogram so
        # /metrics carries per-stage percentiles across all queries
        self.tracer = QueryTracer(observer=self._observe_stage)
        self._pending_ckps: dict[int, int] = {}  # processed, not committed
        self._last_flow_feed = 0.0  # overload-signal feed rate limit
        self._flow_chunks = 0       # warmup chunks skipped (jit compile)
        self._join_probe_seen = 0   # join probe dispatches mirrored out
        self._last_snapshot_ms = 0.0
        self._last_persist_ms = 0.0   # cost of the last state write
        self._last_inline_ms = 0.0    # capture-side stall of last snap
        # condition over a traced re-entrant lock: waits release the
        # lock through the wrapper, so the held-set stays truthful
        self._persist_cv = threading.Condition(
            locktrace.rlock("tasks.persist"))
        self._persist_pending = None  # latest un-persisted capture
        self._persist_busy = False
        self._persist_stop = False
        self._persist_thread: threading.Thread | None = None
        self._dirty = False
        self._crash = False
        self._detach = False
        # two-slot snapshot rotation: next slot to write (restore sets
        # it to the OTHER slot than the one it loaded, so the last
        # known-good snapshot is never the one being overwritten)
        self._snap_slot = 0
        # device-fallback mirror: engine executors count activations
        # that degraded to the host reference path on themselves;
        # deltas land in the device_path_fallbacks counter
        self._dev_fallback_seen = 0
        # engine-counter mirrors (ISSUE 13): late drops + H2D/D2H
        # bytes, delta-based like the fallback mirror
        self._late_seen = 0
        self._h2d_seen = 0
        self._d2h_seen = 0
        # multi-chip plane (ISSUE 16): shard_map dispatch mirror (a
        # JoinExecutor's property already folds its inner aggregate,
        # so the mirror reads the executor attr directly — NEVER via
        # engine_total, which would double-count the inner)
        self._sharded_seen = 0
        # event-time freshness plane (ISSUE 13): the publish-time
        # watermark of ingested records (max record append/publish ms
        # seen) and the wall clock when it was picked up — emission
        # observes append->visible and per-stage lag from these, all
        # host values (zero added dispatches/fetches)
        self._publish_wm_ms = -1
        self._pickup_wall_ms = 0.0
        # every emission flows through the freshness-instrumented sink
        self.sink = self._wrap_sink(sink)

    def _observe_stage(self, stage: str, seconds: float) -> None:
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.observe("stage_latency_ms", stage, seconds * 1e3)
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the ingest loop

    def _observe_kernel(self, family: str, seconds: float) -> None:
        """Engine dispatch observer (ISSUE 13): per-kernel-family host
        dispatch time (step/close/probe/session) into /metrics."""
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.observe("kernel_dispatch_ms", family,
                              seconds * 1e3)
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the ingest loop

    # ---- event-time freshness plane (ISSUE 13) -----------------------------

    def _wrap_sink(self, sink: SinkFn) -> SinkFn:
        """Freshness-instrumented sink: every emission observes
        append->visible latency (publish-time watermark -> now, the
        end-to-end number for views and sink streams), the engine-stage
        lag (wall since the publish watermark's pickup), and the close
        cycle's event-time emit latency — host arithmetic only. The
        original sink's durability barrier (`flush`) rides through."""
        stats = getattr(self.ctx, "stats", None)
        if stats is None:
            return sink

        def wrapped(rows):
            sink(rows)
            if rows is not None and len(rows):
                self._note_emit_freshness(stats, rows)

        flush = getattr(sink, "flush", None)
        if flush is not None:
            wrapped.flush = flush
        return wrapped

    def _note_emit_freshness(self, stats, rows) -> None:
        now = time.time() * 1e3
        qid = self.info.query_id
        try:
            # per-query emission ladder (ISSUE 15): rows on the wire
            # and completed close cycles — the query-scoped stat
            # families the federation fold and `admin stats queries`
            # serve
            stats.stat_add("emit_rows", qid, float(len(rows)))
            stats.stat_add("close_cycles", qid)
        except Exception:  # noqa: BLE001 — metrics must not kill emit
            pass
        try:
            if self._publish_wm_ms >= 0:
                # append -> visible: the emitted answer now reflects
                # (at least) everything published up to the watermark
                stats.observe("append_visible_latency_ms", qid,
                              max(0.0, now - self._publish_wm_ms))
                # engine stage: pickup of the newest ingested records
                # -> rows on the wire (pipeline depth + device work)
                stats.observe("freshness_lag_ms", "engine",
                              max(0.0, now - self._pickup_wall_ms))
            wm = self._event_watermark()
            win_end = _max_win_end(rows)
            if wm is not None:
                # emit latency: max event time the emitted rows can
                # cover (their window end, capped at the watermark —
                # the host mirror of "max event ts in the close
                # cycle") -> wall at emission
                ref = wm if win_end is None else min(win_end, wm)
                stats.observe("emit_latency_ms", qid,
                              max(0.0, now - ref))
        except Exception:  # noqa: BLE001 — metrics must not kill
            pass           # the emit path

    def _event_watermark(self) -> int | None:
        """The executor's event-time watermark (host attribute,
        whichever engine): fixed windows track watermark_abs, sessions
        and joins track watermark. The ONE place that fold lives —
        the freshness gauges and the health plane both read it here."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return None
        wm = getattr(ex, "watermark_abs", None)
        if wm is None:
            wm = getattr(ex, "watermark", None)
        if wm is None or wm < 0:
            return None
        return int(wm)

    def read_version(self) -> tuple | None:
        """The executor's read-plane version tuple (ISSUE 20) — what
        the read cache validates snapshot hits against. None while no
        executor runs or the engine carries no versioning (stateless):
        such state never caches."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return None
        fn = getattr(ex, "read_version", None)
        return None if fn is None else fn()

    def engine_total(self, attr: str) -> int:
        """Sum a host counter over the executor AND a join's lazily
        created inner aggregate (device_fallbacks, late_drops) — the
        one fold the /metrics mirror and the health plane share."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return 0
        total = int(getattr(ex, attr, 0))
        inner = getattr(ex, "_inner", None)
        if inner is not None:
            total += int(getattr(inner, attr, 0))
        return total

    def device_plane_bytes(self) -> dict[str, int]:
        """Exact live device bytes per engine plane — the HBM
        accounting fold devicecost.sample_device_gauges scrapes. Zero
        dispatches, zero fetches: nbytes is shape metadata."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return {}
        fn = getattr(ex, "device_plane_bytes", None)
        if fn is None:
            return {}
        try:
            return fn()
        except Exception:  # noqa: BLE001 — a half-built executor must
            return {}      # not kill the stats sweep

    def mesh_shards(self) -> int:
        """Key-axis size of the running executor's mesh, 0 when the
        query executes single-chip (no mesh, or a mesh whose key axis
        is 1 — the executors only build sharded lattices for >1)."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return 0
        mesh = getattr(ex, "mesh", None)
        if mesh is None:
            mesh = getattr(ex, "_mesh", None)  # ShardedQueryExecutor
        if mesh is None:
            return 0
        axis = getattr(ex, "key_axis", None) \
            or getattr(ex, "_key_axis", "key")
        try:
            if axis not in mesh.axis_names:
                return 0
            n = int(mesh.shape[axis])
        except Exception:  # noqa: BLE001 — a half-built mesh must not
            return 0       # kill the stats sweep
        return n if n > 1 else 0

    def _note_ingest_freshness(self, publish_ms: int) -> None:
        """Called once per ingested chunk with the chunk's max record
        publish/append time: advances the publish watermark (+ its
        pickup wall clock) and observes the ingest-stage lag (time the
        records sat in the store + read path)."""
        now = time.time() * 1e3
        if publish_ms > self._publish_wm_ms:
            self._publish_wm_ms = publish_ms
            self._pickup_wall_ms = now
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.observe("freshness_lag_ms", "ingest",
                              max(0.0, now - publish_ms))
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the ingest loop

    def _journal(self, kind: str, message: str, **fields) -> None:
        events = getattr(self.ctx, "events", None)
        if events is not None:
            try:
                events.append(kind, message, **fields)
            except Exception:  # noqa: BLE001
                pass

    def _count_stat(self, metric: str) -> None:
        """Bump a per-query counter (label = query id); never fatal."""
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.stream_stat_add(metric, self.info.query_id)
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # recovery paths

    def _note_decode(self, metric: str, logid: int, n: int) -> None:
        """Count records through the native libjsondec batch decoder vs
        the per-record Python fallback, per source stream — the /metrics
        evidence that the JSON append path actually hits the native
        decoder (server_json_eps regressions otherwise hide a silent
        fallback)."""
        stats = getattr(self.ctx, "stats", None)
        if stats is None or n <= 0:
            return
        try:
            stats.stream_stat_add(metric, self._sources[logid], n)
        except Exception:  # noqa: BLE001 — metrics must not kill ingest
            pass

    def source_streams(self) -> list[str]:
        names = [self.plan.source]
        if self.plan.join is not None:
            names.append(self.plan.join.right.name)
        return names

    @property
    def is_join(self) -> bool:
        return self.plan.join is not None

    # ---- lifecycle ---------------------------------------------------------

    def stop(self, timeout: float = 10.0, *, crash: bool = False,
             detach: bool = False) -> None:
        """Stop modes:
        default — user-initiated terminate: final snapshot + TERMINATED.
        detach=True — server shutdown: final snapshot but status stays
        RUNNING so boot-time resume_persisted relaunches the query.
        crash=True — fault injection (tests): no snapshot, no status
        update, like a killed process; resume replays from the last
        periodic snapshot."""
        if crash:
            self._crash = True
        if detach:
            self._detach = True
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)

    def run(self) -> None:
        ctx = self.ctx
        try:
            reader = CheckpointedReader(
                f"query-{self.info.query_id}",
                ctx.store.new_reader(max_logs=len(self._sources)),
                ctx.ckp_store)
            self._reader = reader
            reader.set_timeout(POLL_TIMEOUT_MS)
            resumed = self._restore_state()
            for logid in self._sources:
                if resumed is not None and logid in resumed:
                    start = resumed[logid] + 1
                    reader.start_reading(logid, start)
                else:
                    start = reader.start_reading_from_checkpoint(
                        logid, LSN_MIN)
                self.attached_lsns[logid] = start
            ctx.persistence.set_query_status(self.info.query_id,
                                             TaskStatus.RUNNING)
            self.attached.set()
            self._read_thread = threading.Thread(
                target=self._read_loop, args=(reader,),
                name=f"read-{self.info.query_id}", daemon=True)
            self._read_thread.start()
            while not self._stop_ev.is_set():
                try:
                    results = self._read_q.get(
                        timeout=POLL_TIMEOUT_MS / 1000)
                except queue.Empty:
                    results = None
                if isinstance(results, BaseException):
                    raise results  # reader died on the prefetch thread
                if not results:
                    # idle tick: finish any staged-but-unprocessed
                    # batches so emitted rows lag ingest by at most one
                    # poll cycle, then drain deferred changelog fetches
                    self._drain_pipe()
                    self._flush_deferred_changes()
                    self._maybe_snapshot()
                    # idle = not overloaded: zero samples decay the
                    # latency EWMA so the shed level recovers
                    self._feed_flow_signals(0.0)
                    continue
                if FAULTS.active:  # chaos: crash mid-batch — the chunk
                    # is read but neither processed nor checkpointed
                    FAULTS.point("task.step")
                t_step = time.perf_counter()
                self._ingest_results(results)
                self._feed_flow_signals(time.perf_counter() - t_step)
                for r in results:
                    lsn = (r.lsn if isinstance(r, DataBatch) else r.hi_lsn)
                    if lsn > self._pending_ckps.get(r.logid, 0):
                        self._pending_ckps[r.logid] = lsn
                        self._dirty = True
                self._maybe_snapshot()
            if not self._crash:
                # graceful stop: final snapshot persists INLINE so state
                # is durable before the thread exits
                self._snapshot_now(sync=True)
                if not self._detach:
                    ctx.persistence.set_query_status(
                        self.info.query_id, TaskStatus.TERMINATED)
            # detach (server shutdown) and crash both leave status
            # RUNNING so boot-time resume_persisted relaunches the query
        except BaseException as e:  # noqa: BLE001 — status must reflect death
            self.error = e
            log.error("query %s died: %s\n%s", self.info.query_id, e,
                      traceback.format_exc())
            self._journal("query_died",
                          f"query {self.info.query_id} died: "
                          f"{type(e).__name__}: {e}",
                          query=self.info.query_id,
                          error=type(e).__name__)
            try:
                ctx.persistence.set_query_status(self.info.query_id,
                                                 TaskStatus.CONNECTION_ABORT)
            except Exception:
                pass
            # self-healing: hand the death to the supervisor UNLESS a
            # stop was requested (an operator stop racing an error must
            # not resurrect the query)
            sup = getattr(ctx, "supervisor", None)
            if sup is not None and not self._stop_ev.is_set():
                try:
                    sup.note_death(self.info, e)
                except Exception:  # noqa: BLE001 — supervision must
                    pass           # not mask the original death
        finally:
            t = self._read_thread
            if t is not None:
                # the prefetch thread watches _stop_ev; reap it BEFORE
                # the persist worker so no reader call races teardown
                self._stop_ev.set()
                t.join(timeout=10)
            with self._persist_cv:
                self._persist_stop = True
                self._persist_cv.notify_all()
            t = self._persist_thread
            if t is not None:
                # reap the persist worker HERE, not at interpreter
                # teardown: a daemon thread caught mid device fetch
                # during runtime destruction aborts the process
                t.join(timeout=10)
            with self.state_lock:
                pipe = self._pipe
            if pipe is not None:
                pipe.close()
            ctx.running_queries.pop(self.info.query_id, None)

    def _read_loop(self, reader: CheckpointedReader) -> None:
        """Prefetch thread: poll the store ahead of the ingest loop so
        the next chunk's bytes are in hand while the current chunk
        decodes/encodes/computes. Read errors travel to the task thread
        as a sentinel (raised at its next get). Only reader.read runs
        here — checkpoint writes stay on the task/persist threads."""
        while not self._stop_ev.is_set():
            try:
                results = reader.read(READ_CHUNK)
            except BaseException as e:  # noqa: BLE001 — surfaced on
                # the task thread; this thread must not die silently
                results = e
            while not self._stop_ev.is_set():
                try:
                    self._read_q.put(results, timeout=0.25)
                    break
                except queue.Full:
                    continue
            if isinstance(results, BaseException):
                return

    def _feed_flow_signals(self, step_s: float) -> None:
        """Feed the overload detector the signals this task produces:
        per-chunk step latency every chunk (an EWMA update, cheap), and
        pipeline occupancy + reorder-ring depth at ~1 Hz (stats() walks
        the stage rings)."""
        self._note_device_fallbacks()
        flow = getattr(self.ctx, "flow", None)
        if flow is None:
            return
        if step_s > 0.0 and self._flow_chunks < 5:
            # warmup: the first real chunks pay jit compile (seconds on
            # a cold cache) — steady-state overload they are not; idle
            # zero-samples don't consume the warmup budget
            self._flow_chunks += 1
            return
        det = flow.overload
        qid = self.info.query_id  # per-source EWMA: tasks don't blend
        det.note("step_latency_ms", step_s * 1000.0, source=qid)
        with self.state_lock:  # _pipe is guarded (hstream-analyze)
            pipe = self._pipe
        if pipe is None:
            return
        now = time.monotonic()
        if now - self._last_flow_feed < 1.0:
            return
        self._last_flow_feed = now
        st = pipe.stats()
        det.note("pipeline_occupancy",
                 max(st.get("encode_occupancy", 0.0),
                     st.get("step_occupancy", 0.0)), source=qid)
        det.note("reorder_depth",
                 pipe.pending / max(self.pipeline_depth, 1), source=qid)

    def _note_device_fallbacks(self) -> None:
        """Mirror engine-side counters into /metrics, delta-based,
        once per chunk/idle tick: device->host path degradations (join
        activation / fused close falling back to the reference path),
        late-record drops, and H2D/D2H transfer bytes — all plain host
        counters the executors maintain on themselves."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return
        inner = getattr(ex, "_inner", None)
        stats = getattr(self.ctx, "stats", None)
        if inner is not None \
                and getattr(inner, "dispatch_observer", 1) is None:
            # a join's downstream aggregate is created lazily — wire
            # its dispatch observer the first time it appears
            inner.dispatch_observer = self._observe_kernel

        def transfer(key: str) -> int:
            cur = int(getattr(ex, "transfer_stats", {}).get(key, 0))
            if inner is not None:
                cur += int(getattr(inner, "transfer_stats",
                                   {}).get(key, 0))
            return cur

        cur = self.engine_total("device_fallbacks")
        delta = cur - self._dev_fallback_seen
        if delta > 0 and stats is not None:
            self._dev_fallback_seen = cur
            try:
                stats.stream_stat_add("device_path_fallbacks",
                                      self.plan.source, delta)
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the ingest loop
        if stats is None:
            return
        try:
            late = self.engine_total("late_drops")
            if late > self._late_seen:
                stats.stream_stat_add("late_drops", self.info.query_id,
                                      late - self._late_seen)
                self._late_seen = late
            h2d = transfer("h2d_bytes")
            if h2d > self._h2d_seen:
                stats.stream_stat_add("device_h2d_bytes",
                                      self.plan.source,
                                      h2d - self._h2d_seen)
                self._h2d_seen = h2d
            d2h = transfer("d2h_bytes")
            if d2h > self._d2h_seen:
                stats.stream_stat_add("device_d2h_bytes",
                                      self.plan.source,
                                      d2h - self._d2h_seen)
                self._d2h_seen = d2h
            # shard_map dispatches (ISSUE 16): read the executor attr
            # directly — JoinExecutor.sharded_dispatches is a property
            # that already folds its inner aggregate, so engine_total
            # would double-count it
            sd = int(getattr(ex, "sharded_dispatches", 0) or 0)
            if sd > self._sharded_seen:
                stats.stat_add("sharded_dispatches",
                               self.info.query_id,
                               float(sd - self._sharded_seen))
                self._sharded_seen = sd
        except Exception:  # noqa: BLE001 — metrics must not kill
            pass           # the ingest loop

    # ---- operator-state checkpointing --------------------------------------

    def _snapshot_candidates(self) -> list[tuple[str, bytes]]:
        """(label, sealed bytes) restore candidates, best first: the
        pointed-at slot, then the other slot (the previous good
        snapshot), or the single legacy blob."""
        qid = self.info.query_id
        raw = self.ctx.store.meta_get(snapshot_key(qid))
        if raw is None:
            return []
        slot = parse_snapshot_pointer(raw)
        if slot is None:
            return [("legacy", raw)]
        out = []
        for s in (slot, 1 - slot):
            data = self.ctx.store.meta_get(snapshot_slot_key(qid, s))
            if data is not None:
                out.append((f"slot {s}", data))
        return out

    def _restore_state(self) -> dict[int, int] | None:
        """Restore executor + sink state from the last snapshot. Returns
        the read positions the state corresponds to (logid -> committed
        LSN), or None when starting fresh.

        Integrity hardening (ISSUE 8): snapshot blobs are CRC-sealed
        and written to a two-slot rotation. A corrupt/torn newest slot
        journals ``snapshot_corrupt``, bumps ``snapshot_fallbacks`` and
        falls back to the previous good slot — restoring older state +
        its paired (older) checkpoints, so the gap REPLAYS instead of
        the query dying at boot. When every candidate is corrupt the
        checkpoints are removed too (rewind to the trim point) — a
        fresh aggregation beats a boot failure, and beats silently
        skipping the span the lost state covered."""
        qid = self.info.query_id
        candidates = self._snapshot_candidates()
        if not candidates:
            return None
        ex = extra = None
        for i, (label, sealed) in enumerate(candidates):
            try:
                blob = open_blob(sealed)
                if FAULTS.active:  # chaos: provoke a restore failure
                    FAULTS.point("snapshot.restore")
                with self.state_lock:
                    ex, extra = restore_executor(
                        self.plan, blob, mesh=self._query_mesh())
            except Exception as e:  # noqa: BLE001 — corrupt blob,
                # injected fault, or a restore bug: fall back rather
                # than die at boot
                log.error("query %s: snapshot %s unrestorable (%s); "
                          "falling back", qid, label, e)
                self._journal(
                    "snapshot_corrupt",
                    f"query {qid}: snapshot {label} unrestorable "
                    f"({type(e).__name__}: {e})",
                    query=qid, candidate=label, error=type(e).__name__)
                self._count_stat("snapshot_fallbacks")
                continue
            if label.startswith("slot"):
                # next persist must overwrite the OTHER slot, keeping
                # the one that just proved restorable
                self._snap_slot = 1 - int(label.split()[1])
            break
        if ex is None:
            # every candidate corrupt: rewind-from-trim-point — drop
            # the checkpoint mirror so the reader starts at its
            # fallback LSN and re-aggregates
            log.error("query %s: NO restorable snapshot (%d candidates)"
                      "; rewinding to trim point", qid, len(candidates))
            if self._reader is not None:
                self._reader.remove_checkpoints()
            return None
        with self.state_lock:
            self.executor = self._tune_executor(ex)
            if self.sink_load is not None and "sink" in extra:
                self.sink_load(extra["sink"])
        ckps = {int(k): int(v) for k, v in extra.get("ckps", {}).items()}
        self._pending_ckps = dict(ckps)
        # re-mirror to the ckp store: a crash between meta_put and
        # write_checkpoints leaves the observability mirror stale until
        # the next append; the blob's ckps are authoritative either way
        if self._reader is not None and self._pending_ckps:
            self._reader.write_checkpoints(self._pending_ckps)
        self._last_snapshot_ms = time.monotonic() * 1000
        log.info("query %s resumed from snapshot at %s",
                 self.info.query_id, ckps)
        return ckps

    def _flush_deferred_changes(self) -> None:
        """Drain deferred changelog extracts (queued, async-drain, or
        join-coalesced) AND deferred session closes to the sink — idle
        ticks and pre-snapshot; the snapshot guards require an empty
        queue on both surfaces."""
        with self.state_lock:  # executor is guarded (hstream-analyze)
            ex = self.executor
        if ex is None:
            return
        hp = getattr(ex, "has_pending_changes", None)
        pending = (hp() if hp is not None
                   else bool(getattr(ex, "_pending_changes", None)))
        hc = getattr(ex, "has_pending_closes", None)
        pending = pending or (hc is not None and hc())
        if not pending:
            return
        with self.state_lock:
            with trace_span(self.tracer, "close"):
                rows = ex.flush_changes()
            if rows:
                with trace_span(self.tracer, "emit"):
                    self.sink(rows)

    def _maybe_snapshot(self) -> None:
        if not self._dirty:
            return
        now = time.monotonic() * 1000
        # cadence scales with the measured cost of a snapshot — both
        # the inline stall (pipeline barrier + capture + sink flush)
        # and the background persist — so snapshotting never consumes
        # more than ~5% of wall time at ANY state size (SURVEY §7
        # item 8; VERDICT r4 weak #7). Bigger state => rarer
        # snapshots => longer replay-on-crash, the LogDevice trade.
        cost = self._last_inline_ms + self._last_persist_ms
        interval = max(self.snapshot_interval_ms, 19.0 * cost)
        if now - self._last_snapshot_ms >= interval:
            # snapshots are background work: shed them first under
            # overload — but never past 8x cadence, so replay-on-crash
            # stays bounded even through a sustained overload episode
            flow = getattr(self.ctx, "flow", None)
            if (flow is not None
                    and now - self._last_snapshot_ms < 8.0 * interval
                    and flow.admit_background("snapshot") > 0.0):
                return
            t0 = time.monotonic()
            self._snapshot_now()
            self._last_inline_ms = (time.monotonic() - t0) * 1000

    def _snapshot_now(self, *, sync: bool = False) -> None:
        # pipeline barrier FIRST: _pending_ckps covers every submitted
        # batch, so the captured state must too — read positions never
        # advance past durable state
        self._drain_pipe()
        self._flush_deferred_changes()
        with trace_span(self.tracer, "snapshot"):
            self._snapshot_now_inner(sync=sync)

    def _snapshot_now_inner(self, *, sync: bool = False) -> None:
        """Atomically persist (operator state, read checkpoints): one
        meta-KV write. Read positions NEVER advance past durable state —
        the reference's failure mode (commit-then-lose-state undercount)
        cannot happen. The ckp store mirrors the LSNs for observability.

        The task thread only CAPTURES (a consistent device-side
        reference under the lock — cheap); serialization (the full
        device->host state fetch + npz pack) and the store writes run
        on a latest-wins background worker so sustained ingest never
        stalls on snapshot size. sync=True (final snapshot on stop)
        persists inline after draining the worker."""
        if not self._dirty:
            return
        extra: dict[str, Any] = {
            "ckps": {str(k): v for k, v in self._pending_ckps.items()}}
        with self.state_lock:  # executor is guarded (hstream-analyze)
            executor = self.executor
        if executor is None:
            # nothing aggregated yet (e.g. raw records only): committing
            # the read position loses no state
            if self._reader is not None and self._pending_ckps:
                self._reader.write_checkpoints(self._pending_ckps)
            self._last_snapshot_ms = time.monotonic() * 1000
            self._dirty = False
            return
        with self.state_lock:
            if self.sink_dump is not None:
                extra["sink"] = self.sink_dump()
            meta, arrays = capture_executor(self.executor, extra)
            # break aliasing with the step's donated buffers: the async
            # persist serializes AFTER later steps have donated (and so
            # deleted) the captured arrays — a cheap on-device copy,
            # dispatched under the lock, pins this capture's values
            import jax
            import jax.numpy as jnp

            arrays = {k: (jnp.copy(v) if isinstance(v, jax.Array)
                          else v)
                      for k, v in arrays.items()}
        # durability barrier: async sink appends for everything captured
        # must land before this capture's checkpoints can ever commit
        flush = getattr(self.sink, "flush", None)
        if flush is not None:
            flush()
        self._last_snapshot_ms = time.monotonic() * 1000
        self._dirty = False
        if sync:
            self._drain_persist()
            self._persist_capture(meta, arrays,
                                  dict(self._pending_ckps))
            return
        with self._persist_cv:
            # latest wins: an unwritten older capture is superseded —
            # its checkpoints never commit, so resume just replays a
            # little more (at-least-once, unchanged)
            self._persist_pending = (meta, arrays,
                                     dict(self._pending_ckps))
            if self._persist_thread is None:
                self._persist_thread = threading.Thread(
                    target=self._persist_loop,
                    name=f"snap-{self.info.query_id}", daemon=True)
                self._persist_thread.start()
            self._persist_cv.notify_all()

    def _persist_loop(self) -> None:
        while True:
            with self._persist_cv:
                while (self._persist_pending is None
                       and not self._persist_stop):
                    self._persist_cv.wait(0.5)
                item = self._persist_pending
                self._persist_pending = None
                if item is None:
                    return  # stop requested, nothing pending
                self._persist_busy = True
            try:
                self._persist_capture(*item)
            except Exception as e:  # noqa: BLE001 — a failed write keeps
                # the previous snapshot; resume replays from it
                log.exception("snapshot persist for %s failed",
                              self.info.query_id)
                self._journal("snapshot_failed",
                              f"snapshot persist for "
                              f"{self.info.query_id} failed: "
                              f"{type(e).__name__}: {e}",
                              query=self.info.query_id,
                              error=type(e).__name__)
            finally:
                with self._persist_cv:
                    self._persist_busy = False
                    self._persist_cv.notify_all()

    def _persist_capture(self, meta, arrays, ckps: dict[int, int]) -> None:
        """Write one CRC-sealed snapshot into the two-slot rotation:
        slot blob first, pointer second. A crash or torn write anywhere
        in between leaves the pointer at the previous good slot, so
        restore never sees a half-written snapshot as newest-truth."""
        t0 = time.monotonic()
        qid = self.info.query_id
        sealed = seal_blob(serialize_capture(meta, arrays))
        if FAULTS.active:  # chaos: injected persist failure/torn write
            FAULTS.point("snapshot.persist")
            sealed = FAULTS.mutate("snapshot.persist", sealed)
        slot = self._snap_slot & 1
        self.ctx.store.meta_put(snapshot_slot_key(qid, slot), sealed)
        self.ctx.store.meta_put(
            snapshot_key(qid),
            SNAP_PTR_MAGIC + json.dumps({"slot": slot}).encode())
        self._snap_slot = 1 - slot
        if self._reader is not None and ckps:
            self._reader.write_checkpoints(ckps)
        self._last_persist_ms = (time.monotonic() - t0) * 1000

    def _drain_persist(self) -> None:
        deadline = time.monotonic() + 30
        with self._persist_cv:
            while ((self._persist_pending is not None
                    or self._persist_busy)
                   and time.monotonic() < deadline):
                self._persist_cv.wait(0.5)

    # ---- processing --------------------------------------------------------

    def _ingest_results(self, results: list) -> None:
        """Decode + dispatch one poll's worth of read results, coalescing
        payloads ACROSS appended batches of the same source log into one
        decode + engine step — per-append device dispatches would bound
        the JSON path at (records per append) / RTT on real links."""
        groups: list[tuple[int, list[bytes], list[int]]] = []
        newest = max((r.append_time_ms for r in results
                      if isinstance(r, DataBatch)), default=0)
        if newest > 0:
            # freshness plane: one ingest-lag observation per poll
            self._note_ingest_freshness(newest)
        for r in results:
            if not isinstance(r, DataBatch):
                continue
            if groups and groups[-1][0] == r.logid:
                groups[-1][1].extend(r.payloads)
                groups[-1][2].extend(
                    [r.append_time_ms] * len(r.payloads))
            else:
                groups.append((r.logid, list(r.payloads),
                               [r.append_time_ms] * len(r.payloads)))
        for logid, payloads, dts in groups:
            self._ingest_group(logid, payloads, dts)

    def _ingest_group(self, logid: int, payloads: list[bytes],
                      dts: list[int]) -> None:
        """One coalesced run of appended payloads from one source log.
        Multi-record runs go through the native batch decoder (C++ wire
        walk -> columns, common/jsondec); single records and fallback
        classes use the per-record Python path."""
        # zero-copy columnar fast path (ISSUE 12): a run of columnar
        # records — the framed append shape arriving bunched — skips
        # BOTH the native batch classifier walk and the per-record
        # protobuf parse; the payload views feed the staging path
        # directly (those two walks were ~40% of task-thread time at
        # 12x4MB groups)
        views: list | None = []
        for p in payloads:
            v = rec.peek_columnar_payload(p)
            if v is None:
                views = None
                break
            views.append(v)
        if views:
            for v in views:
                self._run_columnar(v, logid)
            return
        decoded = None
        if len(payloads) > 1:
            with trace_span(self.tracer, "decode"):
                decoded = jsondec.decode_batch(
                    payloads, np.asarray(dts, np.int64))
        if decoded is None:
            self._ingest_group_py(logid, payloads, dts)
            return
        ts, cls, cols, nulls = decoded
        n = len(cls)
        self._note_decode("json_decode_native", logid,
                          int(np.sum(cls == jsondec.CLS_JSON)))
        i = 0
        while i < n:
            c = int(cls[i])
            j = i + 1
            while j < n and cls[j] == c:
                j += 1
            if c == jsondec.CLS_JSON:
                if i == 0 and j == n:
                    self._run_json_cols(ts, cols, nulls, logid)
                else:
                    self._run_json_cols(
                        ts[i:j],
                        {k: (kind, arr[i:j], d)
                         for k, (kind, arr, d) in cols.items()},
                        {k: m[i:j] for k, m in nulls.items()}, logid)
            elif c == jsondec.CLS_RAW:
                for k in range(i, j):
                    v = rec.peek_columnar_payload(payloads[k])
                    if v is not None:
                        self._run_columnar(v, logid)
                        continue
                    r = rec.parse_record(payloads[k])
                    if columnar.is_columnar(r.payload):
                        self._run_columnar(r.payload, logid)
                    # other RAW records skipped, like the reference's
                    # JSON-flag filter (HStore.hs:119-143)
            else:  # CLS_PY: nested values / type conflicts / bad bytes
                self._ingest_group_py(logid, payloads[i:j], dts[i:j])
            i = j

    def _ingest_group_py(self, logid: int, payloads: list[bytes],
                         dts: list[int]) -> None:
        """Per-record Python decode (single records, native-decoder
        fallback classes, toolchain-free deployments)."""
        rows: list[dict[str, Any]] = []
        ts: list[int] = []

        def flush_rows() -> None:
            nonlocal rows, ts
            if rows:
                self._run_rows(rows, ts, logid)
                rows, ts = [], []

        with trace_span(self.tracer, "decode"):
            items: list[tuple[str, Any, int]] = []
            for payload, default_ts in zip(payloads, dts):
                v = rec.peek_columnar_payload(payload)
                if v is not None:
                    items.append(("col", v, 0))
                    continue
                r = rec.parse_record(payload)
                if (r.header.flag == rec.pb.RECORD_FLAG_RAW
                        and columnar.is_columnar(r.payload)):
                    items.append(("col", r.payload, 0))
                    continue
                d = rec.record_to_dict(r)
                if d is None:
                    continue  # raw records skipped (HStore.hs:119-143)
                items.append(
                    ("row", d, r.header.publish_time_ms or default_ts))
        self._note_decode("json_decode_fallback", logid,
                          sum(1 for k, _v, _t in items if k == "row"))
        for kind, val, t in items:
            if kind == "col":
                flush_rows()
                self._run_columnar(val, logid)
            else:
                rows.append(val)
                ts.append(t)
        flush_rows()

    def _run_json_cols(self, ts: "np.ndarray", cols: dict, nulls: dict,
                       logid: int) -> None:
        """Dispatch natively-decoded JSON columns (f64/str/bool arrays +
        null masks) through the staged columnar path; joins/sessions/
        stateless materialize rows."""
        if len(ts) == 0:
            return
        with self.state_lock:
            if self.executor is None:
                self.executor = self._make_executor(
                    _sample_rows(ts, cols, nulls), len(ts))
            ex = self.executor
            if not self.is_join and getattr(
                    ex, "supports_columnar_sessions", False):
                # session executors take the batch COLUMNAR too (device
                # session lattice): no row dicts, vectorized key encode
                out = self._run_session_cols(ex, ts, cols, nulls)
                if out:
                    with trace_span(self.tracer, "emit"):
                        self.sink(out)
                return
            if self.is_join or not hasattr(ex, "process_columnar"):
                if self.is_join and getattr(ex, "supports_columnar_join",
                                            False):
                    # stream-stream joins take the batch COLUMNAR: the
                    # join packs device entries straight from the
                    # arrays (null-masked cells = absent fields, the
                    # drop_null row shape) — no row dicts on this path
                    out = self._run_join_cols(
                        ex, ts, _plain_columns(cols), nulls, logid)
                else:
                    with trace_span(self.tracer, "decode"):
                        # drop_null: a record never mentions columns it
                        # doesn't carry — same row shape as the
                        # per-record decode path, independent of
                        # producer batching
                        rws = columnar.to_rows(ts, cols, nulls,
                                               drop_null=True)
                    with trace_span(self.tracer, "step"):
                        if self.is_join:
                            out = ex.process(
                                rws, ts.tolist(),
                                stream=self._sources[logid])
                        else:
                            out = ex.process(rws, ts.tolist())
                if out:
                    with trace_span(self.tracer, "emit"):
                        self.sink(out)
                return
            with trace_span(self.tracer, "key_encode"):
                key_ids = _columnar_key_ids(ex, cols, len(ts),
                                            nulls=nulls)
                dev_cols, dnulls = _device_columns(ex, cols, len(ts),
                                                   nulls=nulls)
            self._submit(ex, key_ids, ts, dev_cols, dnulls)

    def _query_mesh(self):
        """The server mesh, when this plan can execute sharded. The
        exclusions are LOUD (SURVEY §2.3 / VERDICT r4 weak #6): a plan
        that falls back to single-chip logs why, and EXPLAIN carries
        the same note (codegen.explain_text)."""
        from hstream_tpu.sql.codegen import mesh_exclusion_reason

        mesh = getattr(self.ctx, "mesh", None)
        if mesh is None:
            return None
        reason = mesh_exclusion_reason(self.plan)
        if reason is not None:
            log.warning(
                "query %s runs single-chip despite --mesh: %s",
                self.info.query_id, reason)
            return None
        return mesh

    def _make_executor(self, sample_rows: list, first_n: int):
        from hstream_tpu.engine.types import round_up_pow2
        from hstream_tpu.sql.codegen import make_executor

        # size the device batch to the producer's batch shape: a columnar
        # producer sending 256k-row batches must not be split into 64
        # separate device round-trips by the default 4096 capacity
        cap = min(max(round_up_pow2(first_n, lo=4096), 4096), 1 << 19)
        ex = make_executor(self.plan, sample_rows=sample_rows,
                           batch_capacity=cap, mesh=self._query_mesh())
        return self._tune_executor(ex)

    def _tune_executor(self, ex):
        """Per-task executor tuning, applied on BOTH the fresh and the
        snapshot-restore paths."""
        # per-kernel-family dispatch histograms (ISSUE 13): the engine
        # times its kernel dispatches into this task's observer (a
        # join's lazily-created inner aggregate is wired by the
        # per-chunk mirror when it appears)
        for target in (ex, getattr(ex, "_inner", None)):
            if target is not None and hasattr(target,
                                              "dispatch_observer"):
                target.dispatch_observer = self._observe_kernel
        if getattr(ex, "emit_changes", False) and \
                getattr(ex, "supports_deferred_changes", False):
            # pipeline changelog fetches behind later batches' work and
            # fetch them in BATCHED device->host transfers: on a real
            # link each fetch is a full round trip, which otherwise
            # bounds sustained ingest at (batch size / RTT). The idle
            # tick flushes everything pending, so emitted rows lag at
            # most one poll cycle once ingest pauses — under sustained
            # load they lag up to change_drain_depth micro-batches.
            # async_change_drain moves the batched fetch itself onto
            # the shared drain pool, so even the amortized round trip
            # stops serializing the compute loop. Join executors proxy
            # these knobs onto their downstream aggregate.
            ex.defer_change_decode = True
            ex.change_drain_depth = 8
            ex.async_change_drain = True
        return ex

    def _run_rows(self, rows: list, ts: list, logid: int | None) -> None:
        with self.state_lock:
            if self.executor is None:
                self.executor = self._make_executor(rows, len(rows))
            ex = self.executor
            if not self.is_join and hasattr(ex, "process_columnar") \
                    and not getattr(ex, "supports_columnar_sessions",
                                    False):
                # vectorized JSON ingest: one pass per needed column into
                # the same staged columnar path producer batches use
                # (SURVEY §7 "protobuf decode off the critical path")
                with trace_span(self.tracer, "key_encode"):
                    key_ids, cols, nulls = _columnarize_rows(ex, rows)
                self._submit(ex, key_ids, np.asarray(ts, np.int64),
                             cols, nulls)
                return
            with trace_span(self.tracer, "step"):
                if self.is_join:
                    out = ex.process(rows, ts,
                                     stream=self._sources[logid])
                    self._note_join_stats(ex, logid)
                else:
                    out = ex.process(rows, ts)
            # sink under the lock: a window removed from live state must
            # appear in the sink (view closed rows) atomically with the
            # removal, or a concurrent pull-query snapshot sees it in
            # neither half (no lock-order cycle: views.snapshot releases
            # the materialization lock before taking state_lock)
            if out:
                with trace_span(self.tracer, "emit"):
                    self.sink(out)

    # ---- columnar fast path ------------------------------------------------

    def _run_columnar(self, payload: bytes, logid: int) -> None:
        try:
            with trace_span(self.tracer, "decode"):
                # null masks (the framed append path's wire extension)
                # ride through like the native JSON decoder's: a masked
                # cell is a field the producer never sent
                ts, cols, nulls = columnar.decode_columnar_nulls(payload)
            if len(ts) == 0:
                return
        except Exception:  # noqa: BLE001 — a malformed/forged payload
            # must not kill the query task; skip it like any other
            # unrecognized RAW record
            log.warning("skipping malformed columnar record on logid %d",
                        logid)
            return
        with self.state_lock:
            if self.executor is None:
                self.executor = self._make_executor(
                    _sample_rows(ts, cols, nulls), len(ts))
            ex = self.executor
            if not self.is_join and getattr(
                    ex, "supports_columnar_sessions", False):
                out = self._run_session_cols(ex, ts, cols, nulls)
                if out:
                    with trace_span(self.tracer, "emit"):
                        self.sink(out)
                return
            if self.is_join or not hasattr(ex, "process_columnar"):
                if self.is_join and getattr(ex, "supports_columnar_join",
                                            False):
                    out = self._run_join_cols(
                        ex, ts, _plain_columns(cols), nulls, logid)
                else:
                    # stateless: row materialization
                    with trace_span(self.tracer, "decode"):
                        rws = columnar.to_rows(ts, cols, nulls,
                                               drop_null=True)
                    with trace_span(self.tracer, "step"):
                        if self.is_join:
                            out = ex.process(
                                rws, ts.tolist(),
                                stream=self._sources[logid])
                        else:
                            out = ex.process(rws, ts.tolist())
                if out:
                    with trace_span(self.tracer, "emit"):
                        self.sink(out)
                return
            with trace_span(self.tracer, "key_encode"):
                key_ids = _columnar_key_ids(ex, cols, len(ts),
                                            nulls=nulls)
                dev_cols, dnulls = _device_columns(ex, cols, len(ts),
                                                   nulls=nulls)
            self._submit(ex, key_ids, ts, dev_cols, dnulls)

    def _submit(self, ex, key_ids, ts, cols, nulls) -> None:
        """Submit one columnarized micro-batch through the ingest
        pipeline (caller holds state_lock). Rows returned belong to
        EARLIER batches whose encode already finished — emission lags
        submission by at most the pipeline depth; _drain_pipe() (idle
        tick / snapshot barrier) flushes the tail."""
        if self._pipe is None:
            self._pipe = IngestPipeline(ex, depth=self.pipeline_depth,
                                        workers=self.encode_workers)
        with trace_span(self.tracer, "step"):
            out = self._pipe.submit(key_ids, ts, cols, nulls)
        if out:
            with trace_span(self.tracer, "emit"):
                self.sink(out)

    def _run_session_cols(self, ex, ts, cols, nulls):
        """Columnar dispatch into a session executor (device session
        lattice, engine.session): string columns pre-gathered through
        their payload dictionaries into fixed-width unicode arrays, so
        the session key encoder factorizes them at C speed."""
        with trace_span(self.tracer, "step"):
            return ex.process_columnar(ts, _session_columns(cols), nulls)

    def _run_join_cols(self, ex, ts, plain, nulls, logid):
        """Columnar dispatch into a stream-stream join executor."""
        with trace_span(self.tracer, "step"):
            out = ex.process_columnar(
                ts, plain, nulls, stream=self._sources[logid])
        self._note_join_stats(ex, logid)
        return out

    def _note_join_stats(self, ex, logid: int) -> None:
        """Mirror the join executor's probe-dispatch counter into the
        per-stream metrics registry (delta since the last call)."""
        js = getattr(ex, "join_stats", None)
        if js is None:
            return
        cur = js.get("probe_dispatches", 0)
        delta = cur - self._join_probe_seen
        if delta > 0:
            self._join_probe_seen = cur
            self._note_decode("join_probe_dispatches", logid, delta)

    def _drain_pipe(self) -> None:
        """Pipeline barrier: every submitted batch processed, rows sunk."""
        with self.state_lock:  # _pipe is guarded (hstream-analyze)
            pipe = self._pipe
        if pipe is None or pipe.pending == 0:
            return
        with self.state_lock:
            rows = pipe.flush()
            if rows:
                with trace_span(self.tracer, "emit"):
                    self.sink(rows)


def _max_win_end(rows) -> float | None:
    """Max winEnd of an emitted batch, without materializing a
    ColumnarEmit's row view (read its columns directly); dict-row
    lists scan at most 1024 rows (row-shaped emissions are small)."""
    cols = getattr(rows, "cols", None)
    if cols is not None:
        we = cols.get("winEnd")
        if we is None or len(we) == 0:
            return None
        try:
            return float(np.max(we))
        except (TypeError, ValueError):
            return None
    best = None
    if isinstance(rows, list):
        for row in rows[:1024]:
            we = row.get("winEnd") if isinstance(row, dict) else None
            if we is not None and (best is None or we > best):
                best = we
    return None if best is None else float(best)


def _session_columns(cols: dict) -> dict:
    """Decoded payload columns -> the session executor's columnar feed:
    like _plain_columns, but string columns gather into fixed-width
    unicode arrays (one vectorized fancy-index) instead of object
    arrays — the session key encoder's np.unique factorization runs at
    C speed on those and would fall back to a per-row memo loop on
    object dtype."""
    out = {}
    for name, (kind, arr, d) in cols.items():
        if kind == "str":
            out[name] = np.asarray(d)[arr] if d else \
                np.zeros(len(arr), "U1")
        else:
            out[name] = arr
    return out


def _plain_columns(cols: dict) -> dict:
    """Decoded payload columns (kind, arr, dict) -> plain numpy arrays
    for the join's columnar ingest: string columns gather through their
    payload dictionary (one vectorized fancy-index, no per-row Python)."""
    out = {}
    for name, (kind, arr, d) in cols.items():
        if kind == "str":
            out[name] = np.asarray(d, object)[arr]
        else:
            out[name] = arr
    return out


def _sample_rows(ts: "np.ndarray", cols: dict,
                 nulls: dict | None = None, k: int = 8) -> list[dict]:
    n = min(int(len(ts)), k)
    return columnar.to_rows(
        ts[:n], {name: (kind, arr[:n], d)
                 for name, (kind, arr, d) in cols.items()},
        None if nulls is None else {name: m[:n]
                                    for name, m in nulls.items()},
        drop_null=True)


def _columnarize_rows(ex, rows: list) -> tuple:
    """Decoded JSON rows -> (key_ids, cols, nulls) for the staged
    columnar path: one pass per needed column instead of the per-row
    HostBatch scan. Semantics match HostBatch.from_rows: STRING columns
    stringify non-None values; numeric columns NULL anything that is not
    int/float/bool."""
    from hstream_tpu.engine.types import ColumnType

    n = len(rows)
    if ex.group_cols:
        gc = ex.group_cols
        if len(gc) == 1:
            c0 = gc[0]
            key_ids = np.fromiter(
                (ex.key_id_for((r.get(c0),)) for r in rows), np.int32, n)
        else:
            key_ids = np.fromiter(
                (ex.key_id_for(tuple(r.get(c) for c in gc))
                 for r in rows), np.int32, n)
    else:
        key_ids = np.zeros(n, np.int32)
    cols: dict[str, np.ndarray] = {}
    nulls: dict[str, np.ndarray] = {}
    for name in ex._needed_cols:
        want = ex.schema.type_of(name)
        msk = np.zeros(n, np.bool_)
        if want == ColumnType.STRING:
            enc = ex.dicts[name].encode
            arr = np.empty(n, np.int32)
            for i, r in enumerate(rows):
                v = r.get(name)
                if v is None:
                    arr[i] = -1
                    msk[i] = True
                else:
                    arr[i] = enc(str(v))
        else:
            dt = (np.bool_ if want == ColumnType.BOOL
                  else np.int32 if want == ColumnType.INT else np.float32)
            arr = np.zeros(n, dt)
            for i, r in enumerate(rows):
                v = r.get(name)
                if v is None or not isinstance(v, (int, float, bool)):
                    msk[i] = True
                else:
                    arr[i] = v
        cols[name] = arr
        if msk.any():
            nulls[name] = msk
    return key_ids, cols, (nulls or None)


def _columnar_key_ids(ex, cols: dict, n: int,
                      nulls: dict | None = None) -> "np.ndarray":
    """Vectorized group-key encoding: per-column unique+inverse, then
    one key_id_for call per DISTINCT combination (not per row). `nulls`
    marks cells whose group value is None (native JSON decode)."""
    if not ex.group_cols:
        return np.zeros(n, np.int32)
    col_vals: list[list] = []
    col_codes: list[np.ndarray] = []
    for c in ex.group_cols:
        ent = cols.get(c)
        if ent is None:
            col_vals.append([None])
            col_codes.append(np.zeros(n, np.int64))
            continue
        kind, arr, d = ent
        if kind == "str" and len(d) <= n:
            # the payload's dictionary codes ARE dense per-batch value
            # ids (encode_columnar dictionary-encodes with np.unique):
            # use them directly — no O(n log n) unique pass per batch.
            # A forged dict LARGER than the batch row count falls
            # through to the unique path so key registration stays
            # bounded by rows actually present.
            vals: list = list(d)
            codes = arr.astype(np.int64)
        elif kind == "str":
            uniq, inv = np.unique(arr, return_inverse=True)
            vals = [d[int(u)] for u in uniq]
            codes = inv.astype(np.int64)
        elif kind == "bool":
            vals = [False, True]
            codes = arr.astype(np.int64)
        else:
            uniq, inv = np.unique(arr, return_inverse=True)
            if kind == "f64":
                # integral doubles decode as ints, like the Struct
                # number decoding JSON rows go through (records.py)
                vals = [int(u) if float(u).is_integer() else float(u)
                        for u in uniq]
            elif kind == "f32":
                vals = [float(u) for u in uniq]
            else:
                vals = [int(u) for u in uniq]
            codes = inv.astype(np.int64)
        nm = nulls.get(c) if nulls else None
        if nm is not None and nm.any():
            vals = [None] + vals
            codes = np.where(nm, 0, codes + 1)
        col_vals.append(vals)
        col_codes.append(codes)
    if len(col_vals) == 1:
        # single group column: map each distinct value to its key id
        # once, then one LUT gather over the batch. Register ONLY codes
        # that occur in the batch: vals can carry values absent from
        # every (unmasked) row — bool's fixed [False, True] domain, or
        # unique() placeholders from null-masked cells — and a phantom
        # key id would ride every snapshot and could force a needless
        # key-capacity grow.
        vals = col_vals[0]
        codes = col_codes[0]
        # raw-value -> key id memo: at SURVEY-scale cardinality (100K+
        # live keys) the per-distinct key_id_for canon+tuple work is
        # ~100ms per batch; a dict hit is ~10x cheaper. kids never
        # change once assigned, so the memo cannot go stale; it is
        # bounded like the session key caches.
        memo = getattr(ex, "_kid_vmemo", None)
        if memo is None:
            memo = ex._kid_vmemo = {}
        elif len(memo) > (1 << 20):
            memo.clear()
        kid_lut = np.zeros(len(vals), np.int32)
        for p in np.unique(codes).tolist():
            v = vals[p]
            kid = memo.get(v)
            if kid is None:
                kid = ex.key_id_for((v,))
                memo[v] = kid
            kid_lut[p] = kid
        return kid_lut[codes]
    radix = 1
    for vals in col_vals:
        radix *= max(len(vals), 1)
    if radix >= (1 << 62):
        # mixed-radix code would overflow int64 and silently collide
        # distinct groups: fall back to per-row tuples (rare — several
        # high-cardinality group columns in one batch)
        arrs = [np.asarray(vals, object)[codes]
                for vals, codes in zip(col_vals, col_codes)]
        return np.fromiter((ex.key_id_for(t) for t in zip(*arrs)),
                           np.int32, n)
    combined = col_codes[0]
    for codes, vals in zip(col_codes[1:], col_vals[1:]):
        combined = combined * len(vals) + codes
    u, inv = np.unique(combined, return_inverse=True)
    kid_for_u = np.empty(len(u), np.int32)
    for j, cu in enumerate(u.tolist()):
        idxs = []
        for vals in reversed(col_vals[1:]):
            idxs.append(cu % len(vals))
            cu //= len(vals)
        idxs.append(cu)
        idxs.reverse()
        key = tuple(col_vals[k][i] for k, i in enumerate(idxs))
        kid_for_u[j] = ex.key_id_for(key)
    return kid_for_u[inv]


def _device_columns(ex, cols: dict, n: int, nulls: dict | None = None):
    """Map batch columns to the executor's needed device columns;
    missing columns become all-NULL; per-cell null masks (native JSON
    decode) ride through."""
    from hstream_tpu.engine.types import ColumnType

    dev: dict[str, Any] = {}
    out_nulls: dict[str, Any] = {}
    for name in ex._needed_cols:
        ent = cols.get(name)
        want = ex.schema.type_of(name)
        # type mismatch between the batch column and the bound schema
        # (e.g. a later producer sends strings where FLOAT was inferred)
        # becomes NULL, never dictionary ids masquerading as data
        kind = ent[0] if ent is not None else None
        mismatch = (kind == "str") != (want == ColumnType.STRING)
        if ent is None or mismatch:
            dev[name] = np.zeros(
                n, np.int32 if want == ColumnType.STRING else np.float32)
            out_nulls[name] = np.ones(n, np.bool_)
            continue
        kind, arr, d = ent
        if want == ColumnType.STRING:
            lut = np.asarray([ex.dicts[name].encode(s) for s in d],
                             np.int32)
            dev[name] = lut[arr]
        elif want == ColumnType.BOOL:
            dev[name] = np.asarray(arr, np.bool_)
        elif want == ColumnType.INT:
            dev[name] = np.asarray(arr, np.int32)
        else:
            dev[name] = np.asarray(arr, np.float32)
        nm = nulls.get(name) if nulls else None
        if nm is not None and nm.any():
            out_nulls[name] = nm
    return dev, (out_nulls or None)


def stream_sink(ctx, sink_stream: str,
                stream_type: StreamType = StreamType.STREAM) -> SinkFn:
    """Sink emitting rows as JSON records onto a stream (the reference's
    internal sink processor, HStore.hs:152-163).

    On the native store the appends go through the async completion
    queue (the reference's async writer, hs_writer.cpp:29-51): the query
    loop overlaps durable sink writes with the next batch's processing,
    bounded in flight. `sink.flush()` is the durability barrier — the
    task calls it before committing a state snapshot, so a checkpoint
    never outruns its emitted rows."""
    logid = ctx.streams.get_logid(sink_stream, stream_type)
    use_async = hasattr(ctx.store, "append_async")
    pending: list = []

    stats = getattr(ctx, "stats", None)

    def sink(rows: list[dict[str, Any]]) -> None:
        if stats is not None and isinstance(rows, columnar.ColumnarEmit):
            try:
                stats.stream_stat_add("change_rows_columnar",
                                      sink_stream, len(rows))
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # the emit path
        payloads = None
        if isinstance(rows, columnar.ColumnarEmit) or len(rows) >= 32:
            # steady-state batches of homogeneous flat rows go out as
            # ONE columnar record — per-row protobuf Struct building is
            # the emit stage's entire cost at changelog rates. A
            # ColumnarEmit close batch encodes straight from its
            # columns, so the emitted rows never materialize as dicts
            # on this path at ANY batch size.
            packed = columnar.rows_to_payload(rows, rec.now_ms())
            if packed is not None:
                payloads = [rec.build_record(packed).SerializeToString()]
        if payloads is None:
            payloads = [rec.build_record(row).SerializeToString()
                        for row in rows]
        if use_async:
            while len(pending) >= 8:  # bound in-flight appends
                pending.pop(0).result()
            pending.append(ctx.store.append_async(logid, payloads))
        else:
            ctx.store.append_batch(logid, payloads)

    def flush() -> None:
        while pending:
            pending.pop(0).result()

    sink.flush = flush
    return sink
