"""Managed continuous-query tasks.

The reference runs each continuous query as a forked green thread: a
checkpointed reader polls the source stream(s), every record walks the
processor DAG, and sink processors append results downstream
(runTaskWrapper, Handler/Common.hs:169-180; runTask, Processor.hs:99-144).

Here a task is one daemon thread per query driving the batched engine:
read a chunk from the checkpointed reader -> decode JSON records ->
executor.process (the jitted lattice step) -> emit rows to the sink
callback -> checkpoint.

Checkpointing improves on the reference (which checkpoints readers only
— operator state is in-memory, so its restarts undercount every window
spanning them, Codegen.hs:374-385): read positions are committed ONLY
paired with an operator-state snapshot, in one atomic meta-KV write
(engine.snapshot). Resume restores the state and continues from the
paired LSNs — exact, modulo at-least-once re-emission of rows sunk
after the last snapshot.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Callable

import numpy as np

from hstream_tpu.common import columnar
from hstream_tpu.common import records as rec
from hstream_tpu.common.logger import get_logger
from hstream_tpu.common.tracing import QueryTracer, trace_span
from hstream_tpu.engine.snapshot import (
    capture_executor,
    restore_executor,
    serialize_capture,
)
from hstream_tpu.server.persistence import QueryInfo, TaskStatus
from hstream_tpu.store.api import LSN_MIN, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("tasks")

SinkFn = Callable[[list[dict[str, Any]]], None]

READ_CHUNK = 256
POLL_TIMEOUT_MS = 50


def snapshot_key(query_id: str) -> str:
    """Meta-KV key holding a query's operator-state snapshot."""
    return f"qsnap/{query_id}"


class QueryTask(threading.Thread):
    """One continuous query: source stream(s) -> executor -> sink rows."""

    # state snapshot + checkpoint cadence; tests lower it
    snapshot_interval_ms: int = 1000

    def __init__(self, ctx, info: QueryInfo, plan, sink: SinkFn, *,
                 from_beginning: bool = True):
        super().__init__(name=f"query-{info.query_id}", daemon=True)
        self.ctx = ctx
        self.info = info
        self.plan = plan
        self.sink = sink
        self.from_beginning = from_beginning
        # per-context override wins over the class default (main.serve)
        ctx_iv = getattr(ctx, "snapshot_interval_ms", None)
        if ctx_iv is not None:
            self.snapshot_interval_ms = ctx_iv
        self.executor = None
        self.error: BaseException | None = None
        # serializes executor state mutation (this thread) against pull
        # queries peeking live state from gRPC threads (views.snapshot)
        self.state_lock = threading.RLock()
        # optional sink-side state riding in the snapshot (a view's
        # closed-row materialization survives restarts this way)
        self.sink_dump: Callable[[], Any] | None = None
        self.sink_load: Callable[[Any], None] | None = None
        self._stop_ev = threading.Event()
        # readiness: set once the reader is attached to every source at
        # its start LSN — tests and callers wait on this instead of
        # sleeping (the notification mechanism the reference's test tier
        # lacks: "FIXME: requires a notification mechanism",
        # RunSQLSpec.hs:54)
        self.attached = threading.Event()
        self.attached_lsns: dict[int, int] = {}  # logid -> start LSN
        self._sources: dict[int, str] = {}  # logid -> stream name
        for name in self.source_streams():
            self._sources[ctx.streams.get_logid(name)] = name
        self._reader: CheckpointedReader | None = None
        # always-on per-stage timing rings (SURVEY §5.1)
        self.tracer = QueryTracer()
        self._pending_ckps: dict[int, int] = {}  # processed, not committed
        self._last_snapshot_ms = 0.0
        self._dirty = False
        self._crash = False
        self._detach = False

    def source_streams(self) -> list[str]:
        names = [self.plan.source]
        if self.plan.join is not None:
            names.append(self.plan.join.right.name)
        return names

    @property
    def is_join(self) -> bool:
        return self.plan.join is not None

    # ---- lifecycle ---------------------------------------------------------

    def stop(self, timeout: float = 10.0, *, crash: bool = False,
             detach: bool = False) -> None:
        """Stop modes:
        default — user-initiated terminate: final snapshot + TERMINATED.
        detach=True — server shutdown: final snapshot but status stays
        RUNNING so boot-time resume_persisted relaunches the query.
        crash=True — fault injection (tests): no snapshot, no status
        update, like a killed process; resume replays from the last
        periodic snapshot."""
        if crash:
            self._crash = True
        if detach:
            self._detach = True
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)

    def run(self) -> None:
        ctx = self.ctx
        try:
            reader = CheckpointedReader(
                f"query-{self.info.query_id}",
                ctx.store.new_reader(max_logs=len(self._sources)),
                ctx.ckp_store)
            self._reader = reader
            reader.set_timeout(POLL_TIMEOUT_MS)
            resumed = self._restore_state()
            for logid in self._sources:
                if resumed is not None and logid in resumed:
                    start = resumed[logid] + 1
                    reader.start_reading(logid, start)
                else:
                    start = reader.start_reading_from_checkpoint(
                        logid, LSN_MIN)
                self.attached_lsns[logid] = start
            ctx.persistence.set_query_status(self.info.query_id,
                                             TaskStatus.RUNNING)
            self.attached.set()
            while not self._stop_ev.is_set():
                results = reader.read(READ_CHUNK)
                if not results:
                    self._flush_deferred_changes()
                    self._maybe_snapshot()
                    continue
                for r in results:
                    if isinstance(r, DataBatch):
                        self._process_batch(r)
                    lsn = (r.lsn if isinstance(r, DataBatch) else r.hi_lsn)
                    if lsn > self._pending_ckps.get(r.logid, 0):
                        self._pending_ckps[r.logid] = lsn
                        self._dirty = True
                self._maybe_snapshot()
            if not self._crash:
                self._snapshot_now()  # graceful stop: state is durable
                if not self._detach:
                    ctx.persistence.set_query_status(
                        self.info.query_id, TaskStatus.TERMINATED)
            # detach (server shutdown) and crash both leave status
            # RUNNING so boot-time resume_persisted relaunches the query
        except BaseException as e:  # noqa: BLE001 — status must reflect death
            self.error = e
            log.error("query %s died: %s\n%s", self.info.query_id, e,
                      traceback.format_exc())
            try:
                ctx.persistence.set_query_status(self.info.query_id,
                                                 TaskStatus.CONNECTION_ABORT)
            except Exception:
                pass
        finally:
            ctx.running_queries.pop(self.info.query_id, None)

    # ---- operator-state checkpointing --------------------------------------

    def _restore_state(self) -> dict[int, int] | None:
        """Restore executor + sink state from the last snapshot. Returns
        the read positions the state corresponds to (logid -> committed
        LSN), or None when starting fresh."""
        blob = self.ctx.store.meta_get(snapshot_key(self.info.query_id))
        if blob is None:
            return None
        with self.state_lock:
            ex, extra = restore_executor(
                self.plan, blob, mesh=self._query_mesh())
            self.executor = self._tune_executor(ex)
            if self.sink_load is not None and "sink" in extra:
                self.sink_load(extra["sink"])
        ckps = {int(k): int(v) for k, v in extra.get("ckps", {}).items()}
        self._pending_ckps = dict(ckps)
        self._last_snapshot_ms = time.monotonic() * 1000
        log.info("query %s resumed from snapshot at %s",
                 self.info.query_id, ckps)
        return ckps

    def _flush_deferred_changes(self) -> None:
        """Drain deferred changelog extracts to the sink (idle ticks and
        pre-snapshot — the snapshot guard requires an empty queue)."""
        ex = self.executor
        if ex is None or not getattr(ex, "_pending_changes", None):
            return
        with self.state_lock:
            rows = ex.flush_changes()
            if rows:
                with trace_span(self.tracer, "emit"):
                    self.sink(rows)

    def _maybe_snapshot(self) -> None:
        if not self._dirty:
            return
        now = time.monotonic() * 1000
        if now - self._last_snapshot_ms >= self.snapshot_interval_ms:
            self._snapshot_now()

    def _snapshot_now(self) -> None:
        self._flush_deferred_changes()
        with trace_span(self.tracer, "snapshot"):
            self._snapshot_now_inner()

    def _snapshot_now_inner(self) -> None:
        """Atomically persist (operator state, read checkpoints): one
        meta-KV write. Read positions NEVER advance past durable state —
        the reference's failure mode (commit-then-lose-state undercount)
        cannot happen. The ckp store mirrors the LSNs for observability."""
        if not self._dirty:
            return
        extra: dict[str, Any] = {
            "ckps": {str(k): v for k, v in self._pending_ckps.items()}}
        if self.executor is None:
            # nothing aggregated yet (e.g. raw records only): committing
            # the read position loses no state
            if self._reader is not None and self._pending_ckps:
                self._reader.write_checkpoints(self._pending_ckps)
            self._last_snapshot_ms = time.monotonic() * 1000
            self._dirty = False
            return
        # capture under the lock (cheap, consistent), serialize outside
        # (device sync + npz pack must not stall ingest or pull queries)
        with self.state_lock:
            if self.sink_dump is not None:
                extra["sink"] = self.sink_dump()
            meta, arrays = capture_executor(self.executor, extra)
        blob = serialize_capture(meta, arrays)
        # durability barrier: async sink appends must land before the
        # checkpoint advances, or a crash could lose emitted rows that
        # the restored state will never regenerate
        flush = getattr(self.sink, "flush", None)
        if flush is not None:
            flush()
        self.ctx.store.meta_put(snapshot_key(self.info.query_id), blob)
        if self._reader is not None and self._pending_ckps:
            self._reader.write_checkpoints(self._pending_ckps)
        self._last_snapshot_ms = time.monotonic() * 1000
        self._dirty = False

    # ---- processing --------------------------------------------------------

    def _process_batch(self, batch: DataBatch) -> None:
        # phase 1 (timed as "decode"): parse + classify + JSON decode;
        # phase 2 runs the engine OUTSIDE the decode span so nested
        # key_encode/step/emit spans are not double-counted
        items: list[tuple[str, Any, int]] = []
        with trace_span(self.tracer, "decode"):
            for payload in batch.payloads:
                r = rec.parse_record(payload)
                if (r.header.flag == rec.pb.RECORD_FLAG_RAW
                        and columnar.is_columnar(r.payload)):
                    items.append(("col", r.payload, 0))
                    continue
                d = rec.record_to_dict(r)
                if d is None:
                    continue  # raw records skipped, like the reference's
                    # JSON-flag filter (HStore.hs:119-143)
                items.append(
                    ("row", d,
                     r.header.publish_time_ms or batch.append_time_ms))

        rows: list[dict[str, Any]] = []
        ts: list[int] = []

        def flush_rows() -> None:
            if rows:
                self._run_rows(rows.copy(), ts.copy(), batch)
                rows.clear()
                ts.clear()

        for kind, val, t in items:
            if kind == "col":
                # columnar batch payload: the high-throughput producer
                # path — flush accumulated JSON rows first (order)
                flush_rows()
                self._run_columnar(val, batch)
            else:
                rows.append(val)
                ts.append(t)
        flush_rows()

    def _query_mesh(self):
        """The server mesh, when this plan can execute sharded (joins
        stay single-chip; session plans ignore the mesh downstream)."""
        mesh = getattr(self.ctx, "mesh", None)
        if mesh is None or self.plan.join is not None:
            return None
        return mesh

    def _make_executor(self, sample_rows: list, first_n: int):
        from hstream_tpu.engine.types import round_up_pow2
        from hstream_tpu.sql.codegen import make_executor

        # size the device batch to the producer's batch shape: a columnar
        # producer sending 256k-row batches must not be split into 64
        # separate device round-trips by the default 4096 capacity
        cap = min(max(round_up_pow2(first_n, lo=4096), 4096), 1 << 19)
        ex = make_executor(self.plan, sample_rows=sample_rows,
                           batch_capacity=cap, mesh=self._query_mesh())
        return self._tune_executor(ex)

    @staticmethod
    def _tune_executor(ex):
        """Per-task executor tuning, applied on BOTH the fresh and the
        snapshot-restore paths."""
        if getattr(ex, "emit_changes", False) and \
                getattr(ex, "supports_deferred_changes", False):
            # pipeline the changelog fetch behind the next batch's work;
            # the idle tick flushes so rows lag <= one poll cycle
            ex.defer_change_decode = True
        return ex

    def _run_rows(self, rows: list, ts: list, batch: DataBatch) -> None:
        with self.state_lock:
            if self.executor is None:
                self.executor = self._make_executor(rows, len(rows))
            with trace_span(self.tracer, "step"):
                if self.is_join:
                    out = self.executor.process(
                        rows, ts, stream=self._sources[batch.logid])
                else:
                    out = self.executor.process(rows, ts)
            # sink under the lock: a window removed from live state must
            # appear in the sink (view closed rows) atomically with the
            # removal, or a concurrent pull-query snapshot sees it in
            # neither half (no lock-order cycle: views.snapshot releases
            # the materialization lock before taking state_lock)
            if out:
                with trace_span(self.tracer, "emit"):
                    self.sink(out)

    # ---- columnar fast path ------------------------------------------------

    def _run_columnar(self, payload: bytes, batch: DataBatch) -> None:
        try:
            with trace_span(self.tracer, "decode"):
                ts, cols = columnar.decode_columnar(payload)
            if len(ts) == 0:
                return
        except Exception:  # noqa: BLE001 — a malformed/forged payload
            # must not kill the query task; skip it like any other
            # unrecognized RAW record
            log.warning("skipping malformed columnar record on logid %d",
                        batch.logid)
            return
        with self.state_lock:
            if self.executor is None:
                self.executor = self._make_executor(
                    _sample_rows(ts, cols), len(ts))
            ex = self.executor
            if self.is_join or not hasattr(ex, "process_columnar"):
                # joins / sessions / stateless: row materialization
                with trace_span(self.tracer, "decode"):
                    rws = _rows_from_columnar(ts, cols)
                with trace_span(self.tracer, "step"):
                    if self.is_join:
                        out = ex.process(
                            rws, ts.tolist(),
                            stream=self._sources[batch.logid])
                    else:
                        out = ex.process(rws, ts.tolist())
            else:
                with trace_span(self.tracer, "key_encode"):
                    key_ids = _columnar_key_ids(ex, cols, len(ts))
                    dev_cols, nulls = _device_columns(ex, cols, len(ts))
                with trace_span(self.tracer, "step"):
                    out = ex.process_columnar(key_ids, ts, dev_cols,
                                              nulls)
            if out:
                with trace_span(self.tracer, "emit"):
                    self.sink(out)


def _sample_rows(ts: "np.ndarray", cols: dict, k: int = 8) -> list[dict]:
    n = min(int(len(ts)), k)
    return _rows_from_columnar(
        ts[:n], {name: (kind, arr[:n], d)
                 for name, (kind, arr, d) in cols.items()})


def _rows_from_columnar(ts: "np.ndarray", cols: dict) -> list[dict]:
    host = {}
    for name, (kind, arr, d) in cols.items():
        if kind == "str":
            host[name] = [d[int(i)] for i in arr]
        else:
            host[name] = arr.tolist()
    names = list(host)
    return [dict(zip(names, vals))
            for vals in zip(*(host[c] for c in names))]


def _columnar_key_ids(ex, cols: dict, n: int) -> "np.ndarray":
    """Vectorized group-key encoding: per-column unique+inverse, then
    one key_id_for call per DISTINCT combination (not per row)."""
    if not ex.group_cols:
        return np.zeros(n, np.int32)
    col_vals: list[list] = []
    col_codes: list[np.ndarray] = []
    for c in ex.group_cols:
        ent = cols.get(c)
        if ent is None:
            col_vals.append([None])
            col_codes.append(np.zeros(n, np.int64))
            continue
        kind, arr, d = ent
        uniq, codes = np.unique(arr, return_inverse=True)
        if kind == "str":
            vals = [d[int(u)] for u in uniq]
        elif kind == "bool":
            vals = [bool(u) for u in uniq]
        elif kind == "f32":
            vals = [float(u) for u in uniq]
        else:
            vals = [int(u) for u in uniq]
        col_vals.append(vals)
        col_codes.append(codes.astype(np.int64))
    radix = 1
    for vals in col_vals:
        radix *= max(len(vals), 1)
    if radix >= (1 << 62):
        # mixed-radix code would overflow int64 and silently collide
        # distinct groups: fall back to per-row tuples (rare — several
        # high-cardinality group columns in one batch)
        arrs = [np.asarray(vals, object)[codes]
                for vals, codes in zip(col_vals, col_codes)]
        return np.fromiter((ex.key_id_for(t) for t in zip(*arrs)),
                           np.int32, n)
    combined = col_codes[0]
    for codes, vals in zip(col_codes[1:], col_vals[1:]):
        combined = combined * len(vals) + codes
    u, inv = np.unique(combined, return_inverse=True)
    kid_for_u = np.empty(len(u), np.int32)
    for j, cu in enumerate(u.tolist()):
        idxs = []
        for vals in reversed(col_vals[1:]):
            idxs.append(cu % len(vals))
            cu //= len(vals)
        idxs.append(cu)
        idxs.reverse()
        key = tuple(col_vals[k][i] for k, i in enumerate(idxs))
        kid_for_u[j] = ex.key_id_for(key)
    return kid_for_u[inv]


def _device_columns(ex, cols: dict, n: int):
    """Map batch columns to the executor's needed device columns;
    missing columns become all-NULL."""
    from hstream_tpu.engine.types import ColumnType

    dev: dict[str, Any] = {}
    nulls: dict[str, Any] = {}
    for name in ex._needed_cols:
        ent = cols.get(name)
        want = ex.schema.type_of(name)
        # type mismatch between the batch column and the bound schema
        # (e.g. a later producer sends strings where FLOAT was inferred)
        # becomes NULL, never dictionary ids masquerading as data
        kind = ent[0] if ent is not None else None
        mismatch = (kind == "str") != (want == ColumnType.STRING)
        if ent is None or mismatch:
            dev[name] = np.zeros(
                n, np.int32 if want == ColumnType.STRING else np.float32)
            nulls[name] = np.ones(n, np.bool_)
            continue
        kind, arr, d = ent
        if want == ColumnType.STRING:
            lut = np.asarray([ex.dicts[name].encode(s) for s in d],
                             np.int32)
            dev[name] = lut[arr]
        elif want == ColumnType.BOOL:
            dev[name] = np.asarray(arr, np.bool_)
        elif want == ColumnType.INT:
            dev[name] = np.asarray(arr, np.int32)
        else:
            dev[name] = np.asarray(arr, np.float32)
    return dev, (nulls or None)


def stream_sink(ctx, sink_stream: str,
                stream_type: StreamType = StreamType.STREAM) -> SinkFn:
    """Sink emitting rows as JSON records onto a stream (the reference's
    internal sink processor, HStore.hs:152-163).

    On the native store the appends go through the async completion
    queue (the reference's async writer, hs_writer.cpp:29-51): the query
    loop overlaps durable sink writes with the next batch's processing,
    bounded in flight. `sink.flush()` is the durability barrier — the
    task calls it before committing a state snapshot, so a checkpoint
    never outruns its emitted rows."""
    logid = ctx.streams.get_logid(sink_stream, stream_type)
    use_async = hasattr(ctx.store, "append_async")
    pending: list = []

    def sink(rows: list[dict[str, Any]]) -> None:
        payloads = [rec.build_record(row).SerializeToString()
                    for row in rows]
        if use_async:
            while len(pending) >= 8:  # bound in-flight appends
                pending.pop(0).result()
            pending.append(ctx.store.append_async(logid, payloads))
        else:
            ctx.store.append_batch(logid, payloads)

    def flush() -> None:
        while pending:
            pending.pop(0).result()

    sink.flush = flush
    return sink
