"""Managed continuous-query tasks.

The reference runs each continuous query as a forked green thread: a
checkpointed reader polls the source stream(s), every record walks the
processor DAG, and sink processors append results downstream
(runTaskWrapper, Handler/Common.hs:169-180; runTask, Processor.hs:99-144).

Here a task is one daemon thread per query driving the batched engine:
read a chunk from the checkpointed reader -> decode JSON records ->
executor.process (the jitted lattice step) -> emit rows to the sink
callback -> commit read checkpoints. Joins read both streams through the
same reader and route batches by origin stream.
"""

from __future__ import annotations

import threading
import traceback
from typing import Any, Callable

from hstream_tpu.common import records as rec
from hstream_tpu.common.logger import get_logger
from hstream_tpu.server.persistence import QueryInfo, TaskStatus
from hstream_tpu.store.api import LSN_MIN, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("tasks")

SinkFn = Callable[[list[dict[str, Any]]], None]

READ_CHUNK = 256
POLL_TIMEOUT_MS = 50


class QueryTask(threading.Thread):
    """One continuous query: source stream(s) -> executor -> sink rows."""

    def __init__(self, ctx, info: QueryInfo, plan, sink: SinkFn, *,
                 from_beginning: bool = True):
        super().__init__(name=f"query-{info.query_id}", daemon=True)
        self.ctx = ctx
        self.info = info
        self.plan = plan
        self.sink = sink
        self.from_beginning = from_beginning
        self.executor = None
        self.error: BaseException | None = None
        # serializes executor state mutation (this thread) against pull
        # queries peeking live state from gRPC threads (views.snapshot)
        self.state_lock = threading.RLock()
        self._stop_ev = threading.Event()
        self._sources: dict[int, str] = {}  # logid -> stream name
        for name in self.source_streams():
            self._sources[ctx.streams.get_logid(name)] = name
        self._reader: CheckpointedReader | None = None

    def source_streams(self) -> list[str]:
        names = [self.plan.source]
        if self.plan.join is not None:
            names.append(self.plan.join.right.name)
        return names

    @property
    def is_join(self) -> bool:
        return self.plan.join is not None

    # ---- lifecycle ---------------------------------------------------------

    def stop(self, timeout: float = 10.0) -> None:
        self._stop_ev.set()
        if self.is_alive():
            self.join(timeout)

    def run(self) -> None:
        ctx = self.ctx
        try:
            reader = CheckpointedReader(
                f"query-{self.info.query_id}",
                ctx.store.new_reader(max_logs=len(self._sources)),
                ctx.ckp_store)
            self._reader = reader
            reader.set_timeout(POLL_TIMEOUT_MS)
            for logid in self._sources:
                reader.start_reading_from_checkpoint(logid, LSN_MIN)
            ctx.persistence.set_query_status(self.info.query_id,
                                             TaskStatus.RUNNING)
            while not self._stop_ev.is_set():
                results = reader.read(READ_CHUNK)
                if not results:
                    continue
                ckps: dict[int, int] = {}
                for r in results:
                    if isinstance(r, DataBatch):
                        self._process_batch(r)
                    ckps[r.logid] = max(ckps.get(r.logid, 0),
                                        r.lsn if isinstance(r, DataBatch)
                                        else r.hi_lsn)
                reader.write_checkpoints(ckps)
            ctx.persistence.set_query_status(self.info.query_id,
                                             TaskStatus.TERMINATED)
        except BaseException as e:  # noqa: BLE001 — status must reflect death
            self.error = e
            log.error("query %s died: %s\n%s", self.info.query_id, e,
                      traceback.format_exc())
            try:
                ctx.persistence.set_query_status(self.info.query_id,
                                                 TaskStatus.CONNECTION_ABORT)
            except Exception:
                pass
        finally:
            ctx.running_queries.pop(self.info.query_id, None)

    # ---- processing --------------------------------------------------------

    def _process_batch(self, batch: DataBatch) -> None:
        rows: list[dict[str, Any]] = []
        ts: list[int] = []
        for payload in batch.payloads:
            r = rec.parse_record(payload)
            d = rec.record_to_dict(r)
            if d is None:
                continue  # raw records skipped, like the reference's
                # JSON-flag filter (HStore.hs:119-143)
            rows.append(d)
            ts.append(r.header.publish_time_ms or batch.append_time_ms)
        if not rows:
            return
        with self.state_lock:
            if self.executor is None:
                from hstream_tpu.sql.codegen import make_executor

                self.executor = make_executor(self.plan, sample_rows=rows)
            if self.is_join:
                out = self.executor.process(
                    rows, ts, stream=self._sources[batch.logid])
            else:
                out = self.executor.process(rows, ts)
            # sink under the lock: a window removed from live state must
            # appear in the sink (view closed rows) atomically with the
            # removal, or a concurrent pull-query snapshot sees it in
            # neither half (no lock-order cycle: views.snapshot releases
            # the materialization lock before taking state_lock)
            if out:
                self.sink(out)


def stream_sink(ctx, sink_stream: str,
                stream_type: StreamType = StreamType.STREAM) -> SinkFn:
    """Sink emitting rows as JSON records onto a stream (the reference's
    internal sink processor, HStore.hs:152-163)."""
    logid = ctx.streams.get_logid(sink_stream, stream_type)

    def sink(rows: list[dict[str, Any]]) -> None:
        payloads = [rec.build_record(row).SerializeToString()
                    for row in rows]
        ctx.store.append_batch(logid, payloads)

    return sink
