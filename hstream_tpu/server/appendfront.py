"""Sharded append front: the handler-side half of wire-speed ingest.

The plain Append handler appends synchronously on its gRPC thread —
one fsync-bound store call per RPC, which is why `store_append` benches
at ~93k rec/s while the store's OWN completion-queue path
(``NativeLogStore.append_async``, the reference's async writer shape,
cbits hs_writer.cpp:36-45) sits unused. This front puts every columnar
append behind a small lane array keyed by logid:

* on a store with ``append_async`` (the native C++ completion queue,
  or the replicated store's ack-wait pool) the lane IS that queue —
  submissions return a Future and group-commit / overlap ack waits;
* on any other store (mem://) each lane is one worker thread draining
  a FIFO, so N streams append in parallel while the RPC thread
  validates/wraps the NEXT block instead of waiting out the store.

Ordering: a logid always maps to the same lane (``logid % lanes``) and
lanes are FIFO, so per-stream append order is submission order — the
property the streaming AppendColumnar RPC's record ids rely on. The
caller resolves the returned futures (in order) before answering the
client, so acknowledged appends are durable exactly like the sync path.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Sequence

from hstream_tpu.common import locktrace
from hstream_tpu.store.api import Compression

# a lane worker that cannot keep up holds at most this many pending
# batches before submit() backpressures the RPC thread
LANE_DEPTH = 64


class AppendFront:
    """Append lanes in front of one LogStore (see module docstring)."""

    def __init__(self, store, lanes: int = 2):
        self._store = store
        # native path: the C++ completion queue already pipelines and
        # group-commits; extra Python lanes would only add hops
        self._async = hasattr(store, "append_async")
        self.lanes = 1 if self._async else max(int(lanes), 1)
        self._queues: list[queue.Queue] = []
        self._threads: list[threading.Thread] = []
        self._closed = False
        self.submitted = 0   # batches handed to the front
        self.completed = 0   # batches resolved (ok or error)
        # named traced locks (ISSUE 14): the lock-order witness sees
        # every acquire when armed; disarmed cost is one attribute
        # read + one branch per acquire (hot-path contract below)
        self._stat_lock = locktrace.lock("appendfront.stat")
        # serializes the closed-check + enqueue against close(): without
        # it a submit racing shutdown could land its item AFTER the
        # close sentinel and leave its Future unresolved forever
        self._submit_lock = locktrace.lock("appendfront.submit")
        # per-lane enqueue locks: backpressure on one lane must not
        # head-of-line-block submissions to the others
        self._lane_locks = locktrace.lock_list("appendfront.lane",
                                               self.lanes)
        if not self._async:
            for i in range(self.lanes):
                q: queue.Queue = queue.Queue(maxsize=LANE_DEPTH)
                t = threading.Thread(target=self._lane_loop, args=(q,),
                                     name=f"append-lane-{i}", daemon=True)
                self._queues.append(q)
                self._threads.append(t)
                t.start()

    # contract: dispatches<=0 fetches<=0
    def submit(self, logid: int, payloads: Sequence[bytes],
               compression: Compression = Compression.NONE
               ) -> "Future[int]":
        """Queue one batch; the Future resolves to its LSN once the
        store has durably accepted it (or to the store's exception).
        No append-time override on this surface: the completion-queue
        path stamps the store's own clock, so offering the knob only on
        the lane fallback would be a path-dependent divergence — event
        time rides the record headers instead (wrap_raw_record)."""
        with self._stat_lock:
            self.submitted += 1
        fut: Future = Future()
        if self._async:
            try:
                with self._submit_lock:
                    if self._closed:
                        raise RuntimeError("append front is closed")
                    inner = self._store.append_async(logid, payloads,
                                                     compression)
            except BaseException:
                # nothing was submitted: the stat must not count a
                # phantom in-flight batch forever
                with self._stat_lock:
                    self.submitted -= 1
                raise
            # chain through an outer future so the completion count is
            # bumped BEFORE any waiter on the result wakes — a caller
            # that resolved every future must observe in_flight == 0
            inner.add_done_callback(lambda f: self._finish(f, fut))
            return fut
        lane = logid % self.lanes
        # per-LANE lock: a lane at depth blocks only its own stream's
        # submitters, not every other lane (and not close()). The
        # sentinel ordering still holds: close() sets _closed BEFORE
        # taking any lane lock, so a False read here means THIS lane's
        # sentinel has not been placed yet and the item lands ahead of
        # it; a stale-False race just means the item is still processed
        # before the worker exits.
        with self._lane_locks[lane]:
            if self._closed:  # analyze: ok lock-guard — ordering via
                # the lane lock, see above; worst case is an accepted
                # item that the draining worker still completes
                with self._stat_lock:
                    self.submitted -= 1
                raise RuntimeError("append front is closed")
            # deliberate per-lane backpressure: a lane at depth blocks
            # ONLY its own stream's submitters on the lane lock; the
            # worker holds no lock while draining, so the put always
            # unblocks at store speed, and close() (which queues
            # behind this lock only for the sentinel insert) is
            # bounded the same way
            # analyze: ok wait-holding — see rationale above
            self._queues[lane].put(
                (logid, payloads, compression, fut))
        return fut

    def _finish(self, inner: "Future[int]", out: Future) -> None:
        with self._stat_lock:
            self.completed += 1
        err = inner.exception()
        if err is not None:
            out.set_exception(err)
        else:
            out.set_result(inner.result())

    def _lane_loop(self, q: queue.Queue) -> None:
        # exits ONLY on the sentinel: an early _closed return could
        # strand an item (and its Future) a racing submit enqueued just
        # before close() flipped the flag — close() always sentinels
        # (the thread is a daemon, so a never-closed front cannot hang
        # process exit)
        while True:
            try:
                item = q.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:  # close sentinel
                return
            logid, payloads, compression, fut = item
            try:
                lsn = self._store.append_batch(
                    logid, payloads, compression)
            except BaseException as e:  # noqa: BLE001 — the failure
                # belongs to the submitting RPC, not this worker
                err, lsn = e, None
            else:
                err = None
            # completion counts BEFORE the waiter wakes (stats contract)
            with self._stat_lock:
                self.completed += 1
            if err is not None:
                fut.set_exception(err)
            else:
                fut.set_result(lsn)

    def stats(self) -> dict:
        with self._stat_lock:
            submitted, completed = self.submitted, self.completed
        return {"lanes": self.lanes,
                "async": self._async,
                "submitted": submitted,
                "completed": completed,
                "in_flight": submitted - completed}

    def close(self, timeout: float = 5.0) -> None:
        """Drain the lanes and reap the workers. Pending futures still
        resolve (each lane finishes its queue up to the sentinel)."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
        # _closed is set; each lane's sentinel goes in under ITS lock,
        # so no submit can slip an item behind it
        for q, lk in zip(self._queues, self._lane_locks):
            with lk:
                q.put(None)
        for t in self._threads:
            t.join(timeout=timeout)
