"""Flight recorder: bounded postmortem bundles for distressed queries.

ISSUE 18 tentpole (d). When a query first goes STALLED (the health
plane's transition edge, health.evaluate_query) or its crash-loop
breaker opens (scheduler._open_breaker), the moment an operator wants
the evidence is exactly the moment it starts rotting: the journal ring
overwrites, trace spans recycle, the task dies and takes its counters
with it. The flight recorder snapshots everything the postmortem needs
INTO ONE BUNDLE at the transition edge — last-N journal events, the
query's trace spans, the health verdict with reasons, its stat-ladder
row, the compiled-program inventory, and the HBM arena accounting —
and keeps it in a two-slot per-query rotation that SURVIVES query
deletion (the bundle is the black box; deleting the aircraft must not
shred it).

Served via ``GET /queries/<id>/flightrec`` and ``admin flightrec
<id>``; every write journals a ``flightrec_written`` event carrying
the pointer an operator greps for.

Capture cost discipline: host-side folds only — zero device
dispatches, zero fetches, bounded list copies. Every section is
individually best-effort: a half-torn-down subsystem yields an
``"error"`` marker in that section, never a lost bundle.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any

# bounds: the bundle is a black box, not an archive
EVENTS_TAIL = 64       # journal entries captured per bundle
SPANS_CAP = 128        # trace spans captured per bundle
PROGRAM_ROWS_CAP = 64  # program-inventory rows captured per bundle
SLOTS_PER_QUERY = 2    # bundle rotation depth per query
MAX_QUERIES = 32       # LRU bound on distinct queries with bundles


class FlightRecorder:
    """Two-slot-per-query rotation of postmortem bundles, LRU-bounded
    across queries; thread-safe. Construction is cheap — the recorder
    holds nothing until the first distress edge fires."""

    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self._lock = threading.Lock()
        # qid -> deque of bundles (newest last); OrderedDict as LRU
        self._slots: "OrderedDict[str, deque[dict[str, Any]]]" = \
            OrderedDict()
        self._seq = 0
        self.written = 0  # total bundles ever recorded

    # ---- capture -----------------------------------------------------------

    def snapshot(self, qid: str, *, trigger: str,
                 health: dict[str, Any] | None = None) -> dict[str, Any]:
        """Capture one bundle for `qid` at a distress edge. `trigger`
        names the edge ("query_stalled" | "crash_loop_open"); `health`
        is the already-computed verdict dict when the caller has one
        (re-evaluating here would re-fire the transition journaling).
        Never raises — a flight recorder that crashes the plane it is
        recording has failed at its one job."""
        ctx = self.ctx
        with self._lock:
            self._seq += 1
            seq = self._seq
        bundle: dict[str, Any] = {
            "query": qid,
            "trigger": trigger,
            "seq": seq,
            "ts_ms": int(time.time() * 1e3),
        }
        if health is not None:
            bundle["health"] = dict(health)
        bundle["events"] = self._capture_events()
        bundle["spans"] = self._capture_spans(qid)
        bundle["stat_ladder"] = self._capture_stat_ladder(qid)
        bundle["programs"] = self._capture_programs()
        bundle["hbm"] = self._capture_hbm(qid)
        with self._lock:
            ring = self._slots.get(qid)
            if ring is None:
                ring = deque(maxlen=SLOTS_PER_QUERY)
                self._slots[qid] = ring
            ring.append(bundle)
            self._slots.move_to_end(qid)
            while len(self._slots) > MAX_QUERIES:
                self._slots.popitem(last=False)
            self.written += 1
            n_slots = len(ring)
        try:
            ctx.events.append(
                "flightrec_written",
                f"flight recorder captured query {qid} "
                f"({trigger}); GET /queries/{qid}/flightrec",
                query=qid, trigger=trigger, seq=seq, slots=n_slots)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass
        return bundle

    # ---- per-section capture (each individually best-effort) ---------------

    def _capture_events(self) -> Any:
        try:
            return self.ctx.events.query(limit=EVENTS_TAIL)
        except Exception as e:  # noqa: BLE001
            return {"error": type(e).__name__}

    def _capture_spans(self, qid: str) -> Any:
        try:
            spans = self.ctx.tracing.spans(qid)
            return spans[-SPANS_CAP:]
        except Exception as e:  # noqa: BLE001
            return {"error": type(e).__name__}

    def _capture_stat_ladder(self, qid: str) -> Any:
        """The query's full rate ladder, every query-scope family —
        the `admin stats queries` row frozen at the distress edge."""
        try:
            from hstream_tpu.stats.families import families_for_scope

            out = {}
            for fam in families_for_scope("query"):
                lad = self.ctx.stats.stat_ladder(fam.name, qid)
                out[fam.name] = {k: (round(v, 3)
                                     if isinstance(v, float) else v)
                                 for k, v in lad.items()}
            return out
        except Exception as e:  # noqa: BLE001
            return {"error": type(e).__name__}

    def _capture_programs(self) -> Any:
        try:
            from hstream_tpu.stats.devicecost import PROGRAMS

            return {"summary": PROGRAMS.summary(),
                    "rows": PROGRAMS.rows()[:PROGRAM_ROWS_CAP]}
        except Exception as e:  # noqa: BLE001
            return {"error": type(e).__name__}

    def _capture_hbm(self, qid: str) -> Any:
        try:
            from hstream_tpu.stats.devicecost import (
                backend_hbm_bytes,
                query_hbm_bytes,
            )

            out = query_hbm_bytes(self.ctx, qid)
            backend = backend_hbm_bytes()
            if backend is not None:
                out["backend_bytes_in_use"] = backend
            return out
        except Exception as e:  # noqa: BLE001
            return {"error": type(e).__name__}

    # ---- read surface ------------------------------------------------------

    def bundles(self, qid: str) -> list[dict[str, Any]]:
        """Newest-last bundles for a query (empty when none) — works
        after the query itself is deleted."""
        with self._lock:
            ring = self._slots.get(qid)
            return [dict(b) for b in ring] if ring is not None else []

    def queries(self) -> list[str]:
        """Query ids with at least one bundle, oldest-written first."""
        with self._lock:
            return list(self._slots)

    def summary(self) -> dict[str, Any]:
        with self._lock:
            return {
                "written": self.written,
                "queries": {q: len(r) for q, r in self._slots.items()},
                "slots_per_query": SLOTS_PER_QUERY,
                "max_queries": MAX_QUERIES,
            }
