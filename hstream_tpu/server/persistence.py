"""Query / connector metadata persistence.

The reference defines a `Persistence` typeclass with a ZooKeeper znode
tree (`/hstreamdb/hstream/{queries,connectors}/<id>/{sql,createdTime,
type,status}`) and an in-memory IORef instance selected by `--persistent`
(hstream/src/HStream/Server/Persistence.hs:115-256). Here the durable
instance rides the log store's metadata KV — the same KV the stream
namespace uses — so metadata durability follows the store backend
(mem:// = ephemeral, native disk store = durable) with no extra service.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

from hstream_tpu.common.errors import ConnectorNotFound, QueryNotFound
from hstream_tpu.store.api import LogStore


class TaskStatus:
    CREATING = 0
    CREATED = 1
    CREATION_ABORT = 2
    RUNNING = 3
    TERMINATED = 4
    CONNECTION_ABORT = 5
    # crash-loop breaker verdict (QuerySupervisor): K deaths in W
    # seconds — the query stays down until an operator RestartQuery.
    # Rides the wire as a raw value of the open proto3 TaskStatusPB
    # enum (no regenerated descriptor needed).
    FAILED = 6


# query types (reference PersistentQuery createdTime/queryType)
QUERY_PUSH = "push"          # ExecutePushQuery (temp sink, dies with client)
QUERY_STREAM = "stream"      # CREATE STREAM AS SELECT
QUERY_VIEW = "view"          # CREATE VIEW


@dataclass
class QueryInfo:
    query_id: str
    sql: str
    created_time_ms: int
    query_type: str = QUERY_PUSH
    status: int = TaskStatus.CREATED
    sink: str = ""             # sink stream / view name
    extra: dict = field(default_factory=dict)

    def to_json(self) -> dict:
        return {"sql": self.sql, "createdTime": self.created_time_ms,
                "type": self.query_type, "status": self.status,
                "sink": self.sink, "extra": self.extra}

    @classmethod
    def from_json(cls, query_id: str, d: dict) -> "QueryInfo":
        return cls(query_id=query_id, sql=d["sql"],
                   created_time_ms=d["createdTime"], query_type=d["type"],
                   status=d["status"], sink=d.get("sink", ""),
                   extra=d.get("extra", {}))


@dataclass
class ConnectorInfo:
    connector_id: str
    sql: str                   # CREATE SINK CONNECTOR statement / config
    created_time_ms: int
    status: int = TaskStatus.CREATED

    def to_json(self) -> dict:
        return {"sql": self.sql, "createdTime": self.created_time_ms,
                "status": self.status}

    @classmethod
    def from_json(cls, connector_id: str, d: dict) -> "ConnectorInfo":
        return cls(connector_id=connector_id, sql=d["sql"],
                   created_time_ms=d["createdTime"], status=d["status"])


def now_ms() -> int:
    return int(time.time() * 1000)


class Persistence:
    """The metadata interface (reference Persistence.hs:115-130)."""

    # ---- queries ----
    def insert_query(self, info: QueryInfo) -> None:
        raise NotImplementedError

    def get_query(self, query_id: str) -> QueryInfo:
        raise NotImplementedError

    def get_queries(self) -> list[QueryInfo]:
        raise NotImplementedError

    def set_query_status(self, query_id: str, status: int) -> None:
        raise NotImplementedError

    def remove_query(self, query_id: str) -> None:
        raise NotImplementedError

    # ---- connectors ----
    def insert_connector(self, info: ConnectorInfo) -> None:
        raise NotImplementedError

    def get_connector(self, connector_id: str) -> ConnectorInfo:
        raise NotImplementedError

    def get_connectors(self) -> list[ConnectorInfo]:
        raise NotImplementedError

    def set_connector_status(self, connector_id: str, status: int) -> None:
        raise NotImplementedError

    def remove_connector(self, connector_id: str) -> None:
        raise NotImplementedError


class MemPersistence(Persistence):
    """In-memory instance (reference Persistence.hs:128-190)."""

    def __init__(self) -> None:
        self._queries: dict[str, QueryInfo] = {}
        self._connectors: dict[str, ConnectorInfo] = {}
        self._lock = threading.Lock()

    def insert_query(self, info: QueryInfo) -> None:
        with self._lock:
            self._queries[info.query_id] = info

    def get_query(self, query_id: str) -> QueryInfo:
        with self._lock:
            q = self._queries.get(query_id)
        if q is None:
            raise QueryNotFound(query_id)
        return q

    def get_queries(self) -> list[QueryInfo]:
        with self._lock:
            return list(self._queries.values())

    def set_query_status(self, query_id: str, status: int) -> None:
        self.get_query(query_id).status = status

    def remove_query(self, query_id: str) -> None:
        with self._lock:
            if self._queries.pop(query_id, None) is None:
                raise QueryNotFound(query_id)

    def insert_connector(self, info: ConnectorInfo) -> None:
        with self._lock:
            self._connectors[info.connector_id] = info

    def get_connector(self, connector_id: str) -> ConnectorInfo:
        with self._lock:
            c = self._connectors.get(connector_id)
        if c is None:
            raise ConnectorNotFound(connector_id)
        return c

    def get_connectors(self) -> list[ConnectorInfo]:
        with self._lock:
            return list(self._connectors.values())

    def set_connector_status(self, connector_id: str, status: int) -> None:
        self.get_connector(connector_id).status = status

    def remove_connector(self, connector_id: str) -> None:
        with self._lock:
            if self._connectors.pop(connector_id, None) is None:
                raise ConnectorNotFound(connector_id)


class StorePersistence(Persistence):
    """Durable instance over the log store's metadata KV — the analogue
    of the reference's ZooKeeper znode tree (Persistence.hs:197-256),
    with the same key shape `/hstream/queries/<id>`."""

    _QP = "/hstream/queries/"
    _CP = "/hstream/connectors/"

    def __init__(self, store: LogStore):
        self._store = store
        self._lock = threading.Lock()

    # ---- queries ----
    def insert_query(self, info: QueryInfo) -> None:
        self._store.meta_put(self._QP + info.query_id,
                             json.dumps(info.to_json()).encode())

    def get_query(self, query_id: str) -> QueryInfo:
        raw = self._store.meta_get(self._QP + query_id)
        if raw is None:
            raise QueryNotFound(query_id)
        return QueryInfo.from_json(query_id, json.loads(raw))

    def get_queries(self) -> list[QueryInfo]:
        out = []
        for key in self._store.meta_list(self._QP):
            qid = key[len(self._QP):]
            raw = self._store.meta_get(key)
            if raw is not None:
                out.append(QueryInfo.from_json(qid, json.loads(raw)))
        return out

    def set_query_status(self, query_id: str, status: int) -> None:
        with self._lock:
            info = self.get_query(query_id)
            info.status = status
            self.insert_query(info)

    def remove_query(self, query_id: str) -> None:
        if self._store.meta_get(self._QP + query_id) is None:
            raise QueryNotFound(query_id)
        self._store.meta_delete(self._QP + query_id)

    # ---- connectors ----
    def insert_connector(self, info: ConnectorInfo) -> None:
        self._store.meta_put(self._CP + info.connector_id,
                             json.dumps(info.to_json()).encode())

    def get_connector(self, connector_id: str) -> ConnectorInfo:
        raw = self._store.meta_get(self._CP + connector_id)
        if raw is None:
            raise ConnectorNotFound(connector_id)
        return ConnectorInfo.from_json(connector_id, json.loads(raw))

    def get_connectors(self) -> list[ConnectorInfo]:
        out = []
        for key in self._store.meta_list(self._CP):
            cid = key[len(self._CP):]
            raw = self._store.meta_get(key)
            if raw is not None:
                out.append(ConnectorInfo.from_json(cid, json.loads(raw)))
        return out

    def set_connector_status(self, connector_id: str, status: int) -> None:
        with self._lock:
            info = self.get_connector(connector_id)
            info.status = status
            self.insert_connector(info)

    def remove_connector(self, connector_id: str) -> None:
        if self._store.meta_get(self._CP + connector_id) is None:
            raise ConnectorNotFound(connector_id)
        self._store.meta_delete(self._CP + connector_id)
