"""Subscription runtime: fetch/ack with gap-aware ack ranges.

Reference semantics (Handler.hs:420-718, Handler/Common.hs:119-166):

  * a subscription binds a checkpointed reader to a stream at an offset
  * Fetch returns batches as (RecordId{batch_id=LSN, batch_index}, bytes)
    and records each batch's size in `batchNumMap`; gap records are
    inserted straight into the acked ranges
  * Acknowledge merges acked RecordIds into disjoint ranges using the
    successor function: within a batch the next index, across batches the
    first index of the next *known* LSN (Common.hs:119-166 — the subtle
    bit SURVEY flags as property-test-worthy)
  * when the window's lower bound advances past a range, the checkpoint
    commits at `lower.lsn - 1` (partially acked batches are redelivered
    on resume — at-least-once)

`AckWindow` implements exactly that bookkeeping; `SubscriptionRuntime`
owns reader + window + the StreamingFetch consumer round-robin
(Handler.hs:819-922).
"""

from __future__ import annotations

import bisect
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any

from hstream_tpu.common import locktrace
from hstream_tpu.common.errors import (
    SubscriptionExists,
    SubscriptionNotFound,
)
from hstream_tpu.store.api import LSN_MIN, DataBatch, GapRecord
from hstream_tpu.store.checkpoint import CheckpointedReader


@dataclass(frozen=True, order=True)
class RecId:
    lsn: int
    idx: int


def _expand_columnar(payload: bytes) -> list[bytes] | None:
    """Expand an internal columnar record (query sinks pack a whole
    emitted batch into ONE RAW record — tasks.stream_sink) into per-row
    JSON records for subscription consumers, which speak the reference
    wire protocol and would otherwise see opaque bytes. None = not a
    columnar record, deliver verbatim. The RecId batch_index space and
    the AckWindow's batch size both use the expanded count, so ack
    bookkeeping stays consistent."""
    from hstream_tpu.common import columnar, records as rec

    if b"HSCB" not in payload:  # cheap reject before a protobuf parse
        return None
    try:
        r = rec.parse_record(payload)
        if (r.header.flag != rec.pb.RECORD_FLAG_RAW
                or not columnar.is_columnar(r.payload)):
            return None
        ts, cols, nulls = columnar.decode_columnar_nulls(r.payload)
        # drop_null: masked cells (framed-append null masks) read as
        # fields the producer never sent, like every other consumer
        rows = columnar.to_rows(ts, cols, nulls, drop_null=True)
    except Exception:  # noqa: BLE001 — malformed: deliver verbatim
        return None
    if not rows:
        # an empty expansion would note a size-0 batch, which parks the
        # ack window's lower bound forever; deliver verbatim instead
        return None
    pt = r.header.publish_time_ms
    return [rec.build_record(row, key=r.header.key,
                             publish_time_ms=int(t) if t else pt)
            .SerializeToString()
            for row, t in zip(rows, ts.tolist())]


class AckWindow:
    """Ack-range bookkeeping for one subscription (Common.hs:119-166)."""

    def __init__(self) -> None:
        self.lower: RecId | None = None       # next record needing ack
        self.ranges: list[list[RecId]] = []   # disjoint [start, end], sorted
        self.batch_sizes: dict[int, int] = {}
        self.known_lsns: list[int] = []       # sorted delivered LSNs

    # ---- delivery-side bookkeeping ----
    def note_batch(self, lsn: int, size: int) -> None:
        if lsn not in self.batch_sizes:
            bisect.insort(self.known_lsns, lsn)
        self.batch_sizes[lsn] = size
        if self.lower is None:
            self.lower = RecId(lsn, 0)

    def note_gap(self, lo_lsn: int, hi_lsn: int) -> None:
        """A gap [lo, hi] needs no consumer acks: insert it as an acked
        range covering the endpoints (intermediate LSNs can never be
        delivered individually)."""
        self.note_batch(hi_lsn, 1)
        if lo_lsn != hi_lsn and lo_lsn not in self.batch_sizes:
            bisect.insort(self.known_lsns, lo_lsn)
            self.batch_sizes[lo_lsn] = 1
        if self.lower is None:
            self.lower = RecId(lo_lsn, 0)
        self._insert_range(RecId(lo_lsn, 0), RecId(hi_lsn, 0))

    # ---- successor ----
    def successor(self, rid: RecId) -> RecId | None:
        """The next record id after `rid`, or None when the next LSN has
        not been delivered yet (merge retried later)."""
        size = self.batch_sizes.get(rid.lsn, 1)
        if rid.idx + 1 < size:
            return RecId(rid.lsn, rid.idx + 1)
        i = bisect.bisect_right(self.known_lsns, rid.lsn)
        if i < len(self.known_lsns):
            return RecId(self.known_lsns[i], 0)
        return None

    # ---- acks ----
    def ack(self, rid: RecId) -> None:
        self._insert_range(rid, rid)

    def _adjoins(self, end: RecId, start: RecId) -> bool:
        """True when [.., end] and [start, ..] overlap or are adjacent
        (start == successor(end)); unknown successors defer the merge."""
        if start <= end:
            return True
        s = self.successor(end)
        return s is not None and start <= s

    def _insert_range(self, start: RecId, end: RecId) -> None:
        i = bisect.bisect_left(self.ranges, [start, end])
        self.ranges.insert(i, [start, end])
        if i > 0 and self._adjoins(self.ranges[i - 1][1],
                                   self.ranges[i][0]):
            self.ranges[i - 1][1] = max(self.ranges[i - 1][1],
                                        self.ranges[i][1])
            del self.ranges[i]
            i -= 1
        while (i + 1 < len(self.ranges)
               and self._adjoins(self.ranges[i][1], self.ranges[i + 1][0])):
            self.ranges[i][1] = max(self.ranges[i][1],
                                    self.ranges[i + 1][1])
            del self.ranges[i + 1]

    # ---- window advance ----
    def advance(self) -> int | None:
        """Advance the lower bound over fully-acked prefix ranges.
        Returns the new committable checkpoint LSN (lower.lsn - 1), or
        None if the bound did not move. Ranges that could not merge at
        ack time (successor unknown then) are walked here, since the
        loop re-tests the new first range against the advanced bound."""
        moved = False
        while (self.ranges and self.lower is not None
               and self.ranges[0][0] <= self.lower):
            start, end = self.ranges.pop(0)
            if end < self.lower:
                continue  # stale range from duplicate acks
            nxt = self.successor(end)
            if nxt is None:
                # everything delivered so far is acked: park the bound
                # just past the end; the next delivery re-opens it
                self.lower = max(self.lower, RecId(end.lsn + 1, 0))
                moved = True
                break
            self.lower = max(self.lower, nxt)
            moved = True
        if not moved or self.lower is None:
            return None
        return self.lower.lsn - 1


class Consumer:
    def __init__(self, name: str, credit_window: int = 0):
        self.name = name
        self.queue: "queue.Queue[list[tuple[RecId, bytes]]]" = queue.Queue(
            maxsize=64)
        self.alive = True
        # credit-based delivery: one credit per in-flight record,
        # refilled by this consumer's acks. None = unbounded (legacy).
        from hstream_tpu.flow import CreditWindow

        self.credits = (CreditWindow(credit_window)
                        if credit_window > 0 else None)


class SubscriptionRuntime:
    """Reader + ack window + consumers of one subscription."""

    def __init__(self, ctx, meta: Any):
        self.ctx = ctx
        self.meta = meta  # pb Subscription
        self.sub_id = meta.subscription_id
        self.logid = ctx.streams.get_logid(meta.stream_name)
        self.window = AckWindow()
        # named traced lock (ISSUE 14): fetch/ack/dispatch/shutdown
        # all rendezvous here — witness-instrumented
        self.lock = locktrace.lock("subscriptions.runtime")
        self._reader: CheckpointedReader | None = None
        self._committed: int = 0
        # streaming-fetch state
        self.consumers: list[Consumer] = []
        self._rr = 0
        self._dispatcher: threading.Thread | None = None
        self._stop = threading.Event()
        # batches reclaimed from dead consumers' queues, redelivered
        # before anything newly fetched (at-least-once while running)
        self._requeue: list[list[tuple[RecId, bytes]]] = []
        self._last_backlog_feed = 0.0

    # ---- reader ------------------------------------------------------------

    def _start_lsn(self) -> int:
        off = self.meta.offset
        which = off.WhichOneof("offset")
        if which == "record_offset":
            return max(off.record_offset.batch_id, LSN_MIN)
        if off.special_offset == 1:  # LATEST
            return self.ctx.store.tail_lsn(self.logid) + 1
        return LSN_MIN  # EARLIEST

    def reader(self) -> CheckpointedReader:
        with self.lock:
            if self._reader is None:
                r = CheckpointedReader(
                    f"subscription-{self.sub_id}",
                    self.ctx.store.new_reader(), self.ctx.ckp_store)
                start = r.start_reading_from_checkpoint(
                    self.logid, self._start_lsn())
                # committed reflects the ACTUAL start position: records
                # before it are not outstanding, so lag (tail -
                # committed) is 0 for a fresh LATEST subscriber instead
                # of the whole log — a benign new subscriber must not
                # feed a phantom backlog into the overload detector
                self._committed = max(self._committed, start - 1)
                self._reader = r
            return self._reader

    # ---- fetch / ack -------------------------------------------------------

    def fetch(self, timeout_ms: int, max_size: int
              ) -> list[tuple[RecId, bytes]]:
        r = self.reader()
        r.set_timeout(int(timeout_ms))
        t0 = time.perf_counter()
        results = r.read(max(int(max_size), 1))
        # columnar expansion OUTSIDE the runtime lock (ISSUE 20): the
        # decode + per-row re-serialization is the expensive half of a
        # fetch. Log records are immutable, so the shared expansion
        # cache encodes each one ONCE per process and every consumer
        # of the stream reuses the same frame bytes by reference —
        # the encode-once fan-out half of the read plane. Lock hold
        # time shrinks to pure ack-window bookkeeping.
        cache = getattr(self.ctx, "read_cache", None)
        expanded: list[tuple[Any, list[bytes] | None]] = []
        for item in results:
            if not isinstance(item, DataBatch):
                expanded.append((item, None))
                continue
            payloads: list[bytes] = []
            for i, payload in enumerate(item.payloads):
                if cache is not None:
                    frames = cache.expand_frames(
                        self.logid, item.lsn, i, payload,
                        _expand_columnar)
                else:
                    frames = _expand_columnar(payload)
                if frames is None:
                    payloads.append(payload)
                else:
                    payloads.extend(frames)
            expanded.append((item, payloads))
        out: list[tuple[RecId, bytes]] = []
        newest = 0
        with self.lock:
            for item, payloads in expanded:
                if payloads is not None:
                    self.window.note_batch(item.lsn, len(payloads))
                    for i, payload in enumerate(payloads):
                        out.append((RecId(item.lsn, i), payload))
                    if item.append_time_ms > newest:
                        newest = item.append_time_ms
                elif isinstance(item, GapRecord):
                    self.window.note_gap(item.lo_lsn, item.hi_lsn)
            self._maybe_commit()
        if out:
            self._note_delivery(newest, t0)
            stats = getattr(self.ctx, "stats", None)
            if stats is not None:
                try:
                    # per-subscription delivery ladder (ISSUE 15): the
                    # rate a consumer group actually drains at — both
                    # the unary Fetch and the streaming dispatcher
                    # land here
                    nbytes = sum(len(p) for _r, p in out)
                    stats.stat_add("delivered_records", self.sub_id,
                                   float(len(out)))
                    stats.stat_add("delivered_bytes", self.sub_id,
                                   float(nbytes))
                    # read-side rate of the source stream (ISSUE 20):
                    # every subscription drain — unary Fetch AND the
                    # streaming dispatcher — is a read of that stream
                    # (the handler no longer double-counts it)
                    stats.note_read(self.meta.stream_name, len(out),
                                    nbytes)
                except Exception:  # noqa: BLE001 — metrics must not
                    pass           # kill delivery
        return out

    def _note_delivery(self, newest_append_ms: int, t0: float) -> None:
        """Freshness + tracing at the delivery boundary (ISSUE 13):
        append->delivery latency of the newest delivered record (the
        delivery stage of the lag taxonomy), and a `delivery` span
        when the fetching request is sampled. Host arithmetic only;
        never fails a fetch."""
        from hstream_tpu.common import tracing

        stats = getattr(self.ctx, "stats", None)
        if stats is not None and newest_append_ms > 0:
            try:
                lag = max(0.0, time.time() * 1e3 - newest_append_ms)
                stats.observe("freshness_lag_ms", "delivery", lag)
                stats.observe("append_visible_latency_ms", self.sub_id,
                              lag)
            except Exception:  # noqa: BLE001 — metrics must not kill
                pass           # delivery
        tr = getattr(self.ctx, "tracing", None)
        if tr is not None and tr.active:
            sctx = tracing.current_span()
            if sctx is not None:
                trace_id, parent = sctx
                dur_ms = (time.perf_counter() - t0) * 1e3
                try:
                    tr.record_span(
                        self.sub_id, "delivery", trace_id=trace_id,
                        span_id=tracing.new_span_id(),
                        parent_id=parent,
                        t0_ms=time.time() * 1e3 - dur_ms,
                        dur_ms=dur_ms)
                except Exception:  # noqa: BLE001 — span plumbing must
                    pass           # never fail delivery

    def ack(self, rec_ids: list[RecId],
            consumer: "Consumer | None" = None) -> None:
        if rec_ids:
            stats = getattr(self.ctx, "stats", None)
            if stats is not None:
                try:
                    stats.stat_add("acks_received", self.sub_id,
                                   float(len(rec_ids)))
                except Exception:  # noqa: BLE001 — metrics must not
                    pass           # kill the ack path
        with self.lock:
            for rid in rec_ids:
                self.window.ack(rid)
            self._maybe_commit()
            targets = ([consumer] if consumer is not None
                       else list(self.consumers))
        # refill OUTSIDE the runtime lock: the dispatcher blocks on
        # credits while holding nothing, and refill only touches the
        # window's own condition variable. Acks arriving without a
        # consumer (the unary Acknowledge RPC) cannot be attributed, so
        # they conservatively refill every registered consumer — the
        # per-window cap keeps each balance bounded, and a mixed
        # StreamingFetch-delivery/unary-ack client cannot starve itself
        for c in targets:
            if c.credits is not None:
                c.credits.refill(len(rec_ids))

    def _maybe_commit(self) -> None:
        """Caller holds self.lock (fetch/ack call this inside their
        critical section)."""
        ckp = self.window.advance()
        if ckp is not None and ckp > self._committed:
            self._committed = ckp
            if self._reader is not None:
                self._reader.write_checkpoints({self.logid: ckp})

    @property
    def committed_lsn(self) -> int:
        # found by hstream-analyze (lock-guard): _committed is written
        # under self.lock by fetch/ack; an unlocked read here could
        # surface a torn/stale lag to sub-lag admin + the backlog gauge
        with self.lock:
            return self._committed

    def credit_inflight(self) -> int:
        """Delivery credits currently in flight across this
        subscription's consumers (observability: the credit_inflight
        gauge). Unbounded (credits disabled) consumers count 0."""
        with self.lock:
            consumers = list(self.consumers)
        return sum(c.credits.window - c.credits.available
                   for c in consumers if c.credits is not None)

    # ---- streaming fetch (consumer round-robin) ----------------------------

    def register_consumer(self, name: str) -> Consumer:
        flow = getattr(self.ctx, "flow", None)
        c = Consumer(name, getattr(flow, "credit_window", 0) or 0)
        with self.lock:
            self.consumers.append(c)
            if self._dispatcher is None:
                self._dispatcher = threading.Thread(
                    target=self._dispatch_loop,
                    name=f"sub-{self.sub_id}-dispatch", daemon=True)
                self._dispatcher.start()
        return c

    def unregister_consumer(self, c: Consumer) -> None:
        c.alive = False
        with self.lock:
            if c in self.consumers:
                self.consumers.remove(c)
            self._reclaim_locked(c)

    def requeue(self, batch: list[tuple[RecId, bytes]]) -> None:
        """Hand back a delivered-but-unconsumed batch for redelivery
        (e.g. a StreamingFetch handler dying between queue.get and a
        successful yield)."""
        with self.lock:
            self._requeue.append(batch)

    def _reclaim_locked(self, c: Consumer) -> None:
        """Reclaim undelivered batches from a dead consumer's queue for
        redelivery. Caller holds self.lock."""
        while True:
            try:
                self._requeue.append(c.queue.get_nowait())
            except queue.Empty:
                break

    def _feed_backlog_signal(self) -> None:
        """~1 Hz: feed this subscription's lag (tail - committed) to the
        overload detector — the backlog signal of the shed ladder."""
        flow = getattr(self.ctx, "flow", None)
        if flow is None:
            return
        now = time.monotonic()
        if now - self._last_backlog_feed < 1.0:
            return
        with self.lock:
            if self._reader is None:
                return  # no reads yet: _committed is not seeded yet
            committed = self._committed
        self._last_backlog_feed = now
        try:
            tail = self.ctx.store.tail_lsn(self.logid)
            flow.overload.note("sub_backlog",
                               float(max(0, tail - committed)),
                               source=self.sub_id)
        except Exception:  # noqa: BLE001 — monitoring must not kill
            pass           # the dispatcher (e.g. stream being deleted)

    def _dispatch_loop(self) -> None:
        # 10ms low-res poll like the reference's readAndDispatchRecords
        # timer (Handler.hs:819-922), round-robining batches to consumers.
        # A fetched batch is already noted in the AckWindow, so it must
        # never be dropped: a batch that finds no queue slot or no
        # delivery credit is re-offered (rotating consumers) until
        # someone takes it — only then do we fetch more. Otherwise the
        # ack lower bound would stall forever.
        pending: list[tuple[RecId, bytes]] | None = None
        zero_credit_offers = 0  # consecutive offers refused for credit
        while not self._stop.is_set():
            self._feed_backlog_signal()
            with self.lock:
                alive = [c for c in self.consumers if c.alive]
            if not alive:
                if self._stop.wait(0.05):
                    return
                continue
            if pending is None:
                with self.lock:
                    if self._requeue:
                        pending = self._requeue.pop(0)
            if pending is None:
                batch = self.fetch(timeout_ms=10, max_size=64)
                if not batch:
                    continue
                pending = batch
            with self.lock:
                alive = [c for c in self.consumers if c.alive]
                if not alive:
                    continue  # keep pending until a consumer returns
                c = alive[self._rr % len(alive)]
                self._rr += 1
            take = len(pending)
            if c.credits is not None:
                # credit-based delivery: at most the consumer's credit
                # balance goes in flight; zero credit pauses delivery
                # until its acks refill (slow consumers stop inflating
                # server memory). Block on the window only when this is
                # the ONLY consumer — with siblings, rotate immediately
                # so one stalled consumer cannot throttle the healthy
                # ones; a short wait after a full zero-credit rotation
                # keeps the loop from spinning hot
                block = 0.2 if len(alive) == 1 else 0.0
                take = c.credits.take_up_to(len(pending), timeout=block)
                if take == 0:
                    self._note_credit_wait()
                    zero_credit_offers += 1
                    if zero_credit_offers >= len(alive) and block == 0.0:
                        self._stop.wait(0.01)
                    continue  # re-offer (rotated) while they drain
                zero_credit_offers = 0
            chunk = pending[:take]
            try:
                c.queue.put(chunk, timeout=0.2)
            except queue.Full:
                if c.credits is not None:
                    c.credits.refill(take)
                continue  # slow consumer: re-offer to the next one
            pending = pending[take:] or None
            with self.lock:
                if not c.alive:
                    # consumer died around the put: unregister's drain may
                    # have run before the put landed — reclaim anything
                    # stranded in the abandoned queue (at-least-once)
                    self._reclaim_locked(c)

    def _note_credit_wait(self) -> None:
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.stream_stat_add("delivery_credit_waits",
                                      self.meta.stream_name)
            except Exception:  # noqa: BLE001 — stats must not kill
                pass           # delivery

    def shutdown(self) -> None:
        self._stop.set()
        with self.lock:
            for c in self.consumers:
                c.alive = False
            self.consumers.clear()
            dispatcher = self._dispatcher
        # found by hstream-analyze (resource-leak): the dispatcher was
        # signalled but never reaped, so DeleteSubscription could return
        # while the loop was still mid-fetch — racing the checkpoint
        # remove and re-committing into a deleted subscription's store
        # state. Join OUTSIDE the lock (the loop takes self.lock per
        # tick); its waits are all bounded, so 5s covers a full tick.
        if dispatcher is not None \
                and dispatcher is not threading.current_thread():
            dispatcher.join(timeout=5)


class SubscriptionRegistry:
    def __init__(self) -> None:
        self._subs: dict[str, SubscriptionRuntime] = {}
        self._lock = locktrace.lock("subscriptions.registry")

    def create(self, ctx, meta) -> SubscriptionRuntime:
        with self._lock:
            if meta.subscription_id in self._subs:
                raise SubscriptionExists(meta.subscription_id)
            rt = SubscriptionRuntime(ctx, meta)
            self._subs[meta.subscription_id] = rt
            return rt

    def get(self, sub_id: str) -> SubscriptionRuntime:
        with self._lock:
            rt = self._subs.get(sub_id)
        if rt is None:
            raise SubscriptionNotFound(sub_id)
        return rt

    def exists(self, sub_id: str) -> bool:
        with self._lock:
            return sub_id in self._subs

    def remove(self, sub_id: str) -> None:
        with self._lock:
            rt = self._subs.pop(sub_id, None)
        if rt is None:
            raise SubscriptionNotFound(sub_id)
        rt.shutdown()

    def list(self) -> list[SubscriptionRuntime]:
        with self._lock:
            return list(self._subs.values())
