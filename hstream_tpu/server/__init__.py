"""hstream-tpu server: the gRPC HStreamApi service.

Layers (mirroring the reference's hstream/src/HStream/Server):
  context.py        ServerContext (store + registries + running tasks)
  handlers.py       the 35-RPC handler table
  tasks.py          managed continuous-query tasks
  subscriptions.py  fetch/ack runtime with gap-aware ack ranges
  views.py          materialized views + pull-query serving
  persistence.py    query/connector metadata (mem + store-KV backends)
  main.py           boot/CLI
"""

from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.handlers import HStreamApiServicer
from hstream_tpu.server.main import serve

__all__ = ["ServerContext", "HStreamApiServicer", "serve"]
