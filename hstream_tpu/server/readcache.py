"""Read plane: columnar snapshot cache + shared-encode fan-out.

ISSUE 20. The write path became O(1) dispatches per micro-batch in
PRs 5/10/11; this makes the READ path O(1) per close cycle. Two LRU
surfaces share one byte budget:

  * **Snapshot cache** — pull-query results keyed by (view, statement
    text), validated by an exact version tuple: the materialization's
    closed-store counter + the executor's read_version() (engine nonce,
    mutation epoch, close cycles, watermark). N concurrent readers of
    one view cost ONE executor extract + ONE result materialization;
    everyone else is a version-checked hit. The version probe is
    lock-free — every component is a monotone counter bumped AT the
    mutation, so a torn probe yields a spurious miss or a hit
    linearized just before an in-flight mutation, never a stale hit.
    A single-flight latch collapses concurrent misses onto one leader;
    followers consume the leader's cut (which happened after they
    arrived — linearizable).

  * **Expansion cache** — a query sink packs each emitted batch into
    ONE columnar record (tasks.stream_sink); every subscription fetch
    used to re-decode and re-serialize it per consumer. Log records are
    immutable, so the per-row serialized records are cached keyed by
    (logid, lsn, payload index) and every consumer of the stream shares
    the SAME frame bytes by reference — encode once, fan out 10k times.

`--read-max-staleness-ms` additionally age-bounds hits: exactness comes
from the version match, the knob is a freshness SLA backstop (and the
only control for deployments that mutate executors out-of-band). The
budget, hit ratio, and extract counters surface as gauges/counters via
ServerContext.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable

from hstream_tpu.common import locktrace
from hstream_tpu.server.views import serve_parts, serve_select_view


class _Entry:
    __slots__ = ("value", "version", "t", "nbytes")

    def __init__(self, value, version, t, nbytes):
        self.value = value
        self.version = version
        self.t = t
        self.nbytes = nbytes


class _Flight:
    """Single-flight latch for one snapshot key: the first miss leads,
    concurrent misses wait and consume the leader's result."""

    __slots__ = ("event", "rows", "ok")

    def __init__(self):
        self.event = threading.Event()
        self.rows = None
        self.ok = False


def _rows_nbytes(rows) -> int:
    """Cheap deterministic size estimate for the byte budget (cells
    priced, strings by length) — budget enforcement needs proportional,
    not exact."""
    total = 64
    for row in rows:
        total += 48
        for k, v in row.items():
            total += 16 + len(k)
            total += len(v) if isinstance(v, str) else 16
    return total


class ReadCache:
    """One process-wide LRU over snapshot + expansion entries.

    `readcache.lru` is a LEAF lock: held only for dict bookkeeping,
    never while taking tasks.state / views.materialization (the compute
    path runs between two separate lock sections) — the locktrace
    witness certifies this at runtime.
    """

    def __init__(self, *, max_bytes: int = 64 << 20,
                 max_staleness_ms: float | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.max_bytes = int(max_bytes)
        self.max_staleness_ms = max_staleness_ms
        self._clock = clock
        self._lock = locktrace.lock("readcache.lru")
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._flights: dict[tuple, _Flight] = {}
        self._bytes = 0
        # counters (host ints; mirrored into gauges/counters by ctx)
        self.hits = 0            # version-valid snapshot hits
        self.shared = 0          # followers served by a flight leader
        self.misses = 0          # snapshot recomputes
        self.bypasses = 0        # unversioned executors (never cached)
        self.extracts = 0        # serves that actually peeked the engine
        self.evictions = 0
        self.invalidations = 0
        self.expand_hits = 0
        self.expand_misses = 0

    # ---- gauges ------------------------------------------------------------

    def nbytes(self) -> int:
        return self._bytes

    def hit_ratio(self) -> float:
        served = self.hits + self.shared + self.misses
        return (self.hits + self.shared) / served if served else 0.0

    def stats(self) -> dict[str, int | float]:
        return {"hits": self.hits, "shared": self.shared,
                "misses": self.misses, "bypasses": self.bypasses,
                "extracts": self.extracts, "evictions": self.evictions,
                "invalidations": self.invalidations,
                "expand_hits": self.expand_hits,
                "expand_misses": self.expand_misses,
                "bytes": self._bytes, "entries": len(self._entries),
                "hit_ratio": self.hit_ratio()}

    # ---- snapshot cache (pull queries) -------------------------------------

    def _fresh(self, ent: _Entry, now: float) -> bool:
        if self.max_staleness_ms is None:
            return True
        return (now - ent.t) * 1000.0 <= self.max_staleness_ms

    # contract: dispatches<=1 fetches<=1
    def serve_view(self, name: str, mat, select, sql: str
                   ) -> tuple[list[dict[str, Any]], str, bool]:
        """Serve a pull query through the cache. Returns (rows, how,
        extracted) with how in {"hit", "shared", "miss", "bypass"};
        `extracted` is True only when THIS call ran an executor peek.
        At most ONE extract runs per (view, statement, version) — the
        close-cycle read contract."""
        key = ("snap", name, sql)
        version = mat.version()
        if version is None:
            # unversioned executor: correctness cannot be proven, so
            # this view never caches (and never goes stale)
            rows = serve_select_view(mat, select)
            with self._lock:
                self.bypasses += 1
                self.extracts += 1
            return rows, "bypass", True
        now = self._clock()
        while True:
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None and ent.version == version \
                        and self._fresh(ent, now):
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return list(ent.value), "hit", False
                flight = self._flights.get(key)
                if flight is None:
                    flight = _Flight()
                    self._flights[key] = flight
                    break  # this thread leads the recompute
            # follower: the leader's snapshot cut happens after this
            # request arrived, so consuming it is linearizable
            flight.event.wait(timeout=30.0)
            if flight.ok:
                with self._lock:
                    self.shared += 1
                return list(flight.rows), "shared", False
            # leader failed or timed out: retry (probe again / lead)
            version = mat.version()
            if version is None:
                rows = serve_select_view(mat, select)
                with self._lock:
                    self.bypasses += 1
                    self.extracts += 1
                return rows, "bypass", True
            now = self._clock()
        try:
            closed, live, got_version, peeked = mat.snapshot_parts(select)
            rows = serve_parts(closed, live, select)
            flight.rows = rows
            flight.ok = True
            with self._lock:
                self.misses += 1
                if peeked:
                    self.extracts += 1
                if got_version is not None:
                    self._store(key, rows, got_version,
                                _rows_nbytes(rows), self._clock())
            return list(rows), "miss", peeked
        finally:
            with self._lock:
                self._flights.pop(key, None)
            flight.event.set()

    def invalidate_view(self, name: str) -> None:
        """Drop every snapshot entry of a view (view deletion — version
        nonces already prevent stale hits; this frees the budget)."""
        with self._lock:
            dead = [k for k in self._entries
                    if k[0] == "snap" and k[1] == name]
            for k in dead:
                self._drop(k)
            self.invalidations += len(dead)

    # ---- expansion cache (subscription fan-out) ----------------------------

    def expand_frames(self, logid: int, lsn: int, idx: int,
                      payload: bytes,
                      expand: Callable[[bytes], list[bytes] | None]
                      ) -> list[bytes] | None:
        """Per-row serialized records of one immutable log payload,
        expanded at most once per process and shared BY REFERENCE with
        every consumer (encode-once fan-out). None (cached too) means
        not-columnar: deliver the payload verbatim."""
        key = ("enc", logid, lsn, idx)
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None:
                self._entries.move_to_end(key)
                self.expand_hits += 1
                return ent.value
        value = expand(payload)
        nbytes = (sum(len(b) for b in value) + 64) if value else 96
        with self._lock:
            self.expand_misses += 1
            self._store(key, value, None, nbytes, self._clock())
        return value

    # ---- LRU internals (caller holds self._lock) ---------------------------

    def _store(self, key, value, version, nbytes, t) -> None:
        if key in self._entries:
            self._drop(key)
        if nbytes > self.max_bytes:
            return  # larger than the whole budget: never admit
        self._entries[key] = _Entry(value, version, t, nbytes)
        self._bytes += nbytes
        while self._bytes > self.max_bytes and self._entries:
            old_key, old = self._entries.popitem(last=False)
            self._bytes -= old.nbytes
            self.evictions += 1
            if old_key == key:
                break

    def _drop(self, key) -> None:
        ent = self._entries.pop(key, None)
        if ent is not None:
            self._bytes -= ent.nbytes
