"""Materialized views: registry, materialization store, pull queries.

Reference: each grouped query registers its `Materialized` state in a
global `groupbyStores` IORef (Handler/Common.hs:74-76); a pull query
(`SELECT ... FROM view WHERE k = ...` without EMIT CHANGES) serializes
the key, dumps the state store, filters by key, and for fixed windows
groups rows by winStart with "winStart = .../winEnd = ..." labels
(Handler.hs:277-325).

Here a view's query task runs with emit_changes=False, so process()
returns only CLOSED windows — those append to the materialization's
bounded closed-row store — while the live (open-window) half is the
executor's peek(). A pull query serves closed + live rows with the WHERE
filter and projection applied host-side; winStart/winEnd ride along as
structured fields (richer than the reference's string labels).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

from hstream_tpu.common import locktrace
from hstream_tpu.common.errors import ViewNotFound
from hstream_tpu.engine.expr import eval_host
from hstream_tpu.sql import ast


class Materialization:
    """Closed-window rows (bounded, newest kept) + live peek.

    `group_cols` are the plan's actual GROUP BY columns: closed rows are
    keyed on (winStart, group values) so distinct keys of ANY type —
    numeric included — stay distinct. A view over a stateless select has
    no group identity; every row is kept under a sequence key.
    """

    def __init__(self, *, group_cols: list[str] | None = None,
                 max_closed_rows: int = 100_000):
        self._group_cols = group_cols
        self._closed: OrderedDict[tuple, dict[str, Any]] = OrderedDict()
        self._max = max_closed_rows
        self._seq = 0
        # named traced lock (ISSUE 14): the canonical order is
        # tasks.state BEFORE views.materialization (sink under the
        # task's lock; snapshot takes state_lock first for the same
        # reason) — the armed witness certifies it at runtime
        self._lock = locktrace.lock("views.materialization")
        self.task = None  # set by the owner; .executor gives live state

    def _row_key(self, row: dict[str, Any]) -> tuple:
        # (window, group identity): last write per (winStart, key cols)
        if self._group_cols is None:
            self._seq += 1
            return ("#seq", self._seq)
        return (row.get("winStart"),
                tuple(row.get(c) for c in self._group_cols))

    def add_closed(self, rows: list[dict[str, Any]]) -> None:
        # `rows` may be a columnar close batch (common.columnar
        # ColumnarEmit): the view store is a row-shaped boundary (pull
        # queries serve dicts), so iterating materializes the row view
        # once — cached on the batch, shared with any other row-shaped
        # consumer of the same emission.
        with self._lock:
            for row in rows:
                key = self._row_key(row)
                self._closed.pop(key, None)
                self._closed[key] = row
            while len(self._closed) > self._max:
                self._closed.popitem(last=False)

    def dump(self) -> list[dict[str, Any]]:
        """Closed rows in insertion order — rides in the query task's
        operator-state snapshot so the view survives restarts."""
        with self._lock:
            return list(self._closed.values())

    def load(self, rows: list[dict[str, Any]]) -> None:
        self.add_closed(rows)

    def snapshot(self) -> list[dict[str, Any]]:
        task = self.task
        if task is None:
            with self._lock:
                return list(self._closed.values())
        # state_lock around BOTH halves (closed copy + live peek), in the
        # same order the task thread takes them (state_lock -> mat._lock
        # via sink): a window closing between the two reads would
        # otherwise appear in neither half
        with task.state_lock:
            with self._lock:
                rows = list(self._closed.values())
            ex = task.executor
            if ex is not None and hasattr(ex, "peek"):
                rows.extend(ex.peek())
        return rows


class ViewRegistry:
    """view name -> Materialization (the groupbyStores analogue)."""

    def __init__(self) -> None:
        self._views: dict[str, Materialization] = {}
        self._lock = locktrace.lock("views.registry")

    def register(self, name: str, mat: Materialization) -> None:
        with self._lock:
            self._views[name] = mat

    def get(self, name: str) -> Materialization:
        with self._lock:
            mat = self._views.get(name)
        if mat is None:
            raise ViewNotFound(name)
        return mat

    def remove(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._views)


def filter_rows(rows: list[dict[str, Any]],
                select: ast.Select) -> list[dict[str, Any]]:
    """WHERE evaluation shared by view pull queries and LDQuery-lite
    virtual tables (NULL operand -> predicate not true, SQL rules)."""
    if select.where is None:
        return rows
    kept = []
    for row in rows:
        try:
            if eval_host(select.where, row):
                kept.append(row)
        except (TypeError, KeyError):
            continue
    return kept


def project_rows(rows: list[dict[str, Any]], select: ast.Select,
                 keep_meta: tuple[str, ...] = ()) -> list[dict[str, Any]]:
    """SELECT-list projection shared by the same two paths; * keeps
    rows as-is. `keep_meta` names ride along when present (the view
    path keeps window bounds)."""
    if select.items is None:
        return rows
    out = []
    for row in rows:
        proj: dict[str, Any] = {}
        for idx, item in enumerate(select.items):
            name = item.alias or item.text or f"col{idx}"
            try:
                proj[name] = eval_host(item.expr, row)
            except (TypeError, KeyError):
                proj[name] = None
        for meta in keep_meta:
            if meta in row:
                proj[meta] = row[meta]
        out.append(proj)
    return out


def serve_select_view(mat: Materialization,
                      select: ast.Select) -> list[dict[str, Any]]:
    """Execute a pull query against a materialization
    (reference Handler.hs:277-325: key filter + fixed-window slicing)."""
    rows = filter_rows(mat.snapshot(), select)
    # fixed-window slicing: group/order by winStart (labels are fields)
    rows.sort(key=lambda r: (r.get("winStart") or 0))
    return project_rows(rows, select, keep_meta=("winStart", "winEnd"))
