"""Materialized views: registry, materialization store, pull queries.

Reference: each grouped query registers its `Materialized` state in a
global `groupbyStores` IORef (Handler/Common.hs:74-76); a pull query
(`SELECT ... FROM view WHERE k = ...` without EMIT CHANGES) serializes
the key, dumps the state store, filters by key, and for fixed windows
groups rows by winStart with "winStart = .../winEnd = ..." labels
(Handler.hs:277-325).

Here a view's query task runs with emit_changes=False, so process()
returns only CLOSED windows — those append to the materialization's
bounded closed-row store — while the live (open-window) half is the
executor's peek(). A pull query serves closed + live rows with the WHERE
filter and projection applied host-side; winStart/winEnd ride along as
structured fields (richer than the reference's string labels).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any

import numpy as np

from hstream_tpu.common import locktrace
from hstream_tpu.common.columnar import ColumnarEmit
from hstream_tpu.common.errors import ViewNotFound
from hstream_tpu.engine.expr import (
    BinOp,
    Col,
    Lit,
    eval_host,
    eval_host_vec,
)
from hstream_tpu.sql import ast


class Materialization:
    """Closed-window rows (bounded, newest kept) + live peek.

    `group_cols` are the plan's actual GROUP BY columns: closed rows are
    keyed on (winStart, group values) so distinct keys of ANY type —
    numeric included — stay distinct. A view over a stateless select has
    no group identity; every row is kept under a sequence key.
    """

    def __init__(self, *, group_cols: list[str] | None = None,
                 max_closed_rows: int = 100_000):
        self._group_cols = group_cols
        self._closed: OrderedDict[tuple, dict[str, Any]] = OrderedDict()
        self._max = max_closed_rows
        self._seq = 0
        # named traced lock (ISSUE 14): the canonical order is
        # tasks.state BEFORE views.materialization (sink under the
        # task's lock; snapshot takes state_lock first for the same
        # reason) — the armed witness certifies it at runtime
        self._lock = locktrace.lock("views.materialization")
        self.task = None  # set by the owner; .executor gives live state
        # closed-store mutation counter (ISSUE 20): combined with the
        # executor's read_version this makes an exact validity key for
        # the read cache. Bumped under self._lock; probed lock-free (a
        # torn probe can only cause a spurious cache miss).
        self._version = 0

    def _row_key(self, row: dict[str, Any]) -> tuple:
        # (window, group identity): last write per (winStart, key cols)
        if self._group_cols is None:
            self._seq += 1
            return ("#seq", self._seq)
        return (row.get("winStart"),
                tuple(row.get(c) for c in self._group_cols))

    def add_closed(self, rows: list[dict[str, Any]]) -> None:
        # `rows` may be a columnar close batch (common.columnar
        # ColumnarEmit): the view store is a row-shaped boundary (pull
        # queries serve dicts), so iterating materializes the row view
        # once — cached on the batch, shared with any other row-shaped
        # consumer of the same emission.
        with self._lock:
            changed = False
            for row in rows:
                key = self._row_key(row)
                self._closed.pop(key, None)
                self._closed[key] = row
                changed = True
            while len(self._closed) > self._max:
                self._closed.popitem(last=False)
            if changed:
                self._version += 1

    def dump(self) -> list[dict[str, Any]]:
        """Closed rows in insertion order — rides in the query task's
        operator-state snapshot so the view survives restarts."""
        with self._lock:
            return list(self._closed.values())

    def load(self, rows: list[dict[str, Any]]) -> None:
        self.add_closed(rows)

    def snapshot(self) -> list[dict[str, Any]]:
        task = self.task
        if task is None:
            with self._lock:
                return list(self._closed.values())
        # state_lock around BOTH halves (closed copy + live peek), in the
        # same order the task thread takes them (state_lock -> mat._lock
        # via sink): a window closing between the two reads would
        # otherwise appear in neither half
        with task.state_lock:
            with self._lock:
                rows = list(self._closed.values())
            ex = task.executor
            if ex is not None and hasattr(ex, "peek"):
                rows.extend(ex.peek())
        return rows

    def version(self) -> tuple | None:
        """Lock-free validity probe for the read cache (ISSUE 20):
        equal tuples guarantee an identical snapshot. Every component
        is a monotone counter bumped AT the mutation, so a torn read
        can only produce a miss or a hit linearized just before an
        in-flight mutation — never a stale hit. None = this view's
        executor has no read versioning; never cache it."""
        task = self.task
        ex = getattr(task, "executor", None) if task is not None else None
        if ex is None:
            # analyze: ok lock-guard — deliberate lock-free monotone probe
            return (self._version, None)
        rv = getattr(ex, "read_version", None)
        if rv is None:
            return None
        exv = rv()
        if exv is None:
            return None
        # analyze: ok lock-guard — deliberate lock-free monotone probe
        return (self._version, exv)

    def snapshot_parts(self, select: ast.Select | None = None
                       ) -> tuple[list[dict[str, Any]], Any,
                                  tuple | None, bool]:
        """One consistent cut of (closed rows, live batch, version,
        peeked) under the task's state lock — the read cache stores the
        version alongside the served result so hits are exact.

        With `select`, the closed-only fast path applies (ISSUE 20
        satellite): a WHERE that bounds winEnd strictly below every
        live window's earliest possible winEnd is served from the
        materialization store alone — zero executor dispatches — which
        in device mode means the arena is never extracted at all."""
        task = self.task
        if task is None:
            with self._lock:
                return list(self._closed.values()), [], None, False
        with task.state_lock:
            with self._lock:
                closed = list(self._closed.values())
                mver = self._version
            ex = task.executor
            live: Any = []
            peeked = False
            if ex is not None and hasattr(ex, "peek"):
                if not _skip_live(ex, select):
                    live = ex.peek()
                    peeked = True
                rv = getattr(ex, "read_version", None)
                exv = rv() if rv is not None else None
                version = None if exv is None else (mver, exv)
            else:
                version = (mver, None)
        return closed, live, version, peeked


class ViewRegistry:
    """view name -> Materialization (the groupbyStores analogue)."""

    def __init__(self) -> None:
        self._views: dict[str, Materialization] = {}
        self._lock = locktrace.lock("views.registry")

    def register(self, name: str, mat: Materialization) -> None:
        with self._lock:
            self._views[name] = mat

    def get(self, name: str) -> Materialization:
        with self._lock:
            mat = self._views.get(name)
        if mat is None:
            raise ViewNotFound(name)
        return mat

    def remove(self, name: str) -> None:
        with self._lock:
            self._views.pop(name, None)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._views)


def _closed_only_bound(select: ast.Select | None
                       ) -> tuple[float, bool] | None:
    """Tightest upper bound some AND-level WHERE conjunct puts on
    winEnd: (bound, strict) for `winEnd < lit` / `winEnd <= lit` (either
    operand order), None when the WHERE does not bound winEnd. Any row
    violating the conjunct is dropped by the filter regardless of the
    rest of the predicate, so a peek whose every row violates it can be
    skipped exactly."""
    if select is None or select.where is None:
        return None
    flip = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}
    best: tuple[float, bool] | None = None
    stack = [select.where]
    while stack:
        e = stack.pop()
        if isinstance(e, BinOp) and e.op == "AND":
            stack.extend((e.left, e.right))
            continue
        if not isinstance(e, BinOp) or e.op not in flip:
            continue
        op, lhs, rhs = e.op, e.left, e.right
        if isinstance(rhs, Col) and isinstance(lhs, Lit):
            op, lhs, rhs = flip[op], rhs, lhs
        if not (isinstance(lhs, Col) and lhs.name == "winEnd"
                and lhs.stream is None and isinstance(rhs, Lit)
                and isinstance(rhs.value, (int, float))
                and not isinstance(rhs.value, bool)):
            continue
        if op in ("<", "<="):
            cand = (float(rhs.value), op == "<")
            # tighter = smaller bound; strict beats non-strict at equal
            if best is None or (cand[0], not cand[1]) < (best[0],
                                                         not best[1]):
                best = cand
    return best


def _skip_live(ex, select: ast.Select | None) -> bool:
    """True when the live (peek) half provably contributes nothing to
    this SELECT: the WHERE bounds winEnd below the earliest winEnd any
    live window could emit. Live rows WITHOUT a winEnd field (windowless
    aggregates) fail the winEnd conjunct too (NULL comparison -> not
    true), so a None live_min_win_end also skips."""
    bound = _closed_only_bound(select)
    if bound is None:
        return False
    fn = getattr(ex, "live_min_win_end", None)
    if fn is None:
        return False
    lo = fn()
    if lo is None:
        return True
    val, strict = bound
    return lo >= val if strict else lo > val


def filter_rows(rows: list[dict[str, Any]],
                select: ast.Select) -> list[dict[str, Any]]:
    """WHERE evaluation shared by view pull queries and LDQuery-lite
    virtual tables (NULL operand -> predicate not true, SQL rules)."""
    if select.where is None:
        return rows
    kept = []
    for row in rows:
        try:
            if eval_host(select.where, row):
                kept.append(row)
        except (TypeError, KeyError):
            continue
    return kept


def project_rows(rows: list[dict[str, Any]], select: ast.Select,
                 keep_meta: tuple[str, ...] = ()) -> list[dict[str, Any]]:
    """SELECT-list projection shared by the same two paths; * keeps
    rows as-is. `keep_meta` names ride along when present (the view
    path keeps window bounds)."""
    if select.items is None:
        return rows
    out = []
    for row in rows:
        proj: dict[str, Any] = {}
        for idx, item in enumerate(select.items):
            name = item.alias or item.text or f"col{idx}"
            try:
                proj[name] = eval_host(item.expr, row)
            except (TypeError, KeyError):
                proj[name] = None
        for meta in keep_meta:
            if meta in row:
                proj[meta] = row[meta]
        out.append(proj)
    return out


def _select_emit_cols(emit: ColumnarEmit,
                      select: ast.Select) -> list[dict[str, Any]]:
    """Columnwise WHERE + projection over a live peek batch — one
    vectorized pass instead of a per-row interpreter walk (the
    `_postprocess_cols` discipline from the close path). Raises for the
    exact per-row fallback on any op/NULL the vector evaluator does not
    cover."""
    cols, n = emit.cols, emit.n
    if select.where is not None:
        keep = np.broadcast_to(
            np.asarray(eval_host_vec(select.where, cols), np.bool_),
            (n,))
        if not keep.all():
            cols = {k: np.asarray(v)[keep] for k, v in cols.items()}
            n = int(keep.sum())
            if n == 0:
                return []
    if select.items is None:
        return list(ColumnarEmit(cols, n))
    projected: dict[str, Any] = {}
    for idx, item in enumerate(select.items):
        name = item.alias or item.text or f"col{idx}"
        v = eval_host_vec(item.expr, cols)
        projected[name] = np.broadcast_to(np.asarray(v), (n,)) \
            if np.ndim(v) == 0 else np.asarray(v)
    for meta in ("winStart", "winEnd"):
        if meta in cols:
            projected[meta] = np.asarray(cols[meta])
    return list(ColumnarEmit(projected, n))


def _select_emit(emit, select: ast.Select) -> list[dict[str, Any]]:
    """WHERE + projection over the live half: columnwise when the peek
    stayed columnar, whole-batch per-row fallback (exact SQL NULL /
    missing-field semantics) on anything the vector path cannot prove
    identical."""
    if isinstance(emit, ColumnarEmit):
        if emit.n == 0:
            return []
        try:
            return _select_emit_cols(emit, select)
        except Exception:  # noqa: BLE001 — host-only op / NULLs:
            pass           # exact per-row semantics below
    rows = filter_rows(list(emit), select)
    return project_rows(rows, select, keep_meta=("winStart", "winEnd"))


def serve_parts(closed: list[dict[str, Any]], live,
                select: ast.Select) -> list[dict[str, Any]]:
    """Filter + project both halves, then the fixed-window slicing sort
    (stable, so closed-before-live order at equal winStart matches the
    legacy concat pipeline exactly)."""
    out = project_rows(filter_rows(closed, select), select,
                       keep_meta=("winStart", "winEnd"))
    out.extend(_select_emit(live, select))
    out.sort(key=lambda r: (r.get("winStart") or 0))
    return out


def serve_select_view(mat: Materialization,
                      select: ast.Select) -> list[dict[str, Any]]:
    """Execute a pull query against a materialization
    (reference Handler.hs:277-325: key filter + fixed-window slicing)."""
    closed, live, _version, _peeked = mat.snapshot_parts(select)
    return serve_parts(closed, live, select)
