"""Query->server assignment + self-healing supervision.

The reference is single-process here too (every query runs in the one
server, Handler.hs:373-375); SURVEY's TPU-native column asks for a
scheduler persisting query placement in cluster metadata. This module
records, for every launched query, which server owns it — keyed
``scheduler/query/<qid>`` in the CAS-versioned config store — and lets
a booting server ADOPT queries whose owner is gone (its recorded boot
epoch predates ours; the boot-epoch CAS in ServerContext makes epochs
total-ordered per store). Adoption is itself a CAS, so two racing
successors cannot both take a query.

Liveness at BOOT is epoch-based (single store, one active server at a
time — a successor always boots with a higher epoch). A multi-server
deployment (the placer, ISSUE 17) adds heartbeats on the same records:
owners re-stamp ``hb_ms`` every placer tick, survivors adopt through
:func:`try_adopt_live` only when the lease lapses (or the record was
explicitly ``offered`` to them by a rebalance), and the CAS adoption
discipline is unchanged — two racing adopters still converge to one
owner. Record schema (JSON under ``scheduler/query/<qid>``)::

    {"node": "server-1@host:port",  # owner (or offer target)
     "epoch": 7,                    # owner's boot epoch (fencing)
     "hb_ms": 1700000000000,        # last owner heartbeat, wall ms
     "state": "owned" | "offered",  # offered = rebalance handoff
     "src": "server-2@..."}         # offering node (offered only)

``hb_ms``/``state`` are additive: records written by older code (or by
servers running with the placer disarmed) carry neither and keep the
pure epoch semantics everywhere.

``QuerySupervisor`` (ISSUE 8) closes the loop the reference leaves
open ("task distribution: none" — and a dead query stays dead): a
query task that dies on an unexpected exception is restarted from its
last snapshot with jittered exponential backoff, and a crash loop (K
deaths inside W seconds) opens a breaker — status FAILED, a
``crash_loop_open`` journal event + gauge — so a deterministic bug
cannot melt the server with restart storms. Restarts are gated
through ``adoption_allowed`` like boot adoption, so they shed at
DEFER under overload.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque

from hstream_tpu.common import locktrace
from hstream_tpu.common.backoff import jittered_backoff
from hstream_tpu.common.logger import get_logger
from hstream_tpu.store.versioned import VersionMismatch

log = get_logger("scheduler")

_PREFIX = "scheduler/query/"


def _key(query_id: str) -> str:
    return _PREFIX + query_id


def node_name(ctx) -> str:
    return f"server-{ctx.server_id}@{ctx.host}:{ctx.port}"


def now_ms() -> int:
    return int(time.time() * 1000)


def _owned_record(ctx) -> bytes:
    """Armed servers stamp ``hb_ms``/``state``; a server with the
    placer disarmed writes the legacy two-field record instead — it
    will never refresh a heartbeat, and a stamp it can't refresh would
    read as a lapsed lease to every armed peer after ``lease_ms``
    (rolling placer enablement would live-adopt queries whose disarmed
    owner is alive and running)."""
    record = {"node": node_name(ctx), "epoch": ctx.boot_epoch}
    placer = getattr(ctx, "placer", None)
    if placer is not None and placer.armed:
        record["hb_ms"] = now_ms()
        record["state"] = "owned"
    return json.dumps(record).encode()


def owner_heartbeat_age_ms(record: dict | None) -> int | None:
    """Milliseconds since the owner last heartbeated this record, or
    None for legacy records that carry no heartbeat (pure epoch
    liveness)."""
    if not record:
        return None
    hb = record.get("hb_ms")
    if hb is None:
        return None
    return max(0, now_ms() - int(hb))


def owner_live(record: dict | None, lease_ms: int) -> bool:
    """True when the record's owner heartbeated within the lease. A
    record without hb_ms is NOT live by this test (legacy records fall
    back to the epoch rule instead)."""
    age = owner_heartbeat_age_ms(record)
    return age is not None and age <= int(lease_ms)


def record_assignment(ctx, query_id: str) -> None:
    """Unconditionally claim a query for this server (fresh launches:
    the creating server owns the query). Armed, the write carries an
    implicit heartbeat — the owner was alive at launch; disarmed it is
    a legacy epoch-only record."""
    value = _owned_record(ctx)
    for _ in range(16):
        cur = ctx.config.get(_key(query_id))
        try:
            ctx.config.put(_key(query_id), value,
                           base_version=None if cur is None else cur[0])
            return
        except VersionMismatch:
            continue
    log.warning("assignment write for %s kept losing CAS", query_id)


def drop_assignment(ctx, query_id: str) -> None:
    cur = ctx.config.get(_key(query_id))
    if cur is None:
        return
    try:
        ctx.config.delete(_key(query_id), base_version=cur[0])
    except VersionMismatch:
        pass  # someone re-claimed it; their record stands


def assignment(ctx, query_id: str) -> dict | None:
    cur = ctx.config.get(_key(query_id))
    if cur is None:
        return None
    try:
        return json.loads(cur[1])
    except ValueError:
        return None


def adoption_allowed(ctx, query_id: str) -> bool:
    """Flow-control gate on boot-time adoption: taking over a dead
    owner's queries is background work, so it sheds at DEFER — before
    any user append is refused. A skipped query keeps its stale owner
    record and stays claimable by the next (healthier) boot."""
    flow = getattr(ctx, "flow", None)
    if flow is None:
        return True
    wait = flow.admit_background("adopt")
    if wait > 0.0:
        log.info("deferring adoption of %s under overload "
                 "(retry in %.1fs)", query_id, wait)
        return False
    return True


def try_adopt(ctx, query_id: str) -> bool:
    """CAS-claim an unowned or dead-owner query at boot. True = this
    server now owns it and should resume it. The claim record follows
    :func:`_owned_record`: armed servers stamp a heartbeat immediately
    (a boot-adopted query must read as live to peers before the first
    placer tick), disarmed servers write the legacy epoch record."""
    cur = ctx.config.get(_key(query_id))
    mine = _owned_record(ctx)
    if cur is None:
        try:
            ctx.config.put(_key(query_id), mine)
            return True
        except VersionMismatch:
            _journal_adoption_lost(ctx, query_id)
            return False
    version, raw = cur
    try:
        owner = json.loads(raw)
    except ValueError:
        owner = {"node": "?", "epoch": 0}
    if int(owner.get("epoch", 0)) >= ctx.boot_epoch:
        # owned under an epoch at least as new as ours: a live peer
        log.info("query %s owned by %s (epoch %s); not adopting",
                 query_id, owner.get("node"), owner.get("epoch"))
        return False
    try:
        ctx.config.put(_key(query_id), mine, base_version=version)
        log.info("adopted query %s from %s (epoch %s -> %s)", query_id,
                 owner.get("node"), owner.get("epoch"), ctx.boot_epoch)
        _journal_adoption(ctx, query_id, owner)
        return True
    except VersionMismatch:
        # a racing successor won the claim: journal the stand-down so
        # an operator can see WHY this server skipped the query
        _journal_adoption_lost(ctx, query_id)
        return False


def _journal_adoption(ctx, query_id: str, owner: dict) -> None:
    events = getattr(ctx, "events", None)
    if events is None:
        return
    try:
        events.append(
            "query_adopted",
            f"query {query_id} adopted from {owner.get('node')} "
            f"(epoch {owner.get('epoch')} -> {ctx.boot_epoch})",
            query=query_id, prev_owner=owner.get("node"),
            epoch=ctx.boot_epoch)
    except Exception:  # noqa: BLE001 — journaling must not block boot
        pass


def _journal_adoption_lost(ctx, query_id: str) -> None:
    events = getattr(ctx, "events", None)
    if events is None:
        return
    try:
        winner = assignment(ctx, query_id) or {}
        events.append(
            "adoption_lost",
            f"lost the adoption race for query {query_id} to "
            f"{winner.get('node')} (epoch {winner.get('epoch')}); "
            f"standing down",
            query=query_id, winner=winner.get("node"),
            epoch=ctx.boot_epoch)
    except Exception:  # noqa: BLE001 — journaling must not block boot
        pass


def heartbeat_assignment(ctx, query_id: str) -> bool:
    """CAS-refresh ``hb_ms`` on a record this node owns. Returns False
    (without writing) ONLY when the record is gone or no longer names
    this node as owner — the caller definitively lost ownership (a
    peer live-adopted it, or an in-flight rebalance offered it away),
    must not resurrect the record, and must self-fence the local task.
    Transient CAS contention is NOT ownership loss: after the retries
    the last read still named this node, so the caller keeps running
    and the next tick refreshes the stamp."""
    me = node_name(ctx)
    for _ in range(4):
        cur = ctx.config.get(_key(query_id))
        if cur is None:
            return False
        version, raw = cur
        try:
            rec = json.loads(raw)
        except ValueError:
            return False
        if rec.get("node") != me or rec.get("state", "owned") != "owned":
            return False
        rec["hb_ms"] = now_ms()
        rec["epoch"] = ctx.boot_epoch
        try:
            ctx.config.put(_key(query_id), json.dumps(rec).encode(),
                           base_version=version)
            return True
        except VersionMismatch:
            continue
    log.warning("heartbeat CAS for %s kept losing; still owned at "
                "last read, retrying next tick", query_id)
    return True


def offer_assignment(ctx, query_id: str, target_node: str) -> bool:
    """Rebalance handoff: CAS the record from owned-by-me to
    ``offered`` naming ``target_node``. The offer carries a fresh
    ``hb_ms`` so the target has one full lease to claim it before any
    other node may take it through lease lapse; ``epoch`` drops to 0
    so a plain boot-time ``try_adopt`` can also claim an orphaned
    offer. Caller must have stopped the local task FIRST — after this
    write the query has no live owner until someone adopts."""
    me = node_name(ctx)
    cur = ctx.config.get(_key(query_id))
    if cur is None:
        return False
    version, raw = cur
    try:
        rec = json.loads(raw)
    except ValueError:
        return False
    if rec.get("node") != me:
        return False
    offer = json.dumps({"node": target_node, "epoch": 0,
                        "hb_ms": now_ms(), "state": "offered",
                        "src": me}).encode()
    try:
        ctx.config.put(_key(query_id), offer, base_version=version)
        return True
    except VersionMismatch:
        return False


def try_adopt_live(ctx, query_id: str, lease_ms: int) -> bool:
    """Runtime (placer) adoption: CAS-claim a query whose owner's
    heartbeat lapsed past ``lease_ms``, or that was explicitly
    ``offered`` to this node by a rebalance. Unlike boot-time
    :func:`try_adopt` this ignores epoch ORDER for heartbeated records
    — a dead owner may well have booted after us — but a record with a
    FRESH heartbeat is never taken, whatever its epoch. Legacy records
    without ``hb_ms`` fall back to the boot epoch rule."""
    cur = ctx.config.get(_key(query_id))
    me = node_name(ctx)
    if cur is None:
        try:
            ctx.config.put(_key(query_id), _owned_record(ctx))
            return True
        except VersionMismatch:
            _journal_adoption_lost(ctx, query_id)
            return False
    version, raw = cur
    try:
        rec = json.loads(raw)
    except ValueError:
        rec = {"node": "?", "epoch": 0}
    state = rec.get("state", "owned")
    if rec.get("node") == me and state == "owned":
        return False  # already mine; nothing to adopt
    offered_to_me = state == "offered" and rec.get("node") == me
    if not offered_to_me:
        age = owner_heartbeat_age_ms(rec)
        if age is None:
            # legacy record: epoch liveness, exactly like boot
            if int(rec.get("epoch", 0)) >= ctx.boot_epoch:
                return False
        elif age <= int(lease_ms):
            return False  # owner (or offer target) is live
    try:
        ctx.config.put(_key(query_id), _owned_record(ctx),
                       base_version=version)
        log.info("live-adopted query %s from %s (%s, hb age %sms)",
                 query_id, rec.get("node"), state,
                 owner_heartbeat_age_ms(rec))
        _journal_adoption(ctx, query_id, rec)
        return True
    except VersionMismatch:
        _journal_adoption_lost(ctx, query_id)
        return False


def assignments(ctx) -> dict[str, dict]:
    """query_id -> owner record (admin/introspection)."""
    out = {}
    for key in ctx.config.keys():
        if not key.startswith(_PREFIX):
            continue
        qid = key[len(_PREFIX):]
        a = assignment(ctx, qid)
        if a is not None:
            out[qid] = a
    return out


# ---- self-healing supervision ----------------------------------------------


class QuerySupervisor:
    """Restart dead query tasks from their last snapshot; open a
    breaker on crash loops.

    State machine per query::

        RUNNING --death--> backoff wait --restart ok--> RUNNING
                    |                         |
                    |                    restart failed (counts as a
                    |                    death; next wait doubles)
                    v
        K deaths in W seconds --> FAILED (breaker open) until an
        operator RestartQuery resets the breaker

    Restarts run on ONE dedicated daemon thread; the wait between
    attempts is a bounded ``Event.wait`` so shutdown is prompt. Backoff
    is jittered exponential (seeded RNG — a chaos run replays the same
    waits), doubling per in-window death: with the default ``BREAKER_K``
    the wait peaks at ``BACKOFF_BASE_S * 2**(BREAKER_K - 2)`` (2s)
    because the breaker opens on the next death — ``BACKOFF_CAP_S``
    only binds when ``BREAKER_K``/``BREAKER_W_S`` are tuned up. Every
    scheduling decision journals
    ``query_restart_scheduled`` so an operator can reconstruct the
    timeline. Restarting is background work: it is gated through
    ``adoption_allowed``, so under overload a restart defers exactly
    like boot-time adoption would."""

    BACKOFF_BASE_S = 0.25
    BACKOFF_CAP_S = 30.0   # reachable only if BREAKER_K is raised
    BACKOFF_JITTER = 0.25
    BREAKER_K = 5          # deaths ...
    BREAKER_W_S = 60.0     # ... within this window open the breaker

    def __init__(self, ctx, *, resume_fn=None, seed: int = 0,
                 clock=time.monotonic):
        self.ctx = ctx
        # set by the servicer once handlers exist (resume = relaunch
        # from snapshot, the same path RestartQuery uses)
        self.resume_fn = resume_fn
        self.clock = clock
        self._rng = random.Random(seed)
        # named traced lock (ISSUE 14): the supervisor's pending/
        # breaker tables are a cross-object rendezvous (tasks report
        # deaths, handlers cancel, the restart thread dispatches) —
        # exactly where the lock-order witness earns its keep
        self._lock = locktrace.lock("scheduler.supervisor")
        self._wake = threading.Event()
        self._stopped = False
        # qid -> (due monotonic ts, QueryInfo, attempt#)
        self._pending: dict[str, tuple[float, object, int]] = {}
        # restarts currently executing on the supervisor thread:
        # cancel() waits these out so an operator terminate can never
        # be raced by a resurrect (marked at pending-pop time so there
        # is no unmarked window between pop and attempt)
        self._inflight: set[str] = set()
        self._inflight_cv = threading.Condition(self._lock)
        # qid -> recent death timestamps (breaker window)
        self._deaths: dict[str, deque] = {}
        self._breaker_open: set[str] = set()
        self.restarts = 0  # total successful supervisor restarts
        self._thread: threading.Thread | None = None

    # ---- death intake ------------------------------------------------------

    def note_death(self, info, error: BaseException | None = None) -> None:
        """Called (from the dying task's thread, or by a failed restart)
        when a supervised query died unexpectedly. Schedules a restart
        or opens the crash-loop breaker."""
        qid = info.query_id
        from hstream_tpu.common.errors import NotLeaderError

        if isinstance(error, NotLeaderError):
            # leadership loss is NOT a crash loop (ISSUE 9): this
            # node's store was fenced by a promoted peer, so every
            # restart would die the same way and burn the breaker.
            # Stand down instead — the status write on the fenced
            # store failed, so the replicated record still says
            # RUNNING, and the NEW leader's boot (higher boot epoch
            # over the promoted replica) adopts the query through the
            # normal resume path.
            log.warning(
                "query %s died of leadership loss (%s); standing down "
                "instead of restarting — the promoted leader adopts it",
                qid, error)
            self._journal(
                "replica_fenced",
                f"query {qid} stopped: store leadership lost "
                f"({error}); awaiting adoption by the new leader",
                query=qid, leader_hint=error.leader_hint)
            with self._lock:
                self._forget_locked(qid)
            return
        now = self.clock()
        with self._lock:
            if self._stopped or qid in self._breaker_open:
                return
            window = self._deaths.setdefault(
                qid, deque(maxlen=self.BREAKER_K))
            window.append(now)
            recent = [t for t in window if now - t <= self.BREAKER_W_S]
            opened = len(recent) >= self.BREAKER_K
            if opened:
                self._open_breaker_locked(qid, len(recent))
            else:
                attempt = len(recent)
                delay = self._backoff_locked(attempt)
                self._pending[qid] = (now + delay, info, attempt)
        if opened:
            # the black box (ISSUE 18): capture the postmortem bundle
            # at the breaker edge — OUTSIDE the supervisor lock, since
            # the capture folds task/stats state behind its own locks
            rec = getattr(self.ctx, "flightrec", None)
            if rec is not None:
                rec.snapshot(qid, trigger="crash_loop_open")
            return
        self._journal(
            "query_restart_scheduled",
            f"query {qid} restart #{attempt} in {delay:.2f}s "
            f"({type(error).__name__ if error else 'resume failure'})",
            query=qid, attempt=attempt, delay_s=round(delay, 3),
            error=type(error).__name__ if error else None)
        self._ensure_thread()
        self._wake.set()

    def _backoff_locked(self, attempt: int) -> float:
        return jittered_backoff(
            attempt - 1, base=self.BACKOFF_BASE_S,
            cap=self.BACKOFF_CAP_S, jitter=self.BACKOFF_JITTER,
            rng=self._rng, floor=0.05)

    def _open_breaker_locked(self, qid: str, deaths: int) -> None:
        self._breaker_open.add(qid)
        self._pending.pop(qid, None)
        log.error("crash loop on query %s (%d deaths in %.0fs); "
                  "breaker OPEN, status FAILED", qid, deaths,
                  self.BREAKER_W_S)
        try:
            from hstream_tpu.server.persistence import TaskStatus

            self.ctx.persistence.set_query_status(qid, TaskStatus.FAILED)
        except Exception:  # noqa: BLE001 — breaker must open even if
            pass           # the status write fails
        self._journal(
            "crash_loop_open",
            f"query {qid} crash-looped ({deaths} deaths in "
            f"{self.BREAKER_W_S:.0f}s); FAILED until operator restart",
            query=qid, deaths=deaths, window_s=self.BREAKER_W_S)
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.gauge_set("crash_loop_open", qid, 1.0)
            except Exception:  # noqa: BLE001
                pass

    # ---- operator surface --------------------------------------------------

    def _forget_locked(self, qid: str) -> None:
        self._deaths.pop(qid, None)
        self._breaker_open.discard(qid)
        self._pending.pop(qid, None)

    def _drop_breaker_gauge(self, qid: str) -> None:
        stats = getattr(self.ctx, "stats", None)
        if stats is not None:
            try:
                stats.gauge_drop("crash_loop_open", qid)
            except Exception:  # noqa: BLE001
                pass

    def reset(self, qid: str) -> None:
        """Forget the death history and close the breaker so
        supervision starts fresh. Non-blocking — callers that must not
        race an executing restart use :meth:`cancel`."""
        with self._lock:
            self._forget_locked(qid)
        self._drop_breaker_gauge(qid)

    def cancel(self, qid: str) -> None:
        """Query terminated/deleted/operator-restarted: drop any
        pending restart, wait out one already executing on the
        supervisor thread, and forget the death history — with no
        window in which the restart loop could dispatch a fresh
        attempt. The caller's terminate/restart thus always runs AFTER
        any resurrect, so the task it finds in running_queries is the
        final one."""
        deadline = time.monotonic() + 30.0
        with self._inflight_cv:
            # pop FIRST so a due pending entry cannot dispatch while
            # we wait; re-pop after each wakeup to drop requeues made
            # by the in-flight attempt (corpse / defer paths)
            self._pending.pop(qid, None)
            while (qid in self._inflight
                   and time.monotonic() < deadline):
                self._inflight_cv.wait(timeout=0.25)
                self._pending.pop(qid, None)
            if qid in self._inflight:
                log.warning("cancel(%s): in-flight supervised restart "
                            "did not finish within 30s", qid)
            # same lock hold as the final inflight/pending check: the
            # loop cannot pop-and-dispatch in between
            self._forget_locked(qid)
        self._drop_breaker_gauge(qid)

    def status(self) -> dict:
        with self._lock:
            now = self.clock()
            # pending sorted by query id (ISSUE 9 satellite): admin
            # output and chaos-test assertions must not depend on
            # dict-insertion order
            return {
                "restarts": self.restarts,
                "pending": {qid: {"due_in_s": round(due - now, 3),
                                  "attempt": attempt}
                            for qid, (due, _i, attempt)
                            in sorted(self._pending.items())},
                "breaker_open": sorted(self._breaker_open),
            }

    def shutdown(self) -> None:
        with self._lock:
            self._stopped = True
            self._pending.clear()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)

    # ---- restart thread ----------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None and not self._stopped:
                self._thread = threading.Thread(
                    target=self._restart_loop, name="query-supervisor",
                    daemon=True)
                self._thread.start()

    def _restart_loop(self) -> None:
        while True:
            with self._lock:
                if self._stopped:
                    return
                now = self.clock()
                due = [(qid, info, attempt)
                       for qid, (t, info, attempt)
                       in self._pending.items() if t <= now]
                for qid, _i, _a in due:
                    self._pending.pop(qid, None)
                    self._inflight.add(qid)
                wait = min((t - now for t, _i, _a
                            in self._pending.values()), default=None)
            for qid, info, attempt in due:
                try:
                    self._attempt_restart(qid, info, attempt)
                except Exception as e:  # noqa: BLE001 — this thread is
                    # the singleton supervisor: an escaped bug in one
                    # attempt must count as another death (backoff +
                    # breaker), never kill supervision for every query
                    log.exception("supervised restart attempt for %s "
                                  "blew up", qid)
                    try:
                        self.note_death(info, e)
                    except Exception:  # noqa: BLE001
                        pass
                finally:
                    with self._lock:
                        self._inflight.discard(qid)
                        self._inflight_cv.notify_all()
            # nothing pending: block until a death/requeue wakes us
            # (requeue paths set _wake, so the stale `wait` computed
            # before the attempts above cannot strand a new entry)
            self._wake.wait(timeout=None if wait is None
                            else max(min(wait, 0.5), 0.01))
            self._wake.clear()

    def _attempt_restart(self, qid: str, info, attempt: int) -> None:
        ctx = self.ctx
        from hstream_tpu.server.persistence import TaskStatus

        stale = ctx.running_queries.get(qid)
        if stale is not None:
            if getattr(stale, "error", None) is not None:
                # the dead task is still tearing down (its finally
                # joins reader/persist threads, which can hold it past
                # our backoff) — it pops running_queries last, so retry
                # shortly instead of mistaking the corpse for a live
                # operator-owned task and dropping the restart forever
                with self._lock:
                    if not self._stopped \
                            and qid not in self._breaker_open:
                        self._pending[qid] = (self.clock() + 0.25,
                                              info, attempt)
                self._wake.set()
                return
            return  # an operator beat us to it
        try:
            fresh = ctx.persistence.get_query(qid)
        except Exception:  # noqa: BLE001 — deleted while pending
            return
        if fresh.status in (TaskStatus.TERMINATED, TaskStatus.FAILED):
            return  # terminated (or breaker opened) while pending
        placer = getattr(ctx, "placer", None)
        if placer is not None and placer.armed:
            # live-adoption discipline: while pending, a peer may have
            # adopted this query (our heartbeat lapsed during a long
            # backoff) or a rebalance may have offered it away —
            # restarting anyway would make two live owners
            rec = assignment(ctx, qid)
            if rec is not None and (
                    rec.get("node") != node_name(ctx)
                    or rec.get("state", "owned") != "owned"):
                log.info("dropping restart of %s: record now names "
                         "%s (%s)", qid, rec.get("node"),
                         rec.get("state", "owned"))
                return
        if not adoption_allowed(ctx, qid):
            # overload: defer like boot adoption — same slot, later due
            with self._lock:
                if not self._stopped and qid not in self._breaker_open:
                    self._pending[qid] = (self.clock() + 1.0, info,
                                          attempt)
            self._wake.set()
            return
        resume = self.resume_fn
        if resume is None:
            log.warning("no resume_fn bound; dropping restart of %s",
                        qid)
            return
        try:
            resume(info)
        except Exception as e:  # noqa: BLE001 — a failed restart is
            # another death: backoff doubles, the breaker counts it
            log.exception("supervised restart of %s failed", qid)
            self.note_death(info, e)
            return
        with self._lock:
            # the resumed task may ALREADY have died and opened the
            # breaker (a fault fatal on the first chunk): the breaker
            # writes FAILED under this lock, so checking + writing
            # RUNNING under the same hold totally orders the two —
            # RUNNING can never clobber the breaker's FAILED status
            if qid in self._breaker_open:
                return
            try:
                ctx.persistence.set_query_status(qid, TaskStatus.RUNNING)
            except Exception:  # noqa: BLE001 — the task IS running;
                pass           # status catches up on the next write
            self.restarts += 1
        log.info("supervisor restarted query %s (attempt %d)", qid,
                 attempt)
        stats = getattr(ctx, "stats", None)
        if stats is not None:
            try:
                stats.stream_stat_add("query_restarts", qid)
            except Exception:  # noqa: BLE001 — metrics must not stop
                pass           # the restart

    def _journal(self, kind: str, message: str, **fields) -> None:
        events = getattr(self.ctx, "events", None)
        if events is None:
            return
        try:
            events.append(kind, message, **fields)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass
