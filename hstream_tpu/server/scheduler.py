"""Query->server assignment in the versioned config store (the task-
distribution seed, SURVEY §2.3).

The reference is single-process here too (every query runs in the one
server, Handler.hs:373-375); SURVEY's TPU-native column asks for a
scheduler persisting query placement in cluster metadata. This module
records, for every launched query, which server owns it — keyed
``scheduler/query/<qid>`` in the CAS-versioned config store — and lets
a booting server ADOPT queries whose owner is gone (its recorded boot
epoch predates ours; the boot-epoch CAS in ServerContext makes epochs
total-ordered per store). Adoption is itself a CAS, so two racing
successors cannot both take a query.

Liveness here is epoch-based (single store, one active server at a
time — a successor always boots with a higher epoch). A multi-server
deployment over the replicated store adds heartbeats on the same
records; the CAS adoption path is unchanged.
"""

from __future__ import annotations

import json

from hstream_tpu.common.logger import get_logger
from hstream_tpu.store.versioned import VersionMismatch

log = get_logger("scheduler")

_PREFIX = "scheduler/query/"


def _key(query_id: str) -> str:
    return _PREFIX + query_id


def node_name(ctx) -> str:
    return f"server-{ctx.server_id}@{ctx.host}:{ctx.port}"


def record_assignment(ctx, query_id: str) -> None:
    """Unconditionally claim a query for this server (fresh launches:
    the creating server owns the query)."""
    value = json.dumps({"node": node_name(ctx),
                        "epoch": ctx.boot_epoch}).encode()
    for _ in range(16):
        cur = ctx.config.get(_key(query_id))
        try:
            ctx.config.put(_key(query_id), value,
                           base_version=None if cur is None else cur[0])
            return
        except VersionMismatch:
            continue
    log.warning("assignment write for %s kept losing CAS", query_id)


def drop_assignment(ctx, query_id: str) -> None:
    cur = ctx.config.get(_key(query_id))
    if cur is None:
        return
    try:
        ctx.config.delete(_key(query_id), base_version=cur[0])
    except VersionMismatch:
        pass  # someone re-claimed it; their record stands


def assignment(ctx, query_id: str) -> dict | None:
    cur = ctx.config.get(_key(query_id))
    if cur is None:
        return None
    try:
        return json.loads(cur[1])
    except ValueError:
        return None


def adoption_allowed(ctx, query_id: str) -> bool:
    """Flow-control gate on boot-time adoption: taking over a dead
    owner's queries is background work, so it sheds at DEFER — before
    any user append is refused. A skipped query keeps its stale owner
    record and stays claimable by the next (healthier) boot."""
    flow = getattr(ctx, "flow", None)
    if flow is None:
        return True
    wait = flow.admit_background("adopt")
    if wait > 0.0:
        log.info("deferring adoption of %s under overload "
                 "(retry in %.1fs)", query_id, wait)
        return False
    return True


def try_adopt(ctx, query_id: str) -> bool:
    """CAS-claim an unowned or dead-owner query at boot. True = this
    server now owns it and should resume it."""
    cur = ctx.config.get(_key(query_id))
    mine = json.dumps({"node": node_name(ctx),
                       "epoch": ctx.boot_epoch}).encode()
    if cur is None:
        try:
            ctx.config.put(_key(query_id), mine)
            return True
        except VersionMismatch:
            return False
    version, raw = cur
    try:
        owner = json.loads(raw)
    except ValueError:
        owner = {"node": "?", "epoch": 0}
    if int(owner.get("epoch", 0)) >= ctx.boot_epoch:
        # owned under an epoch at least as new as ours: a live peer
        log.info("query %s owned by %s (epoch %s); not adopting",
                 query_id, owner.get("node"), owner.get("epoch"))
        return False
    try:
        ctx.config.put(_key(query_id), mine, base_version=version)
        log.info("adopted query %s from %s (epoch %s -> %s)", query_id,
                 owner.get("node"), owner.get("epoch"), ctx.boot_epoch)
        _journal_adoption(ctx, query_id, owner)
        return True
    except VersionMismatch:
        return False  # a racing successor won the claim


def _journal_adoption(ctx, query_id: str, owner: dict) -> None:
    events = getattr(ctx, "events", None)
    if events is None:
        return
    try:
        events.append(
            "query_adopted",
            f"query {query_id} adopted from {owner.get('node')} "
            f"(epoch {owner.get('epoch')} -> {ctx.boot_epoch})",
            query=query_id, prev_owner=owner.get("node"),
            epoch=ctx.boot_epoch)
    except Exception:  # noqa: BLE001 — journaling must not block boot
        pass


def assignments(ctx) -> dict[str, dict]:
    """query_id -> owner record (admin/introspection)."""
    out = {}
    for key in ctx.config.keys():
        if not key.startswith(_PREFIX):
            continue
        qid = key[len(_PREFIX):]
        a = assignment(ctx, qid)
        if a is not None:
            out[qid] = a
    return out
