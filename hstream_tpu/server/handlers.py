"""The HStreamApi handler table: the reference's 35 RPCs plus the
framed columnar append pair (AppendColumnar / AppendColumnarStream,
ISSUE 12).

Reference: `handlers` wires the full service (Handler.hs:96-174); stream
CRUD + append at Handler.hs:187-231; `executeQueryHandler` dispatches
one-shot plans incl. SelectView slicing (Handler.hs:259-346);
`executePushQueryHandler` = codegen -> temp sink stream -> persist ->
fork task -> stream Structs to the client (Handler.hs:349-415);
subscription machinery at Handler.hs:420-935. Exceptions map to gRPC
statuses like `defaultExceptionHandle` (Server/Exception.hs:27-50).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Any, Iterable

import grpc
from google.protobuf import empty_pb2, struct_pb2

from hstream_tpu.common import colframe, columnar
from hstream_tpu.common import records as rec
from hstream_tpu.common.errors import (
    HStreamError,
    QueryNotFound,
    ServerError,
    SQLValidateError,
    StreamNotFound,
)
from hstream_tpu.common.idgen import gen_unique
from hstream_tpu.common.logger import (
    REQUEST_ID_KEY,
    current_request_id,
    get_logger,
    request_context,
)
from hstream_tpu.common import tracing
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server import scheduler
from hstream_tpu.server.persistence import (
    QUERY_PUSH,
    QUERY_STREAM,
    QUERY_VIEW,
    ConnectorInfo,
    QueryInfo,
    TaskStatus,
    now_ms,
)
from hstream_tpu.server.subscriptions import RecId
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.server.tasks import (
    QueryTask,
    parse_snapshot_pointer,
    snapshot_key,
    snapshot_slot_key,
    stream_sink,
)
from hstream_tpu.server.views import Materialization, serve_select_view
from hstream_tpu.sql import plans
from hstream_tpu.sql.codegen import explain_text, stream_codegen
from hstream_tpu.store.api import LSN_MIN, Compression, DataBatch
from hstream_tpu.store.checkpoint import CheckpointedReader
from hstream_tpu.store.streams import StreamType

log = get_logger("server")

# LDQuery-lite internal tables (reference hs_ldquery.cpp): plain SQL
# over server metadata through ExecuteQuery
VIRTUAL_TABLES = frozenset({
    "__streams__", "__queries__", "__subscriptions__", "__views__",
    "__connectors__", "__stats__"})


def _abort_hstream(context, e: HStreamError) -> None:
    """Map a typed error to its gRPC status; flow-control refusals also
    carry the retry-after hint, and NOT_LEADER refusals the new
    leader's address, as trailing metadata so clients can back off /
    follow without parsing the message text."""
    md = []
    ra = getattr(e, "retry_after_ms", None)
    if ra is not None:
        md.append(("retry-after-ms", str(int(ra))))
    hint = getattr(e, "leader_hint", None)
    if hint:
        md.append(("x-leader-hint", str(hint)))
    if md:
        context.set_trailing_metadata(tuple(md))
    context.abort(e.grpc_status, str(e) or type(e).__name__)


# RPCs measured into fixed-bucket latency histograms (ISSUE 3): the
# metric names live in the stats registry; the label comes from the
# request (stream for data-plane RPCs, leading keyword for SQL)
_RPC_HISTOGRAMS = {
    "Append": "append_latency_ms",
    "AppendColumnar": "append_latency_ms",
    # AppendColumnarStream observes its own latency inside the handler:
    # _finish_rpc only sees the request ITERATOR, which carries no
    # stream name for the label
    "Fetch": "fetch_latency_ms",
    "ExecuteQuery": "sql_execute_latency_ms",
}

# profile-first discipline (ISSUE 12): the framed append path reports
# where its milliseconds live, per stage, into the stage histograms —
# frame/block validation, flow admission, lane handoff, store wait
APPEND_STAGES = ("append_decode", "append_admit", "append_handoff",
                 "append_store")


def _request_id_from(context) -> str:
    try:
        for k, v in context.invocation_metadata() or ():
            if k == REQUEST_ID_KEY:
                return str(v)
    except Exception:  # noqa: BLE001 — metadata is best-effort
        pass
    return ""


def _trace_from(context, rid: str) -> tuple[str, str]:
    """(trace id, parent span id) of the incoming request: the
    x-trace-id metadata when stamped, else the request id itself — the
    correlation id IS the trace id (ISSUE 13), so a request traced
    nowhere upstream still gets a coherent trace."""
    trace_id, parent = rid, ""
    try:
        for k, v in context.invocation_metadata() or ():
            if k == tracing.TRACE_ID_KEY:
                trace_id = str(v)
            elif k == tracing.PARENT_SPAN_KEY:
                parent = str(v)
    except Exception:  # noqa: BLE001 — metadata is best-effort
        pass
    return trace_id, parent


def _trace_scope(request, result) -> str:
    """The ring a handler span lands in: the query id it touched (or
    created), else the target stream/subscription, else the shared
    _rpc scope."""
    for obj in (result, request):
        for attr in ("id", "stream_name", "subscription_id"):
            v = getattr(obj, attr, "")
            if isinstance(v, str) and v:
                return v
    return "_rpc"


def _producer_from(context) -> tuple[str, int] | None:
    """SQL INSERT idempotence stamp: Append carries the producer on the
    request proto; ExecuteQuery carries it as `x-producer-id` /
    `x-producer-seq` metadata (the statement text stays portable). A
    malformed seq on a stamped request is refused INVALID_ARGUMENT —
    silently running the INSERT unstamped would break the exactly-once
    contract the client thinks it has (its retry would double-append)."""
    pid, seq, bad = "", None, None
    try:
        for k, v in context.invocation_metadata() or ():
            if k == "x-producer-id":
                pid = str(v)
            elif k == "x-producer-seq":
                try:
                    seq = int(v)
                except ValueError:
                    bad = str(v)
    except Exception:  # noqa: BLE001 — metadata is best-effort
        return None
    if pid and bad is not None:
        raise SQLValidateError(
            f"malformed x-producer-seq {bad!r} on a stamped request "
            f"(producer {pid!r}): must be a base-10 integer")
    return (pid, seq) if pid and seq is not None else None


def _dedup_append(ctx, logid: int, payloads, compression,
                  producer_id: str, producer_seq: int
                  ) -> tuple[int, int, bool]:
    """Producer-stamped append against either store shape: the
    replicated store runs the lookup+log+apply in ONE critical section
    (and the stamp rides the op-log so every replica derives the same
    window); a single-node store gets the same atomicity from the
    context-level dedup lock. Returns (lsn, n_records, was_dup)."""
    store = ctx.store
    if hasattr(store, "append_batch_dedup"):
        return store.append_batch_dedup(
            logid, payloads, compression,
            producer_id=producer_id, producer_seq=producer_seq)
    from hstream_tpu.store import dedup

    return dedup.guarded_append(store, ctx.dedup_lock, logid, payloads,
                                compression, producer_id, producer_seq)


def _rpc_hist_label(rpc: str, request) -> str:
    if rpc == "ExecuteQuery":
        txt = (getattr(request, "stmt_text", "") or "").lstrip()
        return txt.split(None, 1)[0].lower() if txt else ""
    return (getattr(request, "stream_name", "")
            or getattr(request, "subscription_id", ""))


def _finish_rpc(self, fn_name: str, request, rid: str,
                t0: float) -> None:
    """Post-RPC bookkeeping shared by every unary handler: latency
    histogram + the correlated slow-request log line."""
    dur_ms = (time.perf_counter() - t0) * 1e3
    metric = _RPC_HISTOGRAMS.get(fn_name)
    if metric is not None:
        try:
            self.ctx.stats.observe(metric,
                                   _rpc_hist_label(fn_name, request),
                                   dur_ms)
        except Exception:  # noqa: BLE001 — metrics must not fail RPCs
            pass
    slow_ms = getattr(self.ctx, "slow_request_ms", None)
    if slow_ms is not None and dur_ms >= slow_ms:
        log.warning("slow request: %s took %.1fms (threshold %.0fms)%s",
                    fn_name, dur_ms, slow_ms,
                    "" if rid else " [no request id]")


def unary(fn):
    @functools.wraps(fn)
    def wrapped(self, request, context):
        rid = _request_id_from(context)
        t0 = time.perf_counter()
        # trace context (ISSUE 13): one branch when tracing is
        # disarmed; when the trace id samples in, the handler body runs
        # under a span scope so nested probes (append stages, delivery)
        # parent correctly, and the RPC span lands on completion
        tr = self.ctx.tracing
        span = None  # (trace_id, span_id, parent_id)
        if tr.active:
            trace_id, parent = _trace_from(context, rid)
            if tr.sampled(trace_id):
                span = (trace_id, tracing.new_span_id(), parent)
        result = None
        with request_context(rid):
            try:
                if FAULTS.active:  # chaos: fail/delay at handler entry
                    FAULTS.point("rpc.handler")
                if span is None:
                    result = fn(self, request, context)
                else:
                    with tracing.span_scope(span[0], span[1]):
                        result = fn(self, request, context)
                return result
            except HStreamError as e:
                _abort_hstream(context, e)
            except grpc.RpcError:
                raise
            except Exception as e:  # noqa: BLE001 — boundary mapping
                log.exception("handler %s failed", fn.__name__)
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")
            finally:
                _finish_rpc(self, fn.__name__, request, rid, t0)
                if span is not None:
                    dur_ms = (time.perf_counter() - t0) * 1e3
                    try:
                        tr.record_span(
                            _trace_scope(request, result), "rpc",
                            trace_id=span[0], span_id=span[1],
                            parent_id=span[2],
                            t0_ms=time.time() * 1e3 - dur_ms,
                            dur_ms=dur_ms, rpc=fn.__name__,
                            ok=result is not None)
                    except Exception:  # noqa: BLE001 — span plumbing
                        pass           # must never fail the RPC

    return wrapped


def streaming(fn):
    @functools.wraps(fn)
    def wrapped(self, request, context):
        with request_context(_request_id_from(context)):
            try:
                yield from fn(self, request, context)
            except HStreamError as e:
                _abort_hstream(context, e)
            except grpc.RpcError:
                raise
            except Exception as e:  # noqa: BLE001
                log.exception("handler %s failed", fn.__name__)
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

    return wrapped


def _struct(row: dict[str, Any]) -> struct_pb2.Struct:
    return rec.dict_to_struct(row)


def _reject_virtual_name(kind: str, name: str) -> None:
    """CREATE STREAM/VIEW names must not shadow the reserved virtual
    tables: a user view named __streams__ would be unreachable (SELECT
    routes virtual names to metadata) and a stream of that name would
    silently split reads between the two."""
    if name in VIRTUAL_TABLES:
        raise ServerError(
            f"{kind} name {name!r} collides with a reserved virtual "
            f"table; pick another name")


class HStreamApiServicer:
    def __init__(self, ctx: ServerContext):
        self.ctx = ctx
        # self-healing: the supervisor restarts dead tasks through the
        # same snapshot-resume path RestartQuery uses
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            sup.resume_fn = self._resume_query
        # the placer adopts a dead peer's queries through the SAME
        # snapshot-resume path (live failover adoption, ISSUE 17b)
        placer = getattr(ctx, "placer", None)
        if placer is not None:
            placer.resume_fn = self._resume_query

    # ---- misc ---------------------------------------------------------------

    @unary
    def Echo(self, request, context):
        return pb.EchoResponse(msg=request.msg)

    # ---- streams ------------------------------------------------------------

    @unary
    def CreateStream(self, request, context):
        _reject_virtual_name("stream", request.stream_name)
        self.ctx.streams.create_stream(
            request.stream_name,
            replication_factor=max(request.replication_factor, 1))
        return request

    @unary
    def DeleteStream(self, request, context):
        self.ctx.streams.delete_stream(request.stream_name)
        return empty_pb2.Empty()

    @unary
    def ListStreams(self, request, context):
        out = pb.ListStreamsResponse()
        for name in self.ctx.streams.find_streams():
            meta = self.ctx.streams.stream_meta(name)
            out.streams.append(pb.Stream(
                stream_name=name,
                replication_factor=meta.get("replication_factor", 1)))
        return out

    @unary
    def Append(self, request, context):
        ctx = self.ctx
        logid = ctx.streams.get_logid(request.stream_name)
        now = now_ms()
        payloads = []
        nbytes = 0
        for r in request.records:
            # ISSUE 12 satellite: the batch default timestamp is
            # stamped once (only into headers that carry none), and
            # large payloads are spliced around a header-only
            # serialize instead of re-walked whole (records.py)
            data = rec.record_bytes(r, default_ts=now)
            payloads.append(data)
            nbytes += len(data)
        if not payloads:
            raise ServerError("empty append")
        # flow control: one branch when no quota is set and the overload
        # detector is quiet (ctx.flow.active is a plain attribute)
        if ctx.flow.active:
            ctx.flow.admit_append(request.stream_name, len(payloads),
                                  nbytes)
        compression = getattr(ctx, "append_compression", Compression.NONE)
        try:
            if request.producer_id:
                # idempotent append (ISSUE 9): the (producer_id, seq)
                # stamp rides the replicated entry, so a retry — even
                # one that straddles a leader failover — is answered
                # with the ORIGINAL record ids on every replica
                lsn, n, dup = _dedup_append(
                    ctx, logid, payloads, compression,
                    request.producer_id, request.producer_seq)
            else:
                lsn, n, dup = ctx.store.append_batch(
                    logid, payloads, compression), len(payloads), False
        except Exception:
            # admitted but not stored (store I/O, replication broken,
            # seq behind the dedup window): the failure counter
            # separates this from quota refusals
            ctx.stats.stream_stat_add("append_failed",
                                      request.stream_name)
            raise
        if dup:
            ctx.stats.stream_stat_add("append_deduped",
                                      request.stream_name)
        else:
            ctx.stats.note_append(request.stream_name, len(payloads),
                                  nbytes)
        out = pb.AppendResponse(stream_name=request.stream_name,
                                duplicate=dup)
        for i in range(n):
            out.record_ids.append(pb.RecordId(batch_id=lsn, batch_index=i))
        return out

    # ---- framed columnar append (ISSUE 12 tentpole) -------------------------

    def _observe_append_stage(self, stage: str, seconds: float) -> None:
        try:
            self.ctx.stats.observe("stage_latency_ms", stage,
                                   seconds * 1e3)
        except Exception:  # noqa: BLE001 — metrics must not fail RPCs
            pass

    def _trace_stage_span(self, scope: str, stage: str,
                          dur_s: float) -> None:
        """One child span under the active sampled request (no-op when
        tracing is disarmed or the request wasn't sampled)."""
        tr = self.ctx.tracing
        if not tr.active:
            return
        sctx = tracing.current_span()
        if sctx is None:
            return
        dur_ms = dur_s * 1e3
        try:
            tr.record_span(scope, stage, trace_id=sctx[0],
                           span_id=tracing.new_span_id(),
                           parent_id=sctx[1],
                           t0_ms=time.time() * 1e3 - dur_ms,
                           dur_ms=dur_ms)
        except Exception:  # noqa: BLE001 — span plumbing must never
            pass           # fail the RPC

    def _bind_task_trace(self, task, scope: str) -> None:
        """Attach a newly launched query task to the creating request's
        sampled trace: its pipeline-stage timings then land as spans in
        the query's ring, parented on the handler span."""
        tr = self.ctx.tracing
        sctx = tracing.current_span()
        if tr.active and sctx is not None:
            task.tracer.bind_trace(tr, scope=scope, trace_id=sctx[0],
                                   parent_id=sctx[1])

    # contract: dispatches<=0 fetches<=0
    def _append_blocks(self, stream: str, blocks
                       ) -> tuple["object", int, int, int]:
        """Validate-ALL-then-submit for one request's framed blocks:
        every frame is opened and its columnar block bounds-checked
        BEFORE any byte is handed to the append front, and the whole
        request goes to the store as ONE batch (like the protobuf
        Append path) — so neither a bad frame NOR a store failure can
        partially ingest a request. Returns (future, n_blocks, rows,
        nbytes); the future resolves to the request's shared LSN
        (blocks are addressed (lsn, block_index))."""
        ctx = self.ctx
        logid = ctx.streams.get_logid(stream)
        if not blocks:
            raise ServerError("empty append")
        t0 = time.perf_counter()
        wraps: list[bytes] = []
        rows = 0
        nbytes = 0
        for b in blocks:
            payload, n, last_ts = colframe.open_block(b)
            # the store sees NORMAL columnar records: one header
            # serialize + one memcpy each (no protobuf round-trip),
            # read side unchanged
            wraps.append(rec.wrap_raw_record(payload, last_ts))
            rows += n
            nbytes += len(b)
        t1 = time.perf_counter()
        if ctx.flow.active:
            ctx.flow.admit_append(stream, rows, nbytes)
        t2 = time.perf_counter()
        # honor the operator's storage-compression knob like the
        # protobuf Append path does
        compression = getattr(ctx, "append_compression",
                              Compression.NONE)
        fut = ctx.append_front.submit(logid, wraps, compression)
        t3 = time.perf_counter()
        self._observe_append_stage("append_decode", t1 - t0)
        self._observe_append_stage("append_admit", t2 - t1)
        self._observe_append_stage("append_handoff", t3 - t2)
        if ctx.tracing.active:
            self._trace_stage_span(stream, "append_decode", t1 - t0)
            self._trace_stage_span(stream, "append_admit", t2 - t1)
            self._trace_stage_span(stream, "append_handoff", t3 - t2)
        return fut, len(wraps), rows, nbytes

    def _settle_appends(self, stream: str, entries: list
                        ) -> tuple[list[tuple[int, int]], int, int, int,
                                   BaseException | None]:
        """Wait out EVERY submitted request batch (never abandon a
        future — an unretrieved exception is log noise and an
        uncounted store mutation): returns (record ids as (lsn, idx),
        landed_blocks, landed_rows, landed_bytes, first_error).
        Failures count append_failed."""
        t0 = time.perf_counter()
        ids: list[tuple[int, int]] = []
        blocks = rows = nbytes = 0
        err: BaseException | None = None
        for fut, nblocks, r, nb in entries:
            try:
                lsn = fut.result(timeout=60)
            except Exception as e:  # noqa: BLE001 — surfaced after
                # every sibling batch settles
                self.ctx.stats.stream_stat_add("append_failed", stream)
                if err is None:
                    err = e
            else:
                ids.extend((lsn, i) for i in range(nblocks))
                blocks += nblocks
                rows += r
                nbytes += nb
        dt = time.perf_counter() - t0
        self._observe_append_stage("append_store", dt)
        if self.ctx.tracing.active:
            self._trace_stage_span(stream, "append_store", dt)
        return ids, blocks, rows, nbytes, err

    def _note_landed(self, stream: str, blocks: int, rows: int,
                     nbytes: int) -> None:
        """Metrics for blocks that durably landed — recorded even when
        the RPC itself aborts, so counters never undercount the store."""
        if blocks:
            self.ctx.stats.note_append(stream, blocks, nbytes)
            self.ctx.stats.stream_stat_add("append_columnar_rows",
                                           stream, rows)

    @unary
    def AppendColumnar(self, request, context):
        """Framed columnar append: bounds-check + handoff, no
        per-record protobuf work (the staging layout the encode
        workers consume arrives AS the wire format)."""
        stream = request.stream_name
        entry = self._append_blocks(stream, request.blocks)
        ids, blocks, rows, nbytes, err = self._settle_appends(stream,
                                                              [entry])
        self._note_landed(stream, blocks, rows, nbytes)
        if err is not None:
            raise err
        out = pb.AppendColumnarResponse(stream_name=stream, rows=rows)
        for lsn, idx in ids:
            out.record_ids.append(pb.RecordId(batch_id=lsn,
                                              batch_index=idx))
        return out

    @unary
    def AppendColumnarStream(self, request_iterator, context):
        """Client-streaming framed append: N micro-batches amortize ONE
        RPC. Each request message is validated atomically and its
        blocks submitted to the append front, overlapping the next
        message's receive with the previous blocks' store wait; the
        single response carries every block's record id in submission
        order. A bad frame aborts the call — its own request's blocks
        never land; EARLIER requests were already durably appended
        (their rows stay counted, and their ids would have been acked
        had the stream completed)."""
        ctx = self.ctx
        t_rpc = time.perf_counter()
        stream = None
        pending: list = []    # one (future, blocks, rows, bytes)/request
        ids: list[tuple[int, int]] = []
        landed = [0, 0, 0]           # blocks, rows, bytes

        def settle(limit: int) -> None:
            while len(pending) > limit:
                got, b, r, nb, err = self._settle_appends(
                    stream, [pending.pop(0)])
                ids.extend(got)
                landed[0] += b
                landed[1] += r
                landed[2] += nb
                if err is not None:
                    raise err

        try:
            for req in request_iterator:
                if stream is None:
                    stream = req.stream_name
                    if not stream:
                        raise ServerError(
                            "first AppendColumnarStream request must "
                            "name the stream")
                elif req.stream_name and req.stream_name != stream:
                    raise ServerError(
                        "AppendColumnarStream carries ONE stream per "
                        f"call; got {req.stream_name!r} after "
                        f"{stream!r}")
                pending.append(self._append_blocks(stream, req.blocks))
                # bound in-flight memory without stalling the pipeline
                settle(128)
            if stream is None:
                raise ServerError("empty append stream")
            settle(0)
        finally:
            # aborting or not, every submitted request settles: what
            # durably landed is counted, no future is abandoned
            if pending and stream is not None:
                got, b, r, nb, _err = self._settle_appends(stream,
                                                           pending)
                ids.extend(got)
                landed[0] += b
                landed[1] += r
                landed[2] += nb
            if stream is not None:
                self._note_landed(stream, *landed)
        try:
            # whole-call latency under the STREAM label (see the
            # _RPC_HISTOGRAMS note)
            ctx.stats.observe("append_latency_ms", stream,
                              (time.perf_counter() - t_rpc) * 1e3)
        except Exception:  # noqa: BLE001 — metrics must not fail RPCs
            pass
        out = pb.AppendColumnarResponse(stream_name=stream,
                                        rows=landed[1])
        for lsn, idx in ids:
            out.record_ids.append(pb.RecordId(batch_id=lsn,
                                              batch_index=idx))
        return out

    @unary
    def CreateQueryStream(self, request, context):
        sql = request.query_statement
        plan = stream_codegen(sql)
        if isinstance(plan, plans.SelectPlan):
            select = plan
        elif isinstance(plan, plans.CreateBySelectPlan):
            select = plan.select
        else:
            raise ServerError("CreateQueryStream needs a SELECT statement")
        name = request.query_stream.stream_name
        _reject_virtual_name("stream", name)
        self.ctx.streams.create_stream(
            name,
            replication_factor=max(request.query_stream.replication_factor,
                                   1))
        info = self._launch_query(select, sql, QUERY_STREAM, sink_stream=name)
        return pb.CreateQueryStreamResponse(
            query_stream=request.query_stream,
            stream_query=self._query_pb(info))

    # ---- SQL ----------------------------------------------------------------

    @streaming
    def ExecutePushQuery(self, request, context):
        """codegen -> temp sink stream -> fork task -> stream Structs
        (Handler.hs:349-415)."""
        ctx = self.ctx
        plan = stream_codegen(request.query_text)
        if not isinstance(plan, plans.SelectPlan) or not plan.emit_changes:
            raise ServerError(
                "ExecutePushQuery expects SELECT ... EMIT CHANGES")
        if not ctx.streams.stream_exists(plan.source):
            raise StreamNotFound(plan.source)
        query_id = f"q{gen_unique()}"
        sink_name = query_id
        ctx.streams.create_stream(sink_name, stream_type=StreamType.TEMP)
        info = self._launch_query(plan, request.query_text, QUERY_PUSH,
                                  sink_stream=sink_name,
                                  sink_type=StreamType.TEMP,
                                  query_id=query_id)
        task = ctx.running_queries.get(query_id)

        def cleanup():
            # handlePushQueryCanceled (Handler.hs:376-377)
            if task is not None:
                task.stop()
            try:
                ctx.persistence.set_query_status(query_id,
                                                 TaskStatus.TERMINATED)
            except Exception:
                pass

        context.add_callback(cleanup)
        sink_logid = ctx.streams.get_logid(sink_name, StreamType.TEMP)
        reader = ctx.store.new_reader()
        reader.set_timeout(100)
        reader.start_reading(sink_logid, LSN_MIN)
        while context.is_active():
            try:
                info_now = ctx.persistence.get_query(query_id)
            except QueryNotFound:
                break
            if info_now.status in (TaskStatus.TERMINATED,
                                   TaskStatus.CONNECTION_ABORT):
                break
            for item in reader.read(256):
                if not isinstance(item, DataBatch):
                    continue
                for payload in item.payloads:
                    record = rec.parse_record(payload)
                    if record.header.flag == rec.pb.RECORD_FLAG_RAW:
                        # vectorized sink emission: one columnar record
                        # per changelog batch (tasks.stream_sink)
                        for row in (columnar.payload_rows(record.payload)
                                    or ()):
                            yield rec.dict_to_struct(row)
                        continue
                    s = rec.payload_to_struct(record)
                    if s is not None:
                        yield s

    @unary
    def ExecuteQuery(self, request, context):
        plan = stream_codegen(request.stmt_text)
        rows = self._execute_plan(plan, request.stmt_text,
                                  producer=_producer_from(context))
        out = pb.CommandQueryResponse()
        for row in rows:
            out.result_set.append(_struct(row))
        return out

    # ---- query lifecycle ----------------------------------------------------

    @unary
    def CreateQuery(self, request, context):
        plan = stream_codegen(request.query_text)
        if not isinstance(plan, plans.SelectPlan) or not plan.emit_changes:
            raise ServerError("CreateQuery expects SELECT ... EMIT CHANGES")
        query_id = request.id or f"q{gen_unique()}"
        sink_name = query_id
        # request.id is user-supplied and becomes the sink STREAM name
        _reject_virtual_name("stream", sink_name)
        self.ctx.streams.create_stream(sink_name,
                                       stream_type=StreamType.TEMP)
        info = self._launch_query(plan, request.query_text, QUERY_PUSH,
                                  sink_stream=sink_name,
                                  sink_type=StreamType.TEMP,
                                  query_id=query_id)
        return self._query_pb(info)

    @unary
    def ListQueries(self, request, context):
        out = pb.ListQueriesResponse()
        for info in self.ctx.persistence.get_queries():
            if info.query_type == QUERY_VIEW:
                continue
            out.queries.append(self._query_pb(info))
        return out

    @unary
    def GetQuery(self, request, context):
        return self._query_pb(self.ctx.persistence.get_query(request.id))

    @unary
    def TerminateQueries(self, request, context):
        ids = ([q.query_id for q in self.ctx.persistence.get_queries()
                if q.query_type != QUERY_VIEW]
               if request.all else list(request.query_ids))
        done = []
        for qid in ids:
            try:
                self._terminate_query(qid)
                done.append(qid)
            except QueryNotFound:
                if not request.all:
                    raise
        return pb.TerminateQueriesResponse(query_ids=done)

    @unary
    def DeleteQuery(self, request, context):
        info = self.ctx.persistence.get_query(request.id)
        self._terminate_query(request.id)
        self.ctx.persistence.remove_query(request.id)
        self._remove_query_state(request.id)
        if info.query_type == QUERY_PUSH and info.sink:
            try:
                self.ctx.streams.delete_stream(info.sink, StreamType.TEMP)
            except StreamNotFound:
                pass
        return empty_pb2.Empty()

    @unary
    def RestartQuery(self, request, context):
        """The reference leaves this unimplemented
        (Handler/Query.hs:152-160); here a terminated query resumes from
        its snapshotted operator state + paired read checkpoints."""
        ctx = self.ctx
        info = ctx.persistence.get_query(request.id)
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            # operator intent overrides the crash-loop verdict: close
            # the breaker and forget the death history. cancel (not
            # reset) so an executing supervised restart is waited out
            # first — otherwise both could pass the running check and
            # double-start the query
            sup.cancel(request.id)
        if request.id in ctx.running_queries:
            raise ServerError(f"query {request.id} is already running")
        self._resume_query(info)
        ctx.persistence.set_query_status(info.query_id, TaskStatus.RUNNING)
        try:
            ctx.events.append(
                "query_restarted",
                f"query {info.query_id} restarted by operator",
                query=info.query_id,
                request_id=current_request_id() or None)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass
        return empty_pb2.Empty()

    def _resume_query(self, info: QueryInfo) -> None:
        ctx = self.ctx
        plan = stream_codegen(info.sql)
        if info.query_type == QUERY_VIEW:
            self._start_view_task(info, plan)
        else:
            stype = (StreamType.TEMP if info.query_type == QUERY_PUSH
                     else StreamType.STREAM)
            sink = stream_sink(ctx, info.sink, stype)
            task = QueryTask(ctx, info, plan
                             if isinstance(plan, plans.SelectPlan)
                             else plan.select, sink)
            ctx.running_queries[info.query_id] = task
            task.start()

    def resume_persisted(self) -> None:
        """Boot-time resume: relaunch every query that was RUNNING when
        the server last stopped (the reference resumes query definitions
        from ZK metadata, Persistence.hs:197-256; here operator state
        resumes too via the snapshot blobs)."""
        ctx = self.ctx
        for info in ctx.persistence.get_queries():
            if info.status not in (TaskStatus.RUNNING, TaskStatus.CREATED):
                continue
            if info.query_id in ctx.running_queries:
                continue
            # scheduler seed (SURVEY §2.3 task distribution): only
            # adopt queries whose recorded owner is gone — its boot
            # epoch predates ours; the claim is a CAS, so two racing
            # successors cannot both take one query. Adoption is
            # background work: under overload shedding it defers (the
            # records stay claimable for a later, healthier boot).
            if not scheduler.adoption_allowed(ctx, info.query_id):
                continue
            # armed placer: respect a LIVE peer's heartbeat lease even
            # at boot — a restarting node must not snatch back queries
            # a survivor adopted and is actively heartbeating (its
            # higher boot epoch would win the pure-epoch rule below)
            if ctx.placer.armed:
                rec = scheduler.assignment(ctx, info.query_id)
                if (rec is not None
                        and rec.get("node") != scheduler.node_name(ctx)
                        and scheduler.owner_live(
                            rec, ctx.heartbeat_lease_ms)):
                    continue
            if not scheduler.try_adopt(ctx, info.query_id):
                continue
            try:
                self._resume_query(info)
            except Exception:  # noqa: BLE001 — one bad query must not
                # block boot; its status records the failure
                log.exception("resume of query %s failed", info.query_id)
                try:
                    ctx.persistence.set_query_status(
                        info.query_id, TaskStatus.CONNECTION_ABORT)
                except Exception:
                    pass

    # ---- subscriptions ------------------------------------------------------

    @unary
    def CreateSubscription(self, request, context):
        if not self.ctx.streams.stream_exists(request.stream_name):
            raise StreamNotFound(request.stream_name)
        self.ctx.subscriptions.create(self.ctx, request)
        return request

    @unary
    def Subscribe(self, request, context):
        self.ctx.subscriptions.get(request.subscription_id)
        return pb.SubscribeResponse(
            subscription_id=request.subscription_id)

    @unary
    def ListSubscriptions(self, request, context):
        out = pb.ListSubscriptionsResponse()
        for rt in self.ctx.subscriptions.list():
            out.subscription.append(rt.meta)
        return out

    @unary
    def CheckSubscriptionExist(self, request, context):
        return pb.CheckSubscriptionExistResponse(
            exists=self.ctx.subscriptions.exists(request.subscription_id))

    @unary
    def DeleteSubscription(self, request, context):
        self.ctx.subscriptions.remove(request.subscription_id)
        self.ctx.ckp_store.remove(
            f"subscription-{request.subscription_id}")
        return empty_pb2.Empty()

    @unary
    def SendConsumerHeartbeat(self, request, context):
        # liveness no-op, like the reference (Handler.hs:610-617)
        return pb.ConsumerHeartbeatResponse(
            subscription_id=request.subscription_id)

    @unary
    def Fetch(self, request, context):
        rt = self.ctx.subscriptions.get(request.subscription_id)
        flow = self.ctx.flow
        if flow.active:
            # read quota: gate the call, charge the actual count after
            # (debt-based — sustained rate converges on the quota)
            flow.admit_read(rt.meta.stream_name)
        got = rt.fetch(timeout_ms=int(request.timeout_ms),
                       max_size=int(request.max_size) or 256)
        if flow.active and got:
            flow.charge_read(rt.meta.stream_name, len(got))
        out = pb.FetchResponse()
        for rid, payload in got:
            out.received_records.append(pb.ReceivedRecord(
                record_id=pb.RecordId(batch_id=rid.lsn,
                                      batch_index=rid.idx),
                record=payload))
        # read accounting (note_read) moved into SubscriptionRuntime
        # .fetch so the streaming dispatcher's drains count too
        return out

    @unary
    def Acknowledge(self, request, context):
        rt = self.ctx.subscriptions.get(request.subscription_id)
        rt.ack([RecId(a.batch_id, a.batch_index) for a in request.ack_ids])
        return empty_pb2.Empty()

    @streaming
    def StreamingFetch(self, request_iterator, context):
        """BiDi fetch with consumer round-robin (Handler.hs:720-935):
        the first request registers the consumer, subsequent requests
        carry acks."""
        try:
            first = next(iter(request_iterator))
        except StopIteration:
            return
        rt = self.ctx.subscriptions.get(first.subscription_id)
        consumer = rt.register_consumer(first.consumer_name or "consumer")
        if first.ack_ids:
            rt.ack([RecId(a.batch_id, a.batch_index)
                    for a in first.ack_ids], consumer=consumer)

        def drain_acks():
            try:
                for req in request_iterator:
                    if req.ack_ids:
                        # acks refill this consumer's delivery credits
                        rt.ack([RecId(a.batch_id, a.batch_index)
                                for a in req.ack_ids], consumer=consumer)
            except Exception:
                pass
            finally:
                consumer.alive = False

        t = threading.Thread(target=drain_acks, daemon=True)
        t.start()
        inflight = None  # batch taken from the queue but not yet yielded
        try:
            import queue as _q

            while context.is_active() and consumer.alive:
                try:
                    inflight = consumer.queue.get(timeout=0.1)
                except _q.Empty:
                    continue
                resp = pb.StreamingFetchResponse()
                for rid, payload in inflight:
                    resp.received_records.append(pb.ReceivedRecord(
                        record_id=pb.RecordId(batch_id=rid.lsn,
                                              batch_index=rid.idx),
                        record=payload))
                yield resp
                inflight = None
        finally:
            # a batch obtained but not successfully yielded was noted in
            # the AckWindow — hand it back for redelivery, else the ack
            # lower bound stalls forever
            if inflight is not None:
                rt.requeue(inflight)
            rt.unregister_consumer(consumer)

    # ---- connectors ---------------------------------------------------------

    @unary
    def CreateSinkConnector(self, request, context):
        plan = stream_codegen(request.config)
        if not isinstance(plan, plans.CreateSinkConnectorPlan):
            raise ServerError(
                "config must be a CREATE SINK CONNECTOR statement")
        cid = request.id or plan.name
        info = self._create_connector(cid, request.config, plan)
        return self._connector_pb(info)

    @unary
    def ListConnectors(self, request, context):
        out = pb.ListConnectorsResponse()
        for info in self.ctx.persistence.get_connectors():
            out.connectors.append(self._connector_pb(info))
        return out

    @unary
    def GetConnector(self, request, context):
        return self._connector_pb(
            self.ctx.persistence.get_connector(request.id))

    @unary
    def DeleteConnector(self, request, context):
        self._terminate_connector(request.id)
        self.ctx.persistence.remove_connector(request.id)
        self.ctx.ckp_store.remove(f"connector-{request.id}")
        return empty_pb2.Empty()

    @unary
    def RestartConnector(self, request, context):
        ctx = self.ctx
        info = ctx.persistence.get_connector(request.id)
        if request.id in ctx.running_connectors:
            raise ServerError(f"connector {request.id} is already running")
        plan = stream_codegen(info.sql)
        self._start_connector_task(info, plan)
        return empty_pb2.Empty()

    @unary
    def TerminateConnector(self, request, context):
        self._terminate_connector(request.id)
        return empty_pb2.Empty()

    # ---- views --------------------------------------------------------------

    @unary
    def CreateView(self, request, context):
        plan = stream_codegen(request.sql)
        if not isinstance(plan, plans.CreateViewPlan):
            raise ServerError("sql must be CREATE VIEW ... AS SELECT ...")
        info = self._create_view(plan, request.sql)
        return self._view_pb(info)

    @unary
    def ListViews(self, request, context):
        out = pb.ListViewsResponse()
        for info in self.ctx.persistence.get_queries():
            if info.query_type == QUERY_VIEW:
                out.views.append(self._view_pb(info))
        return out

    @unary
    def GetView(self, request, context):
        info = self.ctx.persistence.get_query(f"view-{request.view_id}")
        return self._view_pb(info)

    @unary
    def DeleteView(self, request, context):
        self._drop_view(request.view_id)
        return empty_pb2.Empty()

    # ---- cluster ------------------------------------------------------------

    @unary
    def ListNodes(self, request, context):
        return pb.ListNodesResponse(nodes=[self._node_pb()])

    @unary
    def GetNode(self, request, context):
        if request.id != self.ctx.server_id:
            raise ServerError(f"unknown node {request.id}")
        return self._node_pb()

    @unary
    def GetQueryTrace(self, request, context):
        """Per-stage timing summary of a RUNNING query (decode /
        key_encode / step / emit / snapshot rings — SURVEY §5.1), plus
        the overlapped-ingest pipeline's stage occupancy when the query
        runs the staged columnar path."""
        task = self.ctx.running_queries.get(request.id)
        if task is None:
            raise QueryNotFound(request.id)
        out = task.tracer.summary()
        pipe = getattr(task, "_pipe", None)
        if pipe is not None:
            out["pipeline"] = pipe.stats()
        return rec.dict_to_struct(out)

    @unary
    def SendAdminCommand(self, request, context):
        """Store-ops verbs (reference hstore-admin trim/findTime/
        offsets + maintenance introspection, admin/app/cli.hs:56-69):
        one JSON-in/JSON-out RPC backing `python -m hstream_tpu.admin`.
        """
        import json as _json

        ctx = self.ctx
        args = rec.struct_to_dict(request.args)
        cmd = request.command

        def stream_logid(name: str) -> int:
            return ctx.streams.get_logid(name)

        if cmd == "trim":
            logid = stream_logid(args["stream"])
            ctx.store.trim(logid, int(args["lsn"]))
            out = {"stream": args["stream"],
                   "trim_point": ctx.store.trim_point(logid)}
        elif cmd == "find-time":
            logid = stream_logid(args["stream"])
            out = {"stream": args["stream"],
                   "lsn": ctx.store.find_time(logid,
                                              int(args["ts_ms"]))}
        elif cmd == "offsets":
            logid = stream_logid(args["stream"])
            out = {"stream": args["stream"], "logid": logid,
                   "trim_point": ctx.store.trim_point(logid),
                   "tail_lsn": ctx.store.tail_lsn(logid),
                   "is_empty": ctx.store.is_log_empty(logid)}
        elif cmd == "sub-lag":
            rt = ctx.subscriptions.get(args["subscription"])
            tail = ctx.store.tail_lsn(rt.logid)
            committed = rt.committed_lsn
            out = {"subscription": args["subscription"],
                   "stream": rt.meta.stream_name,
                   "committed_lsn": committed, "tail_lsn": tail,
                   "lag": max(0, tail - committed)}
        elif cmd == "snapshots":
            out = {}
            for key in ctx.store.meta_list("qsnap/"):
                name = key[len("qsnap/"):]
                if "@" in name:
                    continue  # rotation slots surface via their pointer
                blob = ctx.store.meta_get(key)
                entry = {"bytes": 0 if blob is None else len(blob)}
                slot = (None if blob is None
                        else parse_snapshot_pointer(blob))
                if slot is not None:
                    # two-slot rotation: report the pointed-at blob,
                    # not the ~20-byte pointer an operator would
                    # mistake for the state size
                    sb = ctx.store.meta_get(snapshot_slot_key(name, slot))
                    entry = {"bytes": 0 if sb is None else len(sb),
                             "slot": slot}
                out[name] = entry
        elif cmd == "replicas":
            status = getattr(ctx.store, "follower_status", None)
            out = {"role": "leader" if status else "single",
                   "followers": status() if status else []}
            leader = getattr(ctx.store, "leader_status", None)
            if leader is not None:
                # epoch/fencing/dedup state (ISSUE 9): one verb answers
                # "who leads, at what epoch, is anyone fenced"
                out["leader"] = leader()
        elif cmd == "promote":
            # epoch-fenced failover (ISSUE 9). Two shapes:
            #   promote target=ADDR        planned handoff — THIS
            #     leader raises the target's epoch and fences itself
            #   promote replicas=A,B,...   leader-death path — pick the
            #     most-caught-up reachable replica (highest
            #     (epoch, applied_seq, node_id)) and promote it
            from hstream_tpu.store import replica as _replica

            target = args.get("target") or None
            addrs = [a.strip()
                     for a in str(args.get("replicas") or "").split(",")
                     if a.strip()]
            hint = args.get("leader_addr") or None
            if target:
                promote = getattr(ctx.store, "promote_follower", None)
                if promote is None:
                    raise ServerError(
                        "this server's store is not a replication "
                        "leader; use promote replicas=A,B,... against "
                        "the replica group directly")
                out = promote(target, leader_addr=hint)
            elif addrs:
                out = _replica.promote_best(
                    addrs, leader_addr=hint,
                    promoted_by=scheduler.node_name(ctx))
            else:
                raise ServerError(
                    "promote needs target=ADDR or replicas=A,B,...")
            if out.get("ok"):
                ctx.stats.stream_stat_add("promotions", "_store")
        elif cmd == "assignments":
            out = scheduler.assignments(ctx)
        elif cmd == "placer":
            # placements, per-node scores, last decision + machine-
            # readable reason (ISSUE 17 satellite 1)
            out = ctx.placer.status()
        elif cmd == "quota-set":
            from hstream_tpu.flow import Quota

            scope = args.pop("scope")
            try:
                q = ctx.flow.set_quota(scope, Quota.from_json(args))
            except ValueError as e:
                raise ServerError(str(e)) from e
            out = {"scope": scope, **q.to_json()}
        elif cmd == "quota-get":
            q = ctx.flow.get_quota(args["scope"])
            out = {"scope": args["scope"],
                   **({"unset": True} if q is None else q.to_json())}
        elif cmd == "quota-unset":
            try:
                ctx.flow.unset_quota(args["scope"])
            except ValueError as e:
                raise ServerError(str(e)) from e
            out = {"scope": args["scope"], "unset": True}
        elif cmd == "quota-list":
            out = {scope: q.to_json()
                   for scope, q in ctx.flow.list_quotas().items()}
        elif cmd == "flow-status":
            out = ctx.flow.status()
        elif cmd == "read-cache":
            # read plane (ISSUE 20): snapshot/expansion cache counters
            cache = getattr(ctx, "read_cache", None)
            out = ({"enabled": False} if cache is None
                   else {"enabled": True,
                         "max_bytes": cache.max_bytes,
                         "max_staleness_ms": cache.max_staleness_ms,
                         **cache.stats()})
        elif cmd == "fault-set":
            try:
                ctx.faults.arm(str(args["site"]), str(args["spec"]))
            except (KeyError, ValueError) as e:
                raise ServerError(f"bad fault spec: {e}") from e
            out = {"site": args["site"], "spec": args["spec"],
                   "armed": True}
        elif cmd == "fault-clear":
            site = args.get("site") or None
            ctx.faults.disarm(site)
            out = {"cleared": site or "all"}
        elif cmd == "fault-list":
            out = {"active": ctx.faults.active,
                   "sites": ctx.faults.status()}
        elif cmd == "supervisor":
            sup = getattr(ctx, "supervisor", None)
            out = sup.status() if sup is not None else {}
        elif cmd == "events":
            out = {"events": ctx.events.query(
                kind=args.get("kind") or None,
                since=int(args.get("since", 0)),
                limit=int(args.get("limit", 100)))}
        elif cmd == "metrics":
            # full Prometheus exposition as text — the gateway /metrics
            # route and curl-through-admin both unwrap {"text": ...}
            from hstream_tpu.stats.prometheus import render_metrics

            out = {"text": render_metrics(ctx)}
        elif cmd == "health":
            # per-query health rollup (ISSUE 13): OK/DEGRADED/STALLED
            # with reasons — GET /queries/<id>/health and `admin
            # health` both land here
            from hstream_tpu.server import health as _health

            q = args.get("query") or None
            if q:
                out = _health.evaluate_query(ctx, str(q))
            else:
                out = _health.evaluate_all(ctx)  # qid -> health dict
        elif cmd == "locks":
            # lock-order witness ledger (ISSUE 14): armed state,
            # per-lock acquire/contention counts + wait/hold p50/p99
            # (from the bound histograms), the observed order graph,
            # and any detected cycles. arm/disarm flips the witness
            # at runtime like fault-set does for the chaos registry.
            lt = getattr(ctx, "locktrace", None)
            if lt is None:
                from hstream_tpu.common.locktrace import LOCKTRACE as lt
            action = str(args.get("action") or "")
            if action == "arm":
                lt.arm()
            elif action == "disarm":
                lt.disarm()
            elif action:
                raise ServerError(
                    f"unknown locks action {action!r} (arm/disarm)")
            out = lt.status()
        elif cmd == "stats":
            # declarative-family rate tables (ISSUE 15): one entity
            # scope per call (streams | subscriptions | queries), every
            # family's rate at the requested ladder interval plus the
            # all-time total — the `hadmin server stats` analogue
            # behind `admin stats` and the gateway's GET /stats
            from hstream_tpu.stats.families import families_for_scope
            from hstream_tpu.stats.timeseries import INTERVAL_NAMES

            entity = str(args.get("entity") or "streams")
            scope = {"streams": "stream", "stream": "stream",
                     "subscriptions": "subscription",
                     "subscription": "subscription",
                     "queries": "query", "query": "query"}.get(entity)
            if scope is None:
                raise ServerError(
                    f"unknown stats entity {entity!r} "
                    f"(streams|subscriptions|queries)")
            interval = str(args.get("interval") or "1min")
            if interval not in INTERVAL_NAMES:
                raise ServerError(
                    f"unknown interval {interval!r} "
                    f"(one of {'|'.join(INTERVAL_NAMES)})")
            try:
                fams = families_for_scope(scope)
            except KeyError as e:
                raise ServerError(str(e)) from e
            out = {}
            keys = {k for f in fams for k in ctx.stats.stat_keys(f.name)}
            # every scope reports its LIVE topology (GetStats
            # discipline): a deleted entity's residual ladder — still
            # present until the next scrape-time stat_drop_stale sweep
            # — must not resurface through the admin table. "live" is
            # the one shared definition (cluster.live_entity_keys);
            # only the reserved overflow fold bypasses it.
            from hstream_tpu.stats import TS_OVERFLOW_LABEL
            from hstream_tpu.stats.cluster import live_entity_keys

            live = live_entity_keys(ctx, scope)
            keys = {k for k in keys
                    if k in live or k == TS_OVERFLOW_LABEL}
            for key in sorted(keys):
                row = {"interval": interval}
                for f in fams:
                    lad = ctx.stats.stat_ladder(f.name, key)
                    row[f"{f.name}_per_s"] = round(lad[interval], 3)
                    row[f"{f.name}_total"] = lad["total"]
                out[key] = row
        elif cmd == "cluster-stats":
            # federation (ISSUE 15): fan the ClusterStats RPC out to
            # explicit peers (or this leader's followers) and return
            # every node's report keyed by node name — `admin
            # cluster-stats` renders the merged per-node table from it
            from hstream_tpu.stats import cluster as _cluster

            peers = [a.strip()
                     for a in str(args.get("peers") or "").split(",")
                     if a.strip()]
            timeout = float(args.get("timeout_s") or 5.0)
            reports = _cluster.collect_cluster(ctx, peers,
                                               timeout=timeout)
            # keyed by node name, disambiguated on collision (two
            # bare followers booted with the default node id must
            # BOTH stay visible in the merged table, never silently
            # last-writer-wins)
            out = {}
            for i, r in enumerate(reports):
                key = r.get("node") or r.get("addr") or f"node-{i}"
                if key in out:
                    key = f"{key} [{r.get('addr') or i}]"
                while key in out:
                    key = f"{key}+"
                out[key] = r
        elif cmd == "programs":
            # compiled-program inventory (ISSUE 18): every executable
            # the compile funnel produced, with XLA cost-analysis rows
            # (`admin programs`, GET /programs)
            from hstream_tpu.stats.devicecost import PROGRAMS

            out = {"summary": PROGRAMS.summary(),
                   "programs": PROGRAMS.rows()}
        elif cmd == "flightrec":
            # flight-recorder bundles (ISSUE 18): the postmortem black
            # box for a distressed query (`admin flightrec <id>`,
            # GET /queries/<id>/flightrec); no query id -> the index
            flightrec = getattr(ctx, "flightrec", None)
            qid = str(args.get("query") or "")
            if flightrec is None:
                raise ServerError("flight recorder unavailable")
            if not qid:
                out = flightrec.summary()
            else:
                bundles = flightrec.bundles(qid)
                if not bundles:
                    raise ServerError(
                        f"no flight-recorder bundles for query {qid!r}")
                out = {"query": qid, "bundles": bundles}
        elif cmd == "trace-spans":
            # one scope's span ring as Chrome trace-event JSON
            # (GET /queries/<id>/trace, `admin trace --spans`)
            scope = str(args.get("scope") or "")
            if not scope:
                raise ServerError(
                    "trace-spans needs scope=<query id | stream | "
                    "subscription>")
            out = ctx.tracing.export_chrome(scope)
            out["scope"] = scope
            out["sample_rate"] = ctx.tracing.sample_rate
        else:
            raise ServerError(f"unknown admin command {cmd!r}")
        return pb.AdminCommandResponse(result=_json.dumps(out))

    @unary
    def GetStats(self, request, context):
        """Expose the stats holder (counters + time-series rates) — the
        observability the reference keeps native-only
        (common/clib/stats.h)."""
        from hstream_tpu.stats import (
            PER_STREAM_COUNTERS,
            PER_STREAM_TIME_SERIES,
        )

        stats = self.ctx.stats
        # counters are never pruned; report only streams that still
        # exist so dashboards see the live topology
        live = set(self.ctx.streams.find_streams())
        per_stream: dict[str, pb.StreamStats] = {}

        def ent(stream: str) -> pb.StreamStats:
            e = per_stream.get(stream)
            if e is None:
                e = pb.StreamStats(stream_name=stream)
                per_stream[stream] = e
            return e

        for metric in PER_STREAM_COUNTERS:
            for stream, v in stats.stream_stat_getall(metric).items():
                if stream in live:
                    ent(stream).counters[metric] = v
        for metric, _levels in PER_STREAM_TIME_SERIES:
            for stream in list(per_stream):
                ent(stream).rates[metric] = stats.time_series_peek_rate(
                    metric, stream)
        out = pb.GetStatsResponse()
        for name in sorted(per_stream):
            out.stats.append(per_stream[name])
        return out

    @unary
    def ClusterStats(self, request, context):
        """This node's load report (ISSUE 15): per-stream rate
        ladders, per-query health, append-front depth, rss — one fold
        of the stats holder, no device work. The federation fan-out
        (admin cluster-stats / stats.cluster.collect_cluster) calls
        this on every peer and merges."""
        from hstream_tpu.stats import cluster as _cluster

        return pb.ClusterStatsResponse(reports=[
            _cluster.report_to_pb(_cluster.node_report(self.ctx))])

    # ---- plan execution (executeQueryHandler dispatch) ----------------------

    def _execute_plan(self, plan, sql: str,
                      producer: tuple[str, int] | None = None
                      ) -> list[dict[str, Any]]:
        ctx = self.ctx
        if isinstance(plan, plans.CreatePlan):
            _reject_virtual_name("stream", plan.stream)
            ctx.streams.create_stream(plan.stream)
            return [{"stream": plan.stream, "created": True}]
        if isinstance(plan, plans.CreateBySelectPlan):
            _reject_virtual_name("stream", plan.stream)
            ctx.streams.create_stream(plan.stream)
            info = self._launch_query(plan.select, sql, QUERY_STREAM,
                                      sink_stream=plan.stream)
            return [{"stream": plan.stream, "query": info.query_id}]
        if isinstance(plan, plans.CreateViewPlan):
            info = self._create_view(plan, sql)
            return [{"view": plan.view, "query": info.query_id}]
        if isinstance(plan, plans.CreateSinkConnectorPlan):
            info = self._create_connector(plan.name, sql, plan)
            return [{"connector": info.connector_id}]
        if isinstance(plan, plans.InsertPlan):
            logid = ctx.streams.get_logid(plan.stream)
            if plan.payload is not None:
                record = rec.build_record(plan.payload)
            else:
                record = rec.build_record(plan.raw_payload or b"")
            data = record.SerializeToString()
            if ctx.flow.active:  # SQL INSERT is an ingress path too
                ctx.flow.admit_append(plan.stream, 1, len(data))
            try:
                if producer is not None:
                    # stamped INSERT: same exactly-once contract as a
                    # stamped Append (retry across failover dedups)
                    lsn, _n, dup = _dedup_append(
                        ctx, logid, [data], Compression.NONE,
                        producer[0], producer[1])
                else:
                    lsn, dup = ctx.store.append(logid, data), False
            except Exception:
                ctx.stats.stream_stat_add("append_failed", plan.stream)
                raise
            if dup:
                ctx.stats.stream_stat_add("append_deduped", plan.stream)
                return [{"stream": plan.stream, "lsn": lsn,
                         "duplicate": True}]
            ctx.stats.note_append(plan.stream, 1, len(data))
            return [{"stream": plan.stream, "lsn": lsn}]
        if isinstance(plan, plans.ShowPlan):
            return self._show(plan.what)
        if isinstance(plan, plans.DropPlan):
            return self._drop(plan)
        if isinstance(plan, plans.TerminatePlan):
            if plan.query_id is None:
                ids = [q.query_id for q in ctx.persistence.get_queries()
                       if q.query_type != QUERY_VIEW]
            else:
                ids = [plan.query_id]
            for qid in ids:
                self._terminate_query(qid)
            return [{"terminated": qid} for qid in ids]
        if isinstance(plan, plans.ExplainPlan):
            return [{"explain": plan.text}]
        if isinstance(plan, plans.SelectViewPlan):
            # a pre-existing user view of a reserved name (created
            # before the collision guard) keeps winning the route —
            # rejecting creation must not orphan restored state
            if plan.view in VIRTUAL_TABLES \
                    and plan.view not in ctx.views.names():
                return self._select_virtual(plan)
            mat = ctx.views.get(plan.view)
            return self._serve_view(plan.view, mat, plan.select, sql)
        if isinstance(plan, plans.SelectPlan):
            raise ServerError(
                "push queries (EMIT CHANGES) go through ExecutePushQuery")
        raise ServerError(f"cannot execute {type(plan).__name__}")

    def _serve_view(self, name: str, mat, select, sql: str
                    ) -> list[dict[str, Any]]:
        """Pull-query serve through the read plane (ISSUE 20): the
        snapshot cache collapses N concurrent readers onto ONE executor
        extract per close cycle; `read_out_records` / `read_extracts`
        carry the serve rates per view."""
        ctx = self.ctx
        cache = getattr(ctx, "read_cache", None)
        if cache is None:
            return serve_select_view(mat, select)
        rows, _how, extracted = cache.serve_view(name, mat, select, sql)
        try:
            ctx.stats.stat_add("read_out_records", name, float(len(rows)))
            if extracted:
                ctx.stats.stream_stat_add("read_extracts", name)
        except Exception:  # noqa: BLE001 — metrics must not fail reads
            pass
        return rows

    def _select_virtual(self, plan) -> list[dict[str, Any]]:
        """LDQuery-lite (reference hs_ldquery.cpp:1-175): plain SQL —
        WHERE + projections — over internal metadata tables exposed as
        __streams__/__queries__/__subscriptions__/__views__/
        __connectors__/__stats__. Same AST evaluation the view pull
        path applies (views.serve_select_view), minus window slicing."""
        from hstream_tpu.server.views import filter_rows, project_rows

        select = plan.select
        rows = filter_rows(self._virtual_rows(plan.view), select)
        return project_rows(rows, select)

    def _virtual_rows(self, table: str) -> list[dict[str, Any]]:
        ctx = self.ctx
        if table == "__streams__":
            out = []
            for name in ctx.streams.find_streams():
                meta = ctx.streams.stream_meta(name)
                logid = ctx.streams.get_logid(name)
                out.append({
                    "name": name, "logid": logid,
                    "replication_factor":
                        meta.get("replication_factor", 1),
                    "tail_lsn": ctx.store.tail_lsn(logid),
                    "trim_point": ctx.store.trim_point(logid)})
            return out
        if table == "__queries__":
            return [{"id": q.query_id,
                     "status": getattr(q.status, "name", str(q.status)),
                     "type": q.query_type, "sink": q.sink,
                     "created_ms": q.created_time_ms, "sql": q.sql}
                    for q in ctx.persistence.get_queries()]
        if table == "__subscriptions__":
            out = []
            for rt in ctx.subscriptions.list():
                tail = ctx.store.tail_lsn(rt.logid)
                out.append({"id": rt.sub_id,
                            "stream": rt.meta.stream_name,
                            "committed_lsn": rt.committed_lsn,
                            "tail_lsn": tail,
                            "lag": max(0, tail - rt.committed_lsn)})
            return out
        if table == "__views__":
            return [{"name": n} for n in ctx.views.names()]
        if table == "__connectors__":
            return [{"id": c.connector_id,
                     "status": getattr(c.status, "name", str(c.status)),
                     "sql": c.sql}
                    for c in ctx.persistence.get_connectors()]
        if table == "__stats__":
            from hstream_tpu.stats import (
                PER_STREAM_COUNTERS,
                PER_STREAM_TIME_SERIES,
            )

            live = set(ctx.streams.find_streams())
            rows: dict[str, dict[str, Any]] = {}
            for metric in PER_STREAM_COUNTERS:
                for s, v in ctx.stats.stream_stat_getall(metric).items():
                    if s in live:
                        rows.setdefault(s, {"stream": s})[metric] = v
            for metric, _levels in PER_STREAM_TIME_SERIES:
                for s in rows:
                    rows[s][f"{metric}_rate"] = \
                        ctx.stats.time_series_peek_rate(metric, s)
            return [rows[s] for s in sorted(rows)]
        raise ServerError(f"unknown virtual table {table}")

    def _show(self, what: str) -> list[dict[str, Any]]:
        ctx = self.ctx
        if what == "STREAMS":
            return [{"stream": n} for n in ctx.streams.find_streams()]
        if what == "VIEWS":
            return [{"view": n} for n in ctx.views.names()]
        if what == "QUERIES":
            return [{"id": q.query_id, "status": q.status, "sql": q.sql}
                    for q in ctx.persistence.get_queries()
                    if q.query_type != QUERY_VIEW]
        if what == "CONNECTORS":
            return [{"id": c.connector_id, "status": c.status}
                    for c in ctx.persistence.get_connectors()]
        raise ServerError(f"SHOW {what} unsupported")

    def _drop(self, plan: plans.DropPlan) -> list[dict[str, Any]]:
        ctx = self.ctx
        try:
            if plan.what == "STREAM":
                ctx.streams.delete_stream(plan.name)
            elif plan.what == "VIEW":
                self._drop_view(plan.name)
            elif plan.what == "CONNECTOR":
                self._terminate_connector(plan.name)
                ctx.persistence.remove_connector(plan.name)
            else:
                raise ServerError(f"DROP {plan.what} unsupported")
        except HStreamError:
            if not plan.if_exists:
                raise
        return [{"dropped": plan.name}]

    # ---- task helpers -------------------------------------------------------

    def _check_columns_against_stream(self,
                                      plan: plans.SelectPlan) -> None:
        """Unknown-column validation against SAMPLED records: the
        reference's Validate.hs cannot see data, so an unknown column
        silently becomes NULL and aggregates run on garbage; here query
        creation reads the source stream's tail and rejects references
        to columns absent from every sampled record. An empty stream
        skips the check (nothing to know yet)."""
        if plan.join is not None:
            return  # two sources with qualified refs; not sampled
        from hstream_tpu.engine.plan import AggregateNode
        from hstream_tpu.store.api import LSN_INVALID

        ctx = self.ctx
        referenced = set(plan.schema_req.inferred)
        if isinstance(plan.node, AggregateNode):
            from hstream_tpu.engine.expr import Col as _Col

            referenced |= {g.name for g in plan.node.group_keys
                           if isinstance(g, _Col)}
        if not referenced:
            return
        try:
            logid = ctx.streams.get_logid(plan.source)
            tail = ctx.store.tail_lsn(logid)
        except HStreamError:
            return
        if tail == LSN_INVALID:
            return
        # best-effort sample: head + tail batches, so heterogeneous
        # streams (different record shapes interleaved) are less likely
        # to spuriously miss a real column; a column absent from EVERY
        # sampled record is still rejected — better a creation-time
        # error than aggregates silently running on NULLs
        reader = ctx.store.new_reader()
        reader.set_timeout(0)
        lo = ctx.store.trim_point(logid) + 1
        reader.start_reading(logid, lo, min(lo + 2, tail))
        head = reader.read(16)
        reader.stop_reading(logid)
        reader.start_reading(logid, max(tail - 4, lo), tail)
        fields: set[str] = set()

        def collect(item) -> bool:
            """Union item's record fields into `fields`; True if any
            record was decodable (one shared walk for the sample pass
            and the widen pass)."""
            any_dec = False
            if not isinstance(item, DataBatch):
                return False
            for payload in item.payloads:
                r = rec.parse_record(payload)
                if (r.header.flag == rec.pb.RECORD_FLAG_RAW
                        and columnar.is_columnar(r.payload)):
                    try:
                        _, cols = columnar.decode_columnar(r.payload)
                    except Exception:  # noqa: BLE001
                        continue
                    fields.update(cols)
                    any_dec = True
                else:
                    d = rec.record_to_dict(r)
                    if d is not None:
                        fields.update(d)
                        any_dec = True
            return any_dec

        sampled = False
        for item in head + reader.read(64):
            sampled |= collect(item)
        missing = referenced - fields
        if sampled and missing:
            # widen before rejecting: a heterogeneous stream may carry
            # the column only in batches outside the head/tail sample
            reader.stop_reading(logid)
            reader.start_reading(logid, lo, tail)
            for item in reader.read(512):
                collect(item)
                missing = referenced - fields
                if not missing:
                    break
        if sampled and missing:
            raise ServerError(
                f"unknown column(s) {sorted(missing)}: not present in "
                f"recent records of stream {plan.source!r}")

    def _launch_query(self, plan: plans.SelectPlan, sql: str, qtype: str,
                      *, sink_stream: str,
                      sink_type: StreamType = StreamType.STREAM,
                      query_id: str | None = None) -> QueryInfo:
        ctx = self.ctx
        self._check_columns_against_stream(plan)
        query_id = query_id or f"q{gen_unique()}"
        info = QueryInfo(query_id=query_id, sql=sql,
                         created_time_ms=now_ms(), query_type=qtype,
                         status=TaskStatus.CREATED, sink=sink_stream)
        ctx.persistence.insert_query(info)
        # co-compile packing (ISSUE 17c): with --pack-queries, a query
        # whose (source, window, agg-set) signature matches an existing
        # pack joins that group's shared slot-keyed executor — one
        # dispatch for all members, nothing compiled for the 2nd..Nth
        pool = getattr(ctx, "pack_pool", None)
        if pool is not None:
            from hstream_tpu.placer.packing import PackRefusal

            member = pool.try_attach(
                query_id, plan, stream_sink(ctx, sink_stream, sink_type))
            if not isinstance(member, PackRefusal):
                scheduler.record_assignment(ctx, query_id)
                ctx.running_queries[query_id] = member
                ctx.persistence.set_query_status(
                    query_id, TaskStatus.RUNNING)
                return info
        # placement (ISSUE 17a): an armed placer ranks every node's
        # published load record; when a less-loaded peer wins, this
        # node writes an OFFERED scheduler record instead of launching
        # — the target's adoption sweep claims and resumes it there
        if qtype == QUERY_STREAM:
            target = ctx.placer.place_for_launch(query_id)
            if target is not None:
                return info
        scheduler.record_assignment(ctx, query_id)
        task = QueryTask(ctx, info, plan,
                         stream_sink(ctx, sink_stream, sink_type))
        # correlation: the creating request's id rides the tracer so
        # `admin trace` ties a running query back to who launched it;
        # a SAMPLED creating request additionally binds the task's
        # stage timings into its trace (ISSUE 13)
        task.tracer.request_id = current_request_id() or None
        self._bind_task_trace(task, query_id)
        ctx.running_queries[query_id] = task
        task.start()
        return info

    def _remove_query_state(self, query_id: str) -> None:
        """Durable per-query state cleanup: operator-state snapshot
        (pointer + both rotation slots) + read checkpoints."""
        self.ctx.store.meta_delete(snapshot_key(query_id))
        for slot in (0, 1):
            self.ctx.store.meta_delete(
                snapshot_slot_key(query_id, slot))
        self.ctx.ckp_store.remove(f"query-{query_id}")

    def _terminate_query(self, query_id: str) -> None:
        ctx = self.ctx
        ctx.persistence.get_query(query_id)  # raises if unknown
        sup = getattr(ctx, "supervisor", None)
        if sup is not None:
            # an in-flight supervised restart must not resurrect a
            # query the operator is terminating
            sup.cancel(query_id)
        task = ctx.running_queries.pop(query_id, None)
        if task is not None:
            task.stop()
        ctx.persistence.set_query_status(query_id, TaskStatus.TERMINATED)
        scheduler.drop_assignment(ctx, query_id)

    def _create_view(self, plan: plans.CreateViewPlan,
                     sql: str) -> QueryInfo:
        ctx = self.ctx
        _reject_virtual_name("view", plan.view)
        self._check_columns_against_stream(plan.select)
        query_id = f"view-{plan.view}"
        info = QueryInfo(query_id=query_id, sql=sql,
                         created_time_ms=now_ms(), query_type=QUERY_VIEW,
                         status=TaskStatus.CREATED, sink=plan.view)
        ctx.persistence.insert_query(info)
        scheduler.record_assignment(ctx, query_id)
        self._start_view_task(info, plan)
        return info

    def _start_view_task(self, info: QueryInfo, plan) -> None:
        ctx = self.ctx
        select = plan.select if isinstance(plan, plans.CreateViewPlan) \
            else plan
        from hstream_tpu.engine.plan import AggregateNode
        from hstream_tpu.sql.codegen import emitted_group_cols

        group_cols = None
        if isinstance(select.node, AggregateNode):
            group_cols = emitted_group_cols(select.node)
        mat = Materialization(group_cols=group_cols)
        task = QueryTask(ctx, info, select, mat.add_closed)
        task.sink_dump = mat.dump
        task.sink_load = mat.load
        mat.task = task
        self._bind_task_trace(task, info.query_id)
        ctx.views.register(info.sink, mat)
        ctx.running_queries[info.query_id] = task
        task.start()

    def _drop_view(self, view: str) -> None:
        ctx = self.ctx
        ctx.views.get(view)  # raises if unknown
        query_id = f"view-{view}"
        task = ctx.running_queries.pop(query_id, None)
        if task is not None:
            task.stop()
        ctx.views.remove(view)
        cache = getattr(ctx, "read_cache", None)
        if cache is not None:
            cache.invalidate_view(view)
        try:
            ctx.persistence.remove_query(query_id)
        except QueryNotFound:
            pass
        self._remove_query_state(query_id)
        scheduler.drop_assignment(ctx, query_id)

    def _create_connector(self, cid: str, sql: str,
                          plan: plans.CreateSinkConnectorPlan
                          ) -> ConnectorInfo:
        ctx = self.ctx
        if plan.if_not_exist:
            try:
                return ctx.persistence.get_connector(cid)
            except HStreamError:
                pass
        info = ConnectorInfo(connector_id=cid, sql=sql,
                             created_time_ms=now_ms(),
                             status=TaskStatus.CREATED)
        ctx.persistence.insert_connector(info)
        self._start_connector_task(info, plan)
        return info

    def _start_connector_task(self, info: ConnectorInfo, plan) -> None:
        # deferred import: connectors imports server.persistence, so a
        # module-level import here would close an import cycle for
        # anyone importing hstream_tpu.connectors first
        from hstream_tpu.connectors import ConnectorTask, make_sink

        ctx = self.ctx
        options = plan.options
        source = options.get("STREAM")
        if not source:
            raise ServerError(
                "connector options need STREAM (the source stream)")
        sink = make_sink(ctx, options)
        task = ConnectorTask(ctx, info.connector_id, source, sink)
        ctx.running_connectors[info.connector_id] = task
        task.start()

    def _terminate_connector(self, cid: str) -> None:
        ctx = self.ctx
        ctx.persistence.get_connector(cid)
        task = ctx.running_connectors.pop(cid, None)
        if task is not None:
            task.stop()
        ctx.persistence.set_connector_status(cid, TaskStatus.TERMINATED)

    # ---- pb builders --------------------------------------------------------

    def _query_pb(self, info: QueryInfo) -> pb.Query:
        return pb.Query(id=info.query_id, status=info.status,
                        created_time_ms=info.created_time_ms,
                        query_text=info.sql)

    def _connector_pb(self, info: ConnectorInfo) -> pb.Connector:
        return pb.Connector(id=info.connector_id, status=info.status,
                            created_time_ms=info.created_time_ms,
                            config=info.sql)

    def _view_pb(self, info: QueryInfo) -> pb.View:
        return pb.View(view_id=info.sink, status=info.status,
                       created_time_ms=info.created_time_ms, sql=info.sql)

    def _node_pb(self) -> pb.Node:
        ctx = self.ctx
        return pb.Node(id=ctx.server_id, address=ctx.host, port=ctx.port,
                       roles=["server"], status="Running")
