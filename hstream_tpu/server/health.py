"""Per-query health rollup: one machine-readable verdict per query.

ISSUE 13 tentpole (d). The signals already exist — supervisor breaker
state, freshness lag, source backlog, device fallbacks, the overload
shed ladder — but an operator (or the thousand-query placer, ROADMAP
item 2) had to join five surfaces to answer "is this query healthy".
`evaluate_query` folds them into OK / DEGRADED / STALLED with reasons,
served via ``GET /queries/<id>/health``, ``admin health``, and the
``query_health_level`` gauge; crossing into STALLED journals a
``query_stalled`` event — the signal the chaos harness gates on today
and failover adoption gates on next.

Everything reads host-mirror values (executor watermarks, checkpoint
LSNs, counters): a health evaluation costs ZERO device dispatches,
fetches, or recompiles.

Verdict rules (thresholds are ServerContext knobs, see README):

  STALLED   crash-loop breaker open (``crash_loop``); task dead with
            no pending restart (``dead``); status RUNNING but no task
            owns it (``unowned``); or source backlog > 0 with no
            watermark advance for ``health_stalled_ms`` (default
            30000) (``no_progress``).
  DEGRADED  supervisor restart pending (``restart_pending``); device
            kernels degraded to the host path (``device_fallback``);
            overload shed ladder at DEFER or above (``overload``); or
            backlog > 0 with no watermark advance for
            ``health_degraded_ms`` (default 5000) (``lagging``).
  OK        none of the above (TERMINATED queries report OK — stopped
            is not sick).
"""

from __future__ import annotations

import threading
import time
from typing import Any

from hstream_tpu.server.persistence import TaskStatus

# default thresholds; ServerContext carries per-server overrides
# (--health-degraded-ms / --health-stalled-ms)
DEGRADED_AFTER_MS = 5_000
STALLED_AFTER_MS = 30_000

LEVELS = {"OK": 0, "DEGRADED": 1, "STALLED": 2}


class HealthTracker:
    """Per-query progress memory: last watermark + when it last
    advanced, and the last verdict (so STALLED transitions journal
    exactly once per episode). Evaluation-time state only — nothing
    here is durable or replicated."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # qid -> (last watermark, wall-ms of last advance/first sight)
        self._progress: dict[str, tuple[int, float]] = {}
        self._verdicts: dict[str, str] = {}

    def note_progress(self, qid: str, watermark: int | None,
                      now_ms: float) -> float:
        """Record the query's watermark; returns ms since it last
        advanced (0.0 on first sight or on an advance)."""
        with self._lock:
            prev = self._progress.get(qid)
            if watermark is None:
                # no executor yet: treat task (re)appearance as progress
                if prev is None:
                    self._progress[qid] = (-1, now_ms)
                    return 0.0
                return now_ms - prev[1]
            if prev is None or watermark > prev[0]:
                self._progress[qid] = (watermark, now_ms)
                return 0.0
            return now_ms - prev[1]

    def transition(self, qid: str, verdict: str) -> str | None:
        """Remember the verdict; returns the PREVIOUS verdict when it
        changed (None otherwise)."""
        with self._lock:
            prev = self._verdicts.get(qid)
            if prev == verdict:
                return None
            self._verdicts[qid] = verdict
            return prev or "OK"

    def forget(self, known: set[str]) -> None:
        """Drop memory of queries that no longer exist."""
        with self._lock:
            for qid in list(self._progress):
                if qid not in known:
                    self._progress.pop(qid, None)
                    self._verdicts.pop(qid, None)


def _executor_watermark(task) -> int | None:
    """The executor's event-time watermark (host attribute reads only)
    — delegates to the task's own fold so the health plane and the
    freshness gauges can never disagree on where the watermark lives."""
    fn = getattr(task, "_event_watermark", None)
    return fn() if fn is not None else None


def _source_backlog(ctx, task) -> int:
    """Unprocessed source LSNs: tail minus the highest processed LSN
    per source log (the task's pending checkpoints, or its attach
    point before anything processed)."""
    backlog = 0
    for logid in getattr(task, "_sources", {}):
        try:
            tail = ctx.store.tail_lsn(logid)
        except Exception:  # noqa: BLE001 — stream being deleted
            continue
        processed = task._pending_ckps.get(logid)
        if processed is None:
            processed = task.attached_lsns.get(logid, 1) - 1
        backlog += max(0, tail - processed)
    return backlog


def evaluate_query(ctx, qid: str, *, now_ms: float | None = None,
                   sup_status: dict | None = None,
                   shed_level: int | None = None) -> dict[str, Any]:
    """One query's health verdict + the evidence it folded. Raises
    QueryNotFound for unknown ids (the endpoint maps it to 404).
    ``sup_status``/``shed_level`` let a sweep (sample_health) snapshot
    the server-wide state ONCE instead of per query."""
    from hstream_tpu.server import scheduler

    info = ctx.persistence.get_query(qid)
    now = time.time() * 1e3 if now_ms is None else float(now_ms)
    tracker: HealthTracker = ctx.health
    degraded_ms = float(getattr(ctx, "health_degraded_ms",
                                DEGRADED_AFTER_MS))
    stalled_ms = float(getattr(ctx, "health_stalled_ms",
                               STALLED_AFTER_MS))
    if sup_status is None:
        sup = getattr(ctx, "supervisor", None)
        sup_status = sup.status() if sup is not None else {}
    breaker_open = qid in sup_status.get("breaker_open", ())
    restart_pending = qid in sup_status.get("pending", {})
    task = ctx.running_queries.get(qid)
    if shed_level is None:
        flow = getattr(ctx, "flow", None)
        shed_level = (flow.overload.effective_level()
                      if flow is not None else 0)

    status = getattr(info.status, "name", str(info.status))
    stalled: list[str] = []
    degraded: list[str] = []
    watermark = wm_lag = None
    backlog = 0
    stuck_ms = 0.0
    fallbacks = late = 0
    shards = 0
    owner = None

    if breaker_open:
        stalled.append("crash_loop")
    if restart_pending:
        degraded.append("restart_pending")
    if info.status in (TaskStatus.CONNECTION_ABORT, TaskStatus.FAILED):
        if not restart_pending and not breaker_open:
            stalled.append("dead")
    elif info.status is TaskStatus.RUNNING and task is None \
            and not restart_pending:
        # no task on THIS server drives a RUNNING query. Ownerless —
        # the state failover adoption exists to clear — ONLY when the
        # scheduler record names this node (or nobody): a query owned
        # by a live peer is that peer's to judge, and marking it
        # STALLED from here would journal false distress on every
        # multi-node scrape. (CREATED is excluded: the launch window
        # between insert_query and task registration is milliseconds.)
        owner = scheduler.assignment(ctx, qid)
        owner_node = (owner or {}).get("node")
        if owner_node is None or owner_node == scheduler.node_name(ctx):
            stalled.append("unowned")
        else:
            # owned by a peer: honor its heartbeat lease. A lapsed
            # heartbeat means the owner crashed without cleanup — the
            # query is STALLED "dead" until an armed placer's sweep
            # adopts it; a FRESH peer heartbeat stays healthy here
            # (regression pin: live peers are never flagged).
            age = scheduler.owner_heartbeat_age_ms(owner)
            lease = int(getattr(ctx, "heartbeat_lease_ms", 10_000))
            if age is not None and age > lease:
                stalled.append("dead")

    if task is not None:
        watermark = _executor_watermark(task)
        if watermark is not None:
            wm_lag = max(0.0, now - watermark)
        backlog = _source_backlog(ctx, task)
        stuck_ms = tracker.note_progress(qid, watermark, now)
        fallbacks = task.engine_total("device_fallbacks")
        late = task.engine_total("late_drops")
        shards = int(getattr(task, "mesh_shards", lambda: 0)() or 0)
        if backlog > 0 and stuck_ms >= stalled_ms:
            stalled.append("no_progress")
        elif backlog > 0 and stuck_ms >= degraded_ms:
            degraded.append("lagging")
        if fallbacks > 0:
            degraded.append("device_fallback")
        if shed_level >= 1:
            degraded.append("overload")

    verdict = ("STALLED" if stalled
               else "DEGRADED" if degraded else "OK")
    reasons = stalled + degraded
    out = {
        "query": qid,
        "verdict": verdict,
        "level": LEVELS[verdict],
        "reasons": reasons,
        "status": status,
        "watermark_ms": watermark,
        "watermark_lag_ms": (None if wm_lag is None
                             else round(wm_lag, 1)),
        "watermark_stuck_ms": round(stuck_ms, 1),
        "backlog": backlog,
        "device_fallbacks": fallbacks,
        "late_drops": late,
        # multi-chip plane (ISSUE 16): 0 means single-chip execution
        "mesh_shards": shards,
        "shed_level": shed_level,
        "restart_pending": restart_pending,
        "breaker_open": breaker_open,
        "thresholds": {"degraded_after_ms": degraded_ms,
                       "stalled_after_ms": stalled_ms},
    }
    if task is None and owner is not None:
        # owned elsewhere: name the owner so a caller knows which
        # node's health plane is authoritative for this query
        out["owner"] = owner.get("node")
    prev = tracker.transition(qid, verdict)
    if prev is not None and verdict == "STALLED":
        # the machine-readable distress signal: journaled exactly once
        # per episode, queryable via admin events / GET /events
        try:
            ctx.events.append(
                "query_stalled",
                f"query {qid} STALLED ({', '.join(stalled)}); "
                f"backlog {backlog}, watermark stuck "
                f"{stuck_ms / 1e3:.1f}s",
                query=qid, reasons=reasons, backlog=backlog,
                prev_verdict=prev)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass
        # the black box (ISSUE 18): snapshot the postmortem bundle at
        # the SAME edge the distress signal journals on — exactly once
        # per STALLED episode, with the verdict it already computed
        rec = getattr(ctx, "flightrec", None)
        if rec is not None:
            rec.snapshot(qid, trigger="query_stalled", health=out)
    stats = getattr(ctx, "stats", None)
    if stats is not None:
        try:
            stats.gauge_set("query_health_level", qid, LEVELS[verdict])
        except Exception:  # noqa: BLE001 — metrics must not fail health
            pass
    return out


def _sweep_snapshot(ctx) -> tuple[dict, int]:
    """ONE supervisor-status + shed-level snapshot for a whole sweep —
    per-query re-snapshots would take the supervisor lock and re-sort
    its state O(queries) times per scrape."""
    sup = getattr(ctx, "supervisor", None)
    sup_status = sup.status() if sup is not None else {}
    flow = getattr(ctx, "flow", None)
    shed = flow.overload.effective_level() if flow is not None else 0
    return sup_status, shed


def evaluate_all(ctx) -> dict[str, dict[str, Any]]:
    """qid -> health dict for every known query (the admin verb)."""
    out: dict[str, dict[str, Any]] = {}
    sup_status, shed = _sweep_snapshot(ctx)
    for info in ctx.persistence.get_queries():
        try:
            out[info.query_id] = evaluate_query(
                ctx, info.query_id, sup_status=sup_status,
                shed_level=shed)
        except Exception:  # noqa: BLE001 — one sick record must not
            continue       # hide every other query's verdict
    return out


def sample_health(ctx) -> None:
    """Scrape-time sampling (called from prometheus.sample_gauges):
    per-query watermark/lag gauges + the health verdict gauge, with
    stale series dropped when queries go away. Cost is O(queries) host
    reads — never device work."""
    stats = ctx.stats
    now = time.time() * 1e3
    known: set[str] = set()
    live: set[tuple[str, str]] = set()
    try:
        infos = list(ctx.persistence.get_queries())
    except Exception:  # noqa: BLE001 — persistence mid-teardown
        return
    sup_status, shed = _sweep_snapshot(ctx)
    for info in infos:
        qid = info.query_id
        known.add(qid)
        try:
            evaluate_query(ctx, qid, now_ms=now,
                           sup_status=sup_status, shed_level=shed)
            live.add(("query_health_level", qid))
        except Exception:  # noqa: BLE001
            continue
        task = ctx.running_queries.get(qid)
        if task is None:
            continue
        wm = _executor_watermark(task)
        if wm is None:
            continue
        stats.gauge_set("query_watermark_ms", qid, wm)
        stats.gauge_set("query_watermark_lag_ms", qid,
                        max(0.0, now - wm))
        live.add(("query_watermark_ms", qid))
        live.add(("query_watermark_lag_ms", qid))
        # multi-chip plane (ISSUE 16): the gauge only exists for
        # sharded queries — single-chip queries drop it (absent, not
        # 0) so dashboards can filter on presence
        shards = int(getattr(task, "mesh_shards", lambda: 0)() or 0)
        if shards > 1:
            stats.gauge_set("mesh_shards", qid, shards)
            live.add(("mesh_shards", qid))
    for metric in ("query_watermark_ms", "query_watermark_lag_ms",
                   "query_health_level", "mesh_shards"):
        for label in stats.gauge_labels(metric):
            if (metric, label) not in live:
                stats.gauge_drop(metric, label)
    ctx.health.forget(known)
