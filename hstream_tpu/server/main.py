"""Server boot: flags/config file -> store -> context -> gRPC serve.

Reference: hstream/app/server.hs:36-149 — optparse flags
(host/port/store/replication/timeout/compression/log-level; "TODO:
config file" at server.hs:32-34 — here the config file exists). Flags
override config-file values; see --help for the full surface.
"""

from __future__ import annotations

import argparse
import json
import signal
from concurrent import futures

import grpc

from hstream_tpu.common.logger import get_logger
from hstream_tpu.proto.rpc import add_hstream_api_to_server
from hstream_tpu.server.context import (
    DEFAULT_APPEND_LANES,
    DEFAULT_ENCODE_WORKERS,
    DEFAULT_PIPELINE_DEPTH,
    ServerContext,
)
from hstream_tpu.store import open_store

log = get_logger("main")


def _build_mesh(shape: str):
    """'DxK' -> a (data, key) jax mesh over the first D*K devices."""
    from hstream_tpu.parallel import make_mesh

    n_data, _, n_key = shape.lower().partition("x")
    return make_mesh(n_data=int(n_data), n_key=int(n_key or 1))


def serve(host: str = "127.0.0.1", port: int = 6570,
          store_uri: str = "mem://", *, max_workers: int = 32,
          mesh_shape: str | None = None,
          sync_interval_ms: int | None = None,
          segment_bytes: int | None = None,
          snapshot_interval_ms: int | None = None,
          replicate: str | None = None,
          replication_factor: int = 2,
          replica_ack_timeout_ms: int | None = None,
          store: "LogStore | None" = None,
          append_compression: str | None = None,
          pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
          encode_workers: int = DEFAULT_ENCODE_WORKERS,
          append_lanes: int = DEFAULT_APPEND_LANES,
          credit_window: int | None = None,
          metrics_port: int | None = None,
          slow_request_ms: float = 1000.0,
          faults: str | None = None,
          locktrace: bool = False,
          trace_sample: float = 0.0,
          health_degraded_ms: float | None = None,
          health_stalled_ms: float | None = None,
          load_report_interval_ms: float | None = None,
          placer_interval_ms: float | None = None,
          heartbeat_lease_ms: float | None = None,
          pack_queries: bool = False,
          device_time_sample: int = 0,
          read_max_staleness_ms: float | None = None,
          read_cache_bytes: int = 64 << 20,
          owns_store: bool = True
          ) -> tuple[grpc.Server, ServerContext]:
    """Start a server; returns (grpc_server, ctx). Caller owns shutdown.

    `mesh_shape` ("DxK", e.g. "4x2") shards eligible aggregate queries
    over a (data, key) device mesh (SURVEY §2.3). `replicate` (comma-
    separated follower replica addresses) makes this server the store
    LEADER: every store mutation replicates to those follower nodes
    (run with ``python -m hstream_tpu.store.replica``) over DCN.
    `replica_ack_timeout_ms` bounds the follower-ack wait per append
    (expiry journals `replica_ack_timeout` and degrades honestly).
    `store` (an already-open LogStore) overrides `store_uri` — the
    failover path: promote a follower, then boot a server OVER its
    (promoted) store; the epoch persisted in store meta carries the
    leadership forward."""
    if store is None:
        store = open_store(store_uri, sync_interval_ms=sync_interval_ms,
                           segment_bytes=segment_bytes)
    if replicate:
        from hstream_tpu.store.replica import ReplicatedStore

        store = ReplicatedStore(
            store, [a.strip() for a in replicate.split(",") if a.strip()],
            replication_factor=replication_factor,
            ack_timeout_s=(replica_ack_timeout_ms / 1000.0
                           if replica_ack_timeout_ms else None))
    mesh = _build_mesh(mesh_shape) if mesh_shape else None
    ctx = ServerContext(store, host=host, port=port, mesh=mesh,
                        pipeline_depth=pipeline_depth,
                        encode_workers=encode_workers,
                        credit_window=credit_window,
                        slow_request_ms=slow_request_ms,
                        append_lanes=append_lanes,
                        trace_sample=trace_sample,
                        health_degraded_ms=health_degraded_ms,
                        health_stalled_ms=health_stalled_ms,
                        load_report_interval_ms=load_report_interval_ms,
                        placer_interval_ms=placer_interval_ms,
                        heartbeat_lease_ms=heartbeat_lease_ms,
                        pack_queries=pack_queries,
                        device_time_sample=device_time_sample,
                        read_max_staleness_ms=read_max_staleness_ms,
                        read_cache_bytes=read_cache_bytes,
                        owns_store=owns_store)
    if faults:
        # chaos harness: arm fault sites for this run (same grammar as
        # HSTREAM_FAULTS, which ServerContext already loaded)
        ctx.faults.load_env(faults)
    if locktrace:
        # lock-order witness (ISSUE 14): arm the runtime deadlock
        # detector for this process (HSTREAM_LOCKTRACE=1 equivalent)
        ctx.locktrace.arm()
    if append_compression:
        from hstream_tpu.store.api import Compression

        ctx.append_compression = Compression[append_compression.upper()]
    if snapshot_interval_ms is not None:
        # per-context, not the QueryTask CLASS attribute: two servers in
        # one process must not leak cadence into each other's tasks
        ctx.snapshot_interval_ms = snapshot_interval_ms
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)])
    from hstream_tpu.server.handlers import HStreamApiServicer

    servicer = HStreamApiServicer(ctx)
    add_hstream_api_to_server(servicer, server)
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind {host}:{port}")
    ctx.port = bound
    if hasattr(ctx.store, "client_addr"):
        # the address that rides every Replicate as the leader hint:
        # followers persist it and serve it to redirected clients, so
        # it must be THIS server's client-facing endpoint (known only
        # after the bind)
        ctx.store.client_addr = f"{host}:{bound}"
    # only after a successful bind: a failed boot (port in use) must not
    # relaunch tasks and re-emit at-least-once rows before dying
    servicer.resume_persisted()
    server.start()
    # load reporter starts only now: its boot-time node_load_report
    # must journal the node's REAL bound identity (host:0 would be a
    # phantom node the placer can't match to later reports)
    ctx.load_reporter.start()
    # same bind-first rule for the placer: its node record and its
    # scheduler heartbeats carry server-<id>@host:port, which is only
    # real after the bind. No-op unless --placer-interval-ms armed it.
    ctx.placer.start()
    if metrics_port is not None:
        from hstream_tpu.stats.prometheus import serve_exporter

        ctx.metrics_httpd = serve_exporter(ctx, host=host,
                                           port=metrics_port)
        log.info("metrics exporter on %s:%d (/metrics, /events)",
                 host, ctx.metrics_httpd.server_port)
    log.info("hstream-tpu server listening on %s:%d (store %s)",
             host, bound, store_uri)
    return server, ctx


def _parse_args(argv):
    ap = argparse.ArgumentParser(
        "hstream-tpu-server",
        description="TPU-native streaming database server")
    ap.add_argument("--config", default=None, metavar="FILE",
                    help="JSON config file; flags given on the command "
                         "line override it")
    ap.add_argument("--host", default=None)
    ap.add_argument("--port", type=int, default=None)
    ap.add_argument("--store", default=None,
                    help="mem:// or a directory path for the native "
                         "durable store")
    ap.add_argument("--workers", type=int, default=None,
                    help="gRPC worker threads")
    ap.add_argument("--mesh", default=None, metavar="DxK",
                    help="shard aggregate queries over a (data, key) "
                         "device mesh, e.g. 4x2 (needs D*K devices)")
    ap.add_argument("--log-level", default=None,
                    choices=["DEBUG", "INFO", "WARNING", "ERROR"])
    ap.add_argument("--sync-interval-ms", type=int, default=None,
                    help="native store group-commit fsync cadence")
    ap.add_argument("--segment-bytes", type=int, default=None,
                    help="native store segment roll size")
    ap.add_argument("--snapshot-interval-ms", type=int, default=None,
                    help="operator-state snapshot + checkpoint cadence")
    ap.add_argument("--replicate", default=None, metavar="ADDR,ADDR",
                    help="follower store-replica addresses; this server "
                         "becomes the store leader and replicates every "
                         "mutation to them (reference: server.hs "
                         "--replicate-factor onto LogDevice)")
    ap.add_argument("--replication-factor", type=int, default=None,
                    help="copies (incl. leader) an append waits for")
    ap.add_argument("--replica-ack-timeout-ms", type=int, default=None,
                    help="follower-ack deadline per append; expiry "
                         "journals replica_ack_timeout and records a "
                         "degraded ack instead of blocking forever "
                         "(default 5000)")
    ap.add_argument("--append-compression", default=None,
                    choices=["none", "zlib"],
                    help="storage compression for appended batches "
                         "(reference server.hs --compression)")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    help="ingest staging-ring depth: micro-batches "
                         "wire-encoded ahead of the ordered device "
                         f"step loop (default {DEFAULT_PIPELINE_DEPTH})")
    ap.add_argument("--encode-workers", type=int, default=None,
                    help="host-encode worker threads per query task "
                         "feeding the staging ring (default "
                         f"{DEFAULT_ENCODE_WORKERS})")
    ap.add_argument("--append-lanes", type=int, default=None,
                    help="sharded append-front lanes behind the framed "
                         "columnar append path (stores with a native "
                         "completion queue pipeline there instead; "
                         f"default {DEFAULT_APPEND_LANES})")
    ap.add_argument("--credit-window", type=int, default=None,
                    help="per-consumer in-flight record window for "
                         "push delivery (StreamingFetch); a stalled "
                         "consumer holds at most this many undelivered "
                         "records server-side (default 256)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics + /events on this "
                         "port straight off the server process "
                         "(0 picks a free port); omit to disable")
    ap.add_argument("--slow-request-ms", type=float, default=None,
                    help="log a correlated slow-request warning for "
                         "any RPC slower than this (default 1000)")
    ap.add_argument("--faults", default=None, metavar="SITE=SPEC;...",
                    help="arm chaos fault sites at boot, e.g. "
                         "'store.append=fail:3;snapshot.persist="
                         "torn:2:7' (also: HSTREAM_FAULTS env, admin "
                         "fault-set at runtime)")
    ap.add_argument("--locktrace", action="store_true", default=None,
                    help="arm the runtime lock-order witness "
                         "(GoodLock/lockdep): per-thread held-sets, "
                         "cycle detection journaling lock_cycle, "
                         "lock_wait_ms/lock_hold_ms/lock_contention "
                         "on /metrics, `admin locks` ledger; also: "
                         "HSTREAM_LOCKTRACE=1 env. Disarmed cost is "
                         "one attribute read + one branch per acquire")
    ap.add_argument("--trace-sample", type=float, default=None,
                    help="cross-component span sampling rate in [0,1]: "
                         "0 disarms tracing (one-branch cost), 1 "
                         "records every request's spans into the "
                         "per-query rings (GET /queries/<id>/trace, "
                         "admin trace --spans); default 0")
    ap.add_argument("--health-degraded-ms", type=float, default=None,
                    help="health plane: backlog with no watermark "
                         "advance for this long reads DEGRADED "
                         "(default 5000)")
    ap.add_argument("--health-stalled-ms", type=float, default=None,
                    help="health plane: backlog with no watermark "
                         "advance for this long reads STALLED and "
                         "journals query_stalled (default 30000)")
    ap.add_argument("--load-report-interval-ms", type=float,
                    default=None,
                    help="cadence of the node_load_report journal "
                         "event (per-stream rate ladders, query "
                         "health counts, append-front depth, rss — "
                         "the placement load signal; default 30000)")
    ap.add_argument("--placer-interval-ms", type=float, default=None,
                    help="ARM the placer loop at this cadence: publish "
                         "this node's record to cluster/nodes/<node>, "
                         "heartbeat owned scheduler/query/* records, "
                         "adopt queries whose owner's heartbeat lease "
                         "lapsed, rebalance on load skew. Unset (the "
                         "default) keeps pure boot-epoch adoption with "
                         "zero background config writes")
    ap.add_argument("--heartbeat-lease-ms", type=float, default=None,
                    help="owner-liveness lease: a scheduler record "
                         "whose heartbeat is older than this is "
                         "adoptable by any armed survivor "
                         "(default 10000)")
    ap.add_argument("--device-time-sample", type=int, default=None,
                    help="device-time sampling rate N: every Nth "
                         "dispatch per kernel family is timed with a "
                         "fenced block-until-ready into the "
                         "kernel_device_ms histogram (1 = every "
                         "dispatch, 0 = disarmed; default 0). "
                         "Disarmed cost is one attribute read + one "
                         "branch per dispatch")
    ap.add_argument("--read-max-staleness-ms", type=float, default=None,
                    help="read plane: age-bound snapshot-cache hits to "
                         "this many ms (exactness already comes from "
                         "the version key; this is a freshness SLA "
                         "backstop). Unset = no age bound")
    ap.add_argument("--read-cache-bytes", type=int, default=None,
                    help="read plane: LRU byte budget shared by the "
                         "pull-query snapshot cache and the "
                         "subscription shared-encode cache "
                         "(0 disables both; default 64 MiB)")
    ap.add_argument("--pack-queries", action="store_true", default=None,
                    help="co-compile packing: bucket compatible "
                         "queries (same source/window/agg signature) "
                         "into one shared slot-keyed executor, so N "
                         "queries ride one dispatch and the 2nd..Nth "
                         "compiles nothing")
    args = ap.parse_args(argv)

    defaults = {"host": "0.0.0.0", "port": 6570, "store": "mem://",
                "workers": 32, "mesh": None, "log_level": None,
                "sync_interval_ms": None, "segment_bytes": None,
                "snapshot_interval_ms": None, "replicate": None,
                "replication_factor": 2,
                "replica_ack_timeout_ms": None,
                "append_compression": None,
                "pipeline_depth": DEFAULT_PIPELINE_DEPTH,
                "encode_workers": DEFAULT_ENCODE_WORKERS,
                "append_lanes": DEFAULT_APPEND_LANES,
                "credit_window": None,
                "metrics_port": None,
                "slow_request_ms": 1000.0,
                "faults": None,
                "locktrace": False,
                "trace_sample": 0.0,
                "health_degraded_ms": None,
                "health_stalled_ms": None,
                "load_report_interval_ms": None,
                "placer_interval_ms": None,
                "heartbeat_lease_ms": None,
                "pack_queries": False,
                "device_time_sample": 0,
                "read_max_staleness_ms": None,
                "read_cache_bytes": 64 << 20}
    if args.config:
        with open(args.config) as f:
            file_cfg = json.load(f)
        unknown = set(file_cfg) - set(defaults)
        if unknown:
            raise SystemExit(
                f"unknown config key(s) {sorted(unknown)}; "
                f"valid: {sorted(defaults)}")
        defaults.update(file_cfg)
    for key in defaults:
        v = getattr(args, key)
        if v is not None:
            defaults[key] = v
    return defaults


def main(argv=None) -> None:
    cfg = _parse_args(argv)
    if cfg["log_level"]:
        import logging

        level = str(cfg["log_level"]).upper()
        if level not in ("DEBUG", "INFO", "WARNING", "ERROR"):
            raise SystemExit(f"invalid log_level {cfg['log_level']!r}")
        # project logs ride the non-propagating 'hstream_tpu' logger
        logging.getLogger("hstream_tpu").setLevel(level)
    server, ctx = serve(
        cfg["host"], cfg["port"], cfg["store"],
        max_workers=cfg["workers"], mesh_shape=cfg["mesh"],
        sync_interval_ms=cfg["sync_interval_ms"],
        segment_bytes=cfg["segment_bytes"],
        snapshot_interval_ms=cfg["snapshot_interval_ms"],
        replicate=cfg["replicate"],
        replication_factor=cfg["replication_factor"],
        replica_ack_timeout_ms=cfg["replica_ack_timeout_ms"],
        append_compression=cfg["append_compression"],
        pipeline_depth=cfg["pipeline_depth"],
        encode_workers=cfg["encode_workers"],
        append_lanes=cfg["append_lanes"],
        credit_window=cfg["credit_window"],
        metrics_port=cfg["metrics_port"],
        slow_request_ms=cfg["slow_request_ms"],
        faults=cfg["faults"],
        locktrace=cfg["locktrace"],
        trace_sample=cfg["trace_sample"],
        health_degraded_ms=cfg["health_degraded_ms"],
        health_stalled_ms=cfg["health_stalled_ms"],
        load_report_interval_ms=cfg["load_report_interval_ms"],
        placer_interval_ms=cfg["placer_interval_ms"],
        heartbeat_lease_ms=cfg["heartbeat_lease_ms"],
        pack_queries=cfg["pack_queries"],
        device_time_sample=cfg["device_time_sample"],
        read_max_staleness_ms=cfg["read_max_staleness_ms"],
        read_cache_bytes=cfg["read_cache_bytes"])
    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True
        server.stop(grace=2)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    server.wait_for_termination()
    ctx.shutdown()


if __name__ == "__main__":
    main()
