"""Server boot: flags -> store -> context -> gRPC serve.

Reference: hstream/app/server.hs:36-149 (optparse flags; boot = logger ->
store client -> init checkpoint log -> gRPC event loop).
"""

from __future__ import annotations

import argparse
import signal
from concurrent import futures

import grpc

from hstream_tpu.common.logger import get_logger
from hstream_tpu.proto.rpc import add_hstream_api_to_server
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.handlers import HStreamApiServicer
from hstream_tpu.store import open_store

log = get_logger("main")


def serve(host: str = "127.0.0.1", port: int = 6570,
          store_uri: str = "mem://", *, max_workers: int = 32
          ) -> tuple[grpc.Server, ServerContext]:
    """Start a server; returns (grpc_server, ctx). Caller owns shutdown."""
    store = open_store(store_uri)
    ctx = ServerContext(store, host=host, port=port)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)])
    servicer = HStreamApiServicer(ctx)
    add_hstream_api_to_server(servicer, server)
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind {host}:{port}")
    ctx.port = bound
    # only after a successful bind: a failed boot (port in use) must not
    # relaunch tasks and re-emit at-least-once rows before dying
    servicer.resume_persisted()
    server.start()
    log.info("hstream-tpu server listening on %s:%d (store %s)",
             host, bound, store_uri)
    return server, ctx


def main(argv=None) -> None:
    ap = argparse.ArgumentParser("hstream-tpu-server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6570)
    ap.add_argument("--store", default="mem://",
                    help="mem:// or a directory path for the native "
                         "durable store")
    ap.add_argument("--workers", type=int, default=32)
    args = ap.parse_args(argv)
    server, ctx = serve(args.host, args.port, args.store,
                        max_workers=args.workers)
    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True
        server.stop(grace=2)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    server.wait_for_termination()
    ctx.shutdown()


if __name__ == "__main__":
    main()
