"""Server boot: flags -> store -> context -> gRPC serve.

Reference: hstream/app/server.hs:36-149 (optparse flags; boot = logger ->
store client -> init checkpoint log -> gRPC event loop).
"""

from __future__ import annotations

import argparse
import signal
from concurrent import futures

import grpc

from hstream_tpu.common.logger import get_logger
from hstream_tpu.proto.rpc import add_hstream_api_to_server
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.handlers import HStreamApiServicer
from hstream_tpu.store import open_store

log = get_logger("main")


def _build_mesh(shape: str):
    """'DxK' -> a (data, key) jax mesh over the first D*K devices."""
    from hstream_tpu.parallel import make_mesh

    n_data, _, n_key = shape.lower().partition("x")
    return make_mesh(n_data=int(n_data), n_key=int(n_key or 1))


def serve(host: str = "127.0.0.1", port: int = 6570,
          store_uri: str = "mem://", *, max_workers: int = 32,
          mesh_shape: str | None = None
          ) -> tuple[grpc.Server, ServerContext]:
    """Start a server; returns (grpc_server, ctx). Caller owns shutdown.

    `mesh_shape` ("DxK", e.g. "4x2") shards eligible aggregate queries
    over a (data, key) device mesh (SURVEY §2.3)."""
    store = open_store(store_uri)
    mesh = _build_mesh(mesh_shape) if mesh_shape else None
    ctx = ServerContext(store, host=host, port=port, mesh=mesh)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                 ("grpc.max_send_message_length", 64 * 1024 * 1024)])
    servicer = HStreamApiServicer(ctx)
    add_hstream_api_to_server(servicer, server)
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind {host}:{port}")
    ctx.port = bound
    # only after a successful bind: a failed boot (port in use) must not
    # relaunch tasks and re-emit at-least-once rows before dying
    servicer.resume_persisted()
    server.start()
    log.info("hstream-tpu server listening on %s:%d (store %s)",
             host, bound, store_uri)
    return server, ctx


def main(argv=None) -> None:
    ap = argparse.ArgumentParser("hstream-tpu-server")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=6570)
    ap.add_argument("--store", default="mem://",
                    help="mem:// or a directory path for the native "
                         "durable store")
    ap.add_argument("--workers", type=int, default=32)
    ap.add_argument("--mesh", default=None, metavar="DxK",
                    help="shard aggregate queries over a (data, key) "
                         "device mesh, e.g. 4x2 (needs D*K devices)")
    args = ap.parse_args(argv)
    server, ctx = serve(args.host, args.port, args.store,
                        max_workers=args.workers, mesh_shape=args.mesh)
    stop = {"flag": False}

    def on_signal(signum, frame):
        stop["flag"] = True
        server.stop(grace=2)

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    server.wait_for_termination()
    ctx.shutdown()


if __name__ == "__main__":
    main()
