"""ServerContext: the one object every handler reaches through.

Reference: `ServerContext` bundles the LD client, ZK handle, and the
MVar maps of running queries / connectors / subscriptions
(Handler/Common.hs:85-115). Here it bundles the log store, stream
namespace, checkpoint store, metadata persistence, view registry,
subscription registry and the running-task maps.
"""

from __future__ import annotations

import threading

from hstream_tpu.server.persistence import (
    MemPersistence,
    Persistence,
    StorePersistence,
)
from hstream_tpu.server.subscriptions import SubscriptionRegistry
from hstream_tpu.server.views import ViewRegistry
from hstream_tpu.store.api import LogStore
from hstream_tpu.store.checkpoint import LogCheckpointStore
from hstream_tpu.store.streams import StreamApi

# canonical overlapped-ingest defaults; every consumer (serve() flags,
# QueryTask fallbacks) imports these so they cannot drift
DEFAULT_PIPELINE_DEPTH = 4
DEFAULT_ENCODE_WORKERS = 2
# append-front lanes behind the framed columnar append path (ignored
# on stores with their own completion queue — see server/appendfront)
DEFAULT_APPEND_LANES = 2


class ServerContext:
    def __init__(self, store: LogStore, *,
                 persistence: Persistence | None = None,
                 host: str = "127.0.0.1", port: int = 6570,
                 server_id: int = 1, durable_meta: bool = True,
                 mesh=None,
                 pipeline_depth: int = DEFAULT_PIPELINE_DEPTH,
                 encode_workers: int = DEFAULT_ENCODE_WORKERS,
                 credit_window: int | None = None,
                 slow_request_ms: float = 1000.0,
                 append_lanes: int = DEFAULT_APPEND_LANES,
                 trace_sample: float = 0.0,
                 health_degraded_ms: float | None = None,
                 health_stalled_ms: float | None = None,
                 load_report_interval_ms: float | None = None,
                 placer_interval_ms: float | None = None,
                 heartbeat_lease_ms: float | None = None,
                 pack_queries: bool = False,
                 device_time_sample: int = 0,
                 read_max_staleness_ms: float | None = None,
                 read_cache_bytes: int = 64 << 20,
                 owns_store: bool = True):
        self.store = store
        # in-process multi-node clusters share ONE store across several
        # contexts; only the context that opened it may close it
        self.owns_store = owns_store
        # optional jax.sharding.Mesh: when set, eligible aggregate
        # queries execute sharded over it (parallel.ShardedQueryExecutor)
        self.mesh = mesh
        # overlapped-ingest tuning shared by every query task: staging
        # ring depth (batches encoded ahead of the ordered step loop)
        # and host-encode worker count (server --pipeline-depth /
        # --encode-workers)
        self.pipeline_depth = max(int(pipeline_depth), 1)
        self.encode_workers = max(int(encode_workers), 1)
        self.streams = StreamApi(store)
        self.streams.ensure_checkpoint_log()
        self.ckp_store = LogCheckpointStore(store)
        if persistence is None:
            persistence = (StorePersistence(store) if durable_meta
                           else MemPersistence())
        self.persistence = persistence
        self.views = ViewRegistry()
        self.subscriptions = SubscriptionRegistry()
        # read plane (ISSUE 20): version-validated snapshot cache for
        # pull queries + the shared-encode expansion cache subscription
        # fan-out rides on; budget 0 disables caching entirely
        from hstream_tpu.server.readcache import ReadCache

        self.read_cache = (ReadCache(
            max_bytes=int(read_cache_bytes),
            max_staleness_ms=read_max_staleness_ms)
            if int(read_cache_bytes) > 0 else None)
        # query_id -> QueryTask; connector_id -> ConnectorTask
        self.running_queries: dict[str, object] = {}
        self.running_connectors: dict[str, object] = {}
        from hstream_tpu.common import locktrace

        self.lock = locktrace.lock("context.running")
        self.host = host
        self.port = port
        self.server_id = server_id
        from hstream_tpu.stats import StatsHolder
        from hstream_tpu.stats.events import EventJournal
        from hstream_tpu.store.versioned import VersionedConfigStore

        self.stats = StatsHolder()
        # runtime face of the retrace contract (ISSUE 7): every XLA
        # compile in this process bumps kernel_recompiles, so a
        # steady-state recompile regression is visible on /metrics
        from hstream_tpu.common.tracing import install_recompile_counter

        install_recompile_counter(self.stats)
        # observability plane: structured event journal + the slow-
        # request threshold handlers log correlated warnings above
        self.events = EventJournal()
        # sampler-style gauge: the holder calls it at scrape time
        self.stats.gauge_fn("event_journal_size", "",
                            lambda: len(self.events))
        if self.read_cache is not None:
            self.stats.gauge_fn("read_cache_hit_ratio", "",
                                self.read_cache.hit_ratio)
            self.stats.gauge_fn("read_cache_bytes", "",
                                self.read_cache.nbytes)
        self.slow_request_ms = float(slow_request_ms)
        # cross-component trace spans (ISSUE 13): bounded per-scope
        # rings + the --trace-sample knob; disarmed (rate 0) cost is
        # one attribute read + one branch at every probe site
        from hstream_tpu.common.tracing import SpanCollector

        self.tracing = SpanCollector(sample_rate=trace_sample)
        # device cost plane (ISSUE 18): the compiled-program inventory
        # hooks the process-wide compile funnel (idempotent), and the
        # per-dispatch device-time sampler observes into this holder —
        # armed only when --device-time-sample > 0 (disarmed cost: one
        # attribute read + one branch per kernel_family scope)
        from hstream_tpu.stats.devicecost import DEVICE_TIME, PROGRAMS

        PROGRAMS.install()
        DEVICE_TIME.add_sink(self.stats)
        self.device_time_sample = max(int(device_time_sample), 0)
        if self.device_time_sample > 0:
            DEVICE_TIME.arm(self.device_time_sample)
        # flight recorder (ISSUE 18): postmortem bundles captured at
        # the STALLED / crash-loop edges, surviving query deletion
        from hstream_tpu.server.flightrec import FlightRecorder

        self.flightrec = FlightRecorder(self)
        # per-query health plane (ISSUE 13): progress memory + verdict
        # transitions behind GET /queries/<id>/health, admin health,
        # and the query_health_level gauge
        from hstream_tpu.server.health import (
            DEGRADED_AFTER_MS,
            STALLED_AFTER_MS,
            HealthTracker,
        )

        self.health = HealthTracker()
        self.health_degraded_ms = float(
            DEGRADED_AFTER_MS if health_degraded_ms is None
            else health_degraded_ms)
        self.health_stalled_ms = float(
            STALLED_AFTER_MS if health_stalled_ms is None
            else health_stalled_ms)
        # a replicated store journals degraded acks / follower loss;
        # the leadership binding itself is the first journal entry, so
        # `admin events --kind leader_change` answers "who leads this
        # store, since when" on the serving node
        if hasattr(store, "follower_status"):
            store.journal = self.events
            # fenced_appends / promotions counters + the epoch gauge
            # sample through this binding (stats/prometheus.py)
            store.stats = self.stats
            self.events.append(
                "leader_change",
                f"this server leads the replicated store as "
                f"{store.node_id} (epoch {store.epoch})",
                leader=store.node_id, epoch=store.epoch)
        # producer-stamped appends on a NON-replicated store serialize
        # their lookup+append+record through this lock (the replicated
        # store has its own critical section; store/dedup.py)
        self.dedup_lock = locktrace.lock("context.dedup")
        # wire-speed ingest (ISSUE 12): framed columnar appends go
        # through sharded lanes feeding the store's completion-queue
        # path, so the RPC thread validates the NEXT block while the
        # previous one fsyncs
        from hstream_tpu.server.appendfront import AppendFront

        self.append_front = AppendFront(store, lanes=append_lanes)
        # CAS-versioned cluster config (reference VersionedConfigStore);
        # first consumer: the boot-epoch counter below — each server
        # boot on a store CAS-increments it, so concurrent servers on
        # one store lose the race visibly instead of corrupting state
        self.config = VersionedConfigStore(store)
        self.boot_epoch = self._bump_boot_epoch()
        # flow control: admission quotas + overload shedding + delivery
        # credit windows; quotas persist in the versioned config store
        # (and therefore replicate/survive restart with it)
        from hstream_tpu.flow import DEFAULT_CREDIT_WINDOW, FlowGovernor

        self.flow = FlowGovernor(
            config=self.config, stats=self.stats, events=self.events,
            credit_window=(DEFAULT_CREDIT_WINDOW if credit_window is None
                           else credit_window))
        self.flow.load()
        # chaos harness: the process-wide fault registry journals every
        # injection here; HSTREAM_FAULTS in the environment arms sites
        # for the whole server (admin fault-set does it at runtime)
        from hstream_tpu.common.faultinject import FAULTS

        self.faults = FAULTS
        FAULTS.bind_events(self.events)
        FAULTS.load_env()
        # lock-order witness (ISSUE 14): the named traced locks above
        # (append front, supervisor, subscriptions, tasks, replica,
        # gateway) report into this registry when armed — per-lock
        # wait/hold histograms + contention on /metrics, lock_cycle
        # events in the journal, `admin locks` for the ledger.
        # HSTREAM_LOCKTRACE=1 / --locktrace arms it for the process.
        from hstream_tpu.common.locktrace import LOCKTRACE

        self.locktrace = LOCKTRACE
        LOCKTRACE.bind(stats=self.stats, events=self.events)
        LOCKTRACE.load_env()
        # self-healing supervision: tasks report unexpected deaths here;
        # the servicer binds resume_fn once handlers exist
        from hstream_tpu.server.scheduler import QuerySupervisor

        self.supervisor = QuerySupervisor(self)
        # cluster stats plane (ISSUE 15): periodic node_load_report
        # journal events — one bounded holder fold per interval, the
        # machine-readable load signal the thousand-query placer gates
        # on. Always on (a node that stops reporting load is invisible
        # to placement); the interval is tunable for tests/CI.
        # Constructed here, STARTED by serve() after the port binds —
        # the boot report must carry the node's real (bound) identity.
        from hstream_tpu.stats.cluster import (
            DEFAULT_LOAD_REPORT_INTERVAL_S,
            LoadReporter,
        )

        self.load_reporter = LoadReporter(
            self, interval_s=(DEFAULT_LOAD_REPORT_INTERVAL_S
                              if load_report_interval_ms is None
                              else load_report_interval_ms / 1000.0))
        # the placer (ISSUE 17): placement + live failover adoption +
        # rebalance over the CAS scheduler records. Constructed always
        # (admin `placer` and /metrics read its status), ARMED only when
        # --placer-interval-ms is set — disarmed it never heartbeats,
        # never publishes node records and never sweeps, so single-node
        # deployments keep the pure boot-epoch adoption semantics.
        # Started by serve() after the port binds, like the reporter.
        from hstream_tpu.placer import DEFAULT_LEASE_MS, PackPool, Placer

        self.heartbeat_lease_ms = int(
            DEFAULT_LEASE_MS if heartbeat_lease_ms is None
            else heartbeat_lease_ms)
        self.placer = Placer(self, interval_ms=placer_interval_ms,
                             lease_ms=self.heartbeat_lease_ms)
        # the placer clamps a lease shorter than 3 ticks (a healthy
        # owner must never look dead between heartbeats); health and
        # the boot-time live-peer guard must judge by the SAME lease
        self.heartbeat_lease_ms = self.placer.lease_ms
        # co-compile packing: compatible queries share one executor /
        # one dispatch (ISSUE 17c); opt-in via --pack-queries
        self.pack_pool = PackPool(self) if pack_queries else None
        # the checkpoint-log replay above (LogCheckpointStore) happened
        # before the journal existed: surface any corrupt entries it
        # had to skip as a queryable event now
        skipped = getattr(self.ckp_store, "replay_skipped", 0)
        if skipped:
            self.events.append(
                "checkpoint_corrupt",
                f"checkpoint-log replay skipped {skipped} corrupt "
                f"entries; affected readers rewind and replay",
                skipped=skipped)

    def _bump_boot_epoch(self) -> int:
        from hstream_tpu.store.versioned import VersionMismatch

        for _ in range(16):
            cur = self.config.get("cluster/boot_epoch")
            try:
                if cur is None:
                    self.config.put("cluster/boot_epoch", b"1")
                    return 1
                version, raw = cur
                epoch = int(raw) + 1
                self.config.put("cluster/boot_epoch",
                                str(epoch).encode(),
                                base_version=version)
                return epoch
            except VersionMismatch:
                continue
        raise RuntimeError("boot-epoch CAS kept losing; another server "
                           "is racing this store")

    def shutdown(self) -> None:
        # stop the placer before the supervisor: a placement/adoption
        # sweep racing shutdown would relaunch or move a query the
        # loop below is about to stop
        placer = getattr(self, "placer", None)
        if placer is not None:
            try:
                placer.stop()
            except Exception:
                pass
        pool = getattr(self, "pack_pool", None)
        if pool is not None:
            try:
                pool.stop()
            except Exception:
                pass
        rep = getattr(self, "load_reporter", None)
        if rep is not None:
            try:
                rep.stop()
            except Exception:
                pass
        # stop the supervisor FIRST: a restart racing shutdown would
        # relaunch a task the loop below just stopped
        sup = getattr(self, "supervisor", None)
        if sup is not None:
            try:
                sup.shutdown()
            except Exception:
                pass
        httpd = getattr(self, "metrics_httpd", None)
        if httpd is not None:
            try:
                httpd.shutdown()
                httpd.server_close()  # release the listening socket
            except Exception:
                pass
        for task in list(self.running_queries.values()):
            try:
                # detach: snapshot state but leave status RUNNING so the
                # next boot's resume_persisted relaunches the query
                task.stop(detach=True)
            except Exception:
                pass
        for task in list(self.running_connectors.values()):
            try:
                task.stop()
            except Exception:
                pass
        for rt in self.subscriptions.list():
            rt.shutdown()
        front = getattr(self, "append_front", None)
        if front is not None:
            # drain the append lanes BEFORE the store closes: a lane
            # worker mid-append against a closed store would fail an
            # acknowledged-in-flight batch
            front.close()
        if self.owns_store:
            self.store.close()
