"""Declarative stat families: the ``.inc`` X-macro analogue, enforced.

Reference: adding a per-stream metric is ONE line in
``per_stream_time_series.inc`` — the registry, the holder wiring, and
the admin aggregation all derive from it at compile time
(common/include/per_stream_time_series.inc:24-40). Python cannot get
that from the compiler, so this table is the single declaration point
and two mechanisms restore the property:

  * ``StatsHolder.stat_add`` auto-creates a MultiLevelTimeSeries from
    the row here (unknown family -> KeyError, even on a cold path);
  * the analyzer's registry pass (rule ``registry-family``) machine-
    checks that every literal ``stat_add``/``stat_rate``/... call site
    in the production tree names a declared family, and that every
    declared family has at least one call site (``registry-dead``).

One row declares: the family name, its scope (the entity kind the key
labels — ``stream`` / ``subscription`` / ``query``), the unit the
values carry, and the HELP text the exposition serves. Every family
gets the full default ladder (60x1s / 60x10s / 60x60s + all-time);
rates surface per entity via ``admin stats <scope>s --interval ...``,
``GET /stats``, the ``stream_rate`` exposition ladder, and the
``NodeStatsReport`` federation fold (stats/cluster.py).
"""

from __future__ import annotations

from typing import NamedTuple


class StatFamily(NamedTuple):
    name: str
    scope: str  # "stream" | "subscription" | "query"
    unit: str
    help: str


# ---- the table (one line per family; keep scopes grouped) ------------------

STAT_FAMILIES = [
    # per-stream ingest/egress (the reference's appends/reads ladders)
    StatFamily("append_in_bytes", "stream", "bytes",
               "append byte rate over the trailing window"),
    StatFamily("append_in_records", "stream", "records",
               "append record rate over the trailing window"),
    StatFamily("record_bytes", "stream", "bytes",
               "read byte rate over the trailing window"),
    StatFamily("read_out_records", "stream", "records",
               "read record rate over the trailing window"),
    # per-subscription delivery (reference subscription_time_series)
    StatFamily("delivered_records", "subscription", "records",
               "records delivered to consumers over the trailing "
               "window"),
    StatFamily("delivered_bytes", "subscription", "bytes",
               "payload bytes delivered to consumers over the "
               "trailing window"),
    StatFamily("acks_received", "subscription", "records",
               "record acknowledgements received over the trailing "
               "window"),
    # per-query emission (the close-cycle heartbeat of a continuous
    # query: rows on the wire and cycles completed)
    StatFamily("emit_rows", "query", "rows",
               "aggregate rows emitted over the trailing window"),
    StatFamily("close_cycles", "query", "cycles",
               "window close cycles emitted over the trailing window"),
    # multi-chip execution (ISSUE 16): device dispatches that ran
    # under shard_map — the rate a sharded query's fused kernels hit
    # the mesh (zero for single-chip queries)
    StatFamily("sharded_dispatches", "query", "dispatches",
               "device dispatches executed under shard_map over the "
               "trailing window"),
]

FAMILY_NAMES = frozenset(f.name for f in STAT_FAMILIES)
FAMILY_BY_NAME = {f.name: f for f in STAT_FAMILIES}
FAMILY_SCOPES = ("stream", "subscription", "query")


def families_for_scope(scope: str) -> list[StatFamily]:
    if scope not in FAMILY_SCOPES:
        raise KeyError(f"unknown stat scope {scope!r} "
                       f"(one of {FAMILY_SCOPES})")
    return [f for f in STAT_FAMILIES if f.scope == scope]
