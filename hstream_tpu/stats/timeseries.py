"""MultiLevelTimeSeries: fixed-ring rate ladders (folly's shape).

Reference: every per-stream stat feeds a folly ``MultiLevelTimeSeries``
— a small stack of ring buffers at widening bucket widths plus an
all-time accumulator, so "appends/s over the last minute / 10 minutes /
hour" is one O(levels) read with no per-query scan (stats.h:56-118).
The previous reproduction kept a single dict ring of 1s buckets pruned
by comprehension on the add path — per-add dict churn, one window, and
an O(window) sum per query.

Here each level is a pair of fixed lists (sums, counts) over ``n``
buckets of ``width_s`` seconds. ``add`` is O(1): integer-divide now
into a bucket index, lazily rotate the ring forward (work is bounded by
the ring size and amortizes to O(1) across adds), bump one slot. A
query first rotates to *its* now, then folds the ring — so the value is
EXACTLY "sum of adds whose second lands in the trailing ``n`` bucket
slots aligned to ``width_s``", the property the brute-force tests
recount (tests/test_cluster_stats.py).

Late adds land in their own bucket when it is still inside the ring and
are dropped from the levels (never from the all-time sum/count) once
older than the widest slot — time never flows backwards through a ring.
"""

from __future__ import annotations

import threading
import time

# (bucket seconds, bucket count) per level, narrow -> wide: 60 x 1s,
# 60 x 10s, 60 x 60s — the reference ladder — plus the implicit
# all-time level (sum/count since process start).
DEFAULT_LEVELS = ((1, 60), (10, 60), (60, 60))

# operator-facing names for the default ladder's trailing windows
INTERVALS = {"1min": 0, "10min": 1, "1h": 2}
INTERVAL_NAMES = tuple(INTERVALS)  # declaration order: narrow -> wide


def level_for_window(window_s: float,
                     levels=DEFAULT_LEVELS) -> int:
    """Index of the narrowest level whose trailing window covers
    ``window_s`` seconds (the widest level when none does)."""
    for i, (width, n) in enumerate(levels):
        if width * n >= window_s:
            return i
    return len(levels) - 1


class _Level:
    """One fixed ring: ``n`` buckets of ``width_s`` seconds. The owner
    (MultiLevelTimeSeries) holds the lock; nothing here locks."""

    __slots__ = ("width", "n", "sums", "counts", "cur", "head")

    def __init__(self, width_s: int, n_buckets: int):
        self.width = int(width_s)
        self.n = int(n_buckets)
        self.sums = [0.0] * self.n
        self.counts = [0] * self.n
        # bucket index (seconds // width) the head slot represents;
        # -1 = empty ring (first add claims its bucket without rotating
        # through the whole span since the epoch)
        self.cur = -1
        self.head = 0

    def rotate(self, bucket: int) -> None:
        """Advance the ring so ``bucket`` is the head slot, zeroing
        every slot rolled past. Work is capped at ``n`` slot clears no
        matter how long the series sat idle (a gap wider than the ring
        clears it whole)."""
        if self.cur < 0:
            self.cur = bucket
            return
        steps = bucket - self.cur
        if steps <= 0:
            return
        if steps >= self.n:
            for i in range(self.n):
                self.sums[i] = 0.0
                self.counts[i] = 0
            self.head = 0
        else:
            for _ in range(steps):
                self.head = (self.head + 1) % self.n
                self.sums[self.head] = 0.0
                self.counts[self.head] = 0
        self.cur = bucket

    def add(self, value: float, bucket: int) -> None:
        if bucket >= self.cur or self.cur < 0:
            self.rotate(bucket)
            self.sums[self.head] += value
            self.counts[self.head] += 1
            return
        # late add: its bucket may still be inside the ring
        offset = self.cur - bucket
        if offset < self.n:
            i = (self.head - offset) % self.n
            self.sums[i] += value
            self.counts[i] += 1
        # older than the ring: dropped from this level (all-time
        # accumulation happens in the owner)

    def total(self) -> tuple[float, int]:
        return sum(self.sums), sum(self.counts)


class MultiLevelTimeSeries:
    """Fixed-ring rate ladder + all-time sum/count; thread-safe.

    ``add`` touches one slot per level under one lock — no allocation,
    no dict churn, no pruning pass. Queries (``rate``/``sum``/``avg``/
    ``count``) take a level index or interval name ("1min"/"10min"/
    "1h") and fold that level's ring after rotating it to now.
    """

    __slots__ = ("levels", "total_sum", "total_count", "_lock")

    def __init__(self, levels=DEFAULT_LEVELS):
        self.levels = tuple(_Level(w, n) for w, n in levels)
        self.total_sum = 0.0
        self.total_count = 0
        self._lock = threading.Lock()

    def _level(self, level) -> _Level:
        if isinstance(level, str):
            try:
                level = INTERVALS[level]
            except KeyError:
                raise KeyError(f"unknown interval {level!r} "
                               f"(one of {INTERVAL_NAMES})") from None
        return self.levels[level]

    def add(self, value: float, now: float | None = None) -> None:
        sec = int(now if now is not None else time.time())
        v = float(value)
        with self._lock:
            self.total_sum += v
            self.total_count += 1
            for lv in self.levels:
                lv.add(v, sec // lv.width)

    def sum(self, level=0, now: float | None = None) -> float:
        """Sum of adds over the level's trailing window."""
        sec = int(now if now is not None else time.time())
        lv = self._level(level)
        with self._lock:
            lv.rotate(sec // lv.width)
            return sum(lv.sums)

    def count(self, level=0, now: float | None = None) -> int:
        sec = int(now if now is not None else time.time())
        lv = self._level(level)
        with self._lock:
            lv.rotate(sec // lv.width)
            return sum(lv.counts)

    def avg(self, level=0, now: float | None = None) -> float:
        """Mean add value over the window (0.0 while empty)."""
        sec = int(now if now is not None else time.time())
        lv = self._level(level)
        with self._lock:
            lv.rotate(sec // lv.width)
            s, c = lv.total()
        return s / c if c else 0.0

    def rate(self, level=0, now: float | None = None) -> float:
        """Per-second rate over the level's trailing window."""
        lv = self._level(level)
        return self.sum(level, now) / float(lv.width * lv.n)

    def all_time(self) -> tuple[float, int]:
        """(sum, count) since construction — never windowed."""
        with self._lock:
            return self.total_sum, self.total_count

    def ladder(self, now: float | None = None) -> dict[str, float]:
        """Every interval's per-second rate plus the all-time sum —
        the NodeStatsReport / stream_rate exposition shape."""
        out = {name: self.rate(i, now) for name, i in INTERVALS.items()}
        s, c = self.all_time()
        out["total"] = s
        out["total_count"] = float(c)
        return out
