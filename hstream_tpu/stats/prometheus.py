"""Prometheus text exposition over the stats holder + live subsystems.

Renders every registered counter, time-series rate, gauge, and
histogram in the text format scrapers expect (text/plain; version
0.0.4): `_total` counters, `_bucket`/`_sum`/`_count` histogram series
with cumulative `le` buckets ending at `+Inf`, label values escaped
per the spec (backslash, double-quote, newline).

`sample_gauges(ctx)` is the scrape-time bridge from live subsystems —
pipeline occupancy / reorder depth per running query, subscription
backlog and delivery credits in flight, the overload ladder state,
replica ack lag, and the durable store's segment/WAL footprint — into
the holder's gauge registry; `render_metrics(ctx)` samples and renders
in one call (the gateway's /metrics, the server's --metrics-port
exporter, and the admin `metrics` verb all go through it).
"""

from __future__ import annotations

import os
import threading
import time

from hstream_tpu.stats import (
    GAUGES,
    HIST_LABEL_KEYS,
    PER_STREAM_COUNTERS,
    TS_OVERFLOW_LABEL,
)
from hstream_tpu.stats.families import STAT_FAMILIES, families_for_scope
from hstream_tpu.stats.timeseries import INTERVAL_NAMES

PREFIX = "hstream"

# counters whose series label is a QUERY id, not a stream name: they
# live outside the stream namespace, so the live-stream filter must
# not drop them (same rationale as "_"-prefixed pseudo-streams). A
# restart/fallback series for a crash-looped (FAILED, detached) query
# especially must survive the scrape — it is the evidence an operator
# scrapes FOR. kernel_recompiles joins the set with ISSUE 13's named
# RetraceGuard attribution (a compile observed under a named guard
# counts against that query/bench scope, not only `_process`).
QUERY_LABEL_COUNTERS = frozenset({"query_restarts", "snapshot_fallbacks",
                                  "late_drops", "kernel_recompiles",
                                  "placement_decisions",
                                  "queries_adopted"})

# counters whose label is a closed vocabulary outside both the stream
# and query namespaces (kernel families): never liveness-filtered
FAMILY_LABEL_COUNTERS = frozenset({"factory_recompiles"})

# counters labeled by a traced-lock ROLE name (locktrace witness):
# lock roles are a small closed set named in code, not streams —
# the liveness filter must not drop them (ISSUE 14)
LOCK_LABEL_COUNTERS = frozenset({"lock_contention"})

_HELP = {
    "append_payload_bytes": "bytes appended (payload only)",
    "append_total": "append batches accepted",
    "append_failed": "append batches failed",
    "append_throttled": "appends refused by quota (flow control)",
    "shed_total": "requests refused by overload shedding",
    "delivery_credit_waits": "push deliveries paused at zero credit",
    "record_payload_bytes": "bytes read out by consumers/queries",
    "record_total": "records read",
    "json_decode_native": "JSON records decoded by the native batch "
                          "decoder (libjsondec)",
    "json_decode_fallback": "JSON records decoded by the per-record "
                            "Python fallback",
    "join_probe_dispatches": "device interval-join probe dispatches "
                             "(one per join micro-batch)",
    "change_rows_columnar": "emitted aggregate rows that reached the "
                            "sink columnar (no per-row dicts)",
    "kernel_recompiles": "XLA executable builds observed at runtime "
                         "(zero in steady state)",
    "query_restarts": "supervisor-initiated query restarts",
    "snapshot_fallbacks": "restores that skipped a corrupt snapshot "
                          "slot for the previous good one",
    "device_path_fallbacks": "device kernel activations degraded to "
                             "the host reference path",
    "promotions": "replica promotions driven through this server",
    "fenced_appends": "mutations refused NOT_LEADER after the store "
                      "was fenced by a higher epoch",
    "append_deduped": "producer-stamped appends answered from the "
                      "dedup window (retries landed exactly once)",
    "append_columnar_rows": "rows ingested through the framed columnar "
                            "append path",
    "late_drops": "records dropped as late (past the window close "
                  "boundary at the pre-batch watermark)",
    "device_h2d_bytes": "host-to-device bytes on the staging path",
    "device_d2h_bytes": "device-to-host bytes on the close/changelog "
                        "drain paths",
    "factory_recompiles": "XLA executable builds attributed to the "
                          "kernel family whose dispatch triggered them",
    "stream_rate": "per-stream family rate ladder: records|bytes per "
                   "second over the named trailing interval "
                   "(1min/10min/1h), sampled at scrape",
    "node_rss_bytes": "resident set size of this server process",
    "append_inflight": "framed appends submitted to the append front "
                       "but not yet completed",
    "pipeline_occupancy": "ingest pipeline busy fraction per query",
    "pipeline_reorder_depth": "staged-but-unstepped batches per query",
    "sub_backlog": "subscription lag in LSNs (tail - committed)",
    "credit_inflight": "delivery credits in flight per subscription",
    "overload_level": "shed ladder: 0 admit / 1 defer / 2 reject",
    "replica_ack_lag": "op-log entries a follower is behind",
    "store_segment_bytes": "durable store segment bytes on disk",
    "store_wal_bytes": "durable store write-ahead-log bytes on disk",
    "running_queries": "live query tasks on this server",
    "event_journal_size": "entries held by the event journal",
    "crash_loop_open": "1 while the crash-loop breaker holds a query "
                       "FAILED",
    "replica_epoch": "leadership epoch of the replicated store this "
                     "server fronts",
    "dedup_window_size": "producer-dedup seqs remembered across all "
                         "producers",
    "query_watermark_ms": "event-time watermark of the query's "
                          "executor (absolute ms)",
    "query_watermark_lag_ms": "wall clock minus the query's event-time "
                              "watermark (answer staleness)",
    "query_health_level": "health-plane verdict: 0 OK / 1 DEGRADED / "
                          "2 STALLED",
    "mesh_shards": "key-axis shard count of the mesh the query's "
                   "executor runs on (absent for single-chip queries)",
    "append_latency_ms": "Append RPC latency",
    "fetch_latency_ms": "Fetch RPC latency",
    "sql_execute_latency_ms": "ExecuteQuery RPC latency",
    "stage_latency_ms": "per-stage query pipeline timings",
    "emit_latency_ms": "close-cycle event time to emitted rows on the "
                       "wire (per query)",
    "append_visible_latency_ms": "record publish time to visibility "
                                 "(view/sink emit, or subscription "
                                 "delivery)",
    "freshness_lag_ms": "end-to-end lag attributed per stage "
                        "(ingest / engine / delivery)",
    "kernel_dispatch_ms": "host dispatch time per kernel family "
                          "(step / close / probe / session)",
    "lock_contention": "traced-lock acquires that found the lock "
                       "taken (lock-order witness armed)",
    "placement_decisions": "placer decisions written onto "
                           "scheduler/query/* (place, live adopt, or "
                           "rebalance offer)",
    "queries_adopted": "queries claimed live through the heartbeat-"
                       "lease CAS (boot adoption not included)",
    "placer_node_score": "placer load score per cluster node folded "
                         "from its published node record (lower = "
                         "preferred)",
    "lock_wait_ms": "time spent waiting to acquire each named traced "
                    "lock (lock-order witness armed)",
    "lock_hold_ms": "time each named traced lock was held per "
                    "critical section (lock-order witness armed)",
    "device_hbm_bytes": "device bytes held by the query's live "
                        "arenas/stores (exact nbytes fold, zero "
                        "added dispatches)",
    "device_arena_bytes": "device bytes of one named arena/store "
                          "plane of a query",
    "device_hbm_total_bytes": "process total of device_hbm_bytes "
                              "across all live queries",
    "device_hbm_backend_bytes": "bytes-in-use per the backend "
                                "allocator's memory_stats() (absent "
                                "where the platform reports none)",
    "kernel_device_ms": "device execution time per kernel family "
                        "(fenced block-until-ready on a deterministic "
                        "1/N dispatch sample, --device-time-sample)",
    "read_extracts": "pull-query serves that actually ran an executor "
                     "peek (~one per view per close cycle, not one "
                     "per reader)",
    "read_cache_hit_ratio": "snapshot-cache hit ratio over all "
                            "versioned pull-query serves",
    "read_cache_bytes": "bytes held by the read-plane snapshot + "
                        "shared-encode LRU (--read-cache-bytes)",
}

# rate-family HELP text lives on the declaration itself (the one-line
# `.inc` property: declaring a family brings its exposition docs)
_HELP.update({f.name: f.help for f in STAT_FAMILIES})


def escape_label_value(v: str) -> str:
    """Label-value escaping per the exposition format: backslash,
    double-quote, and newline."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value: float) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _series(name: str, labels: dict[str, str], value: float) -> str:
    if labels:
        inner = ",".join(f'{k}="{escape_label_value(v)}"'
                         for k, v in labels.items())
        return f"{name}{{{inner}}} {_fmt(value)}"
    return f"{name} {_fmt(value)}"


def _header(lines: list[str], name: str, mtype: str, help_key: str
            ) -> None:
    help_text = _HELP.get(help_key, help_key)
    lines.append(f"# HELP {name} {help_text}")
    lines.append(f"# TYPE {name} {mtype}")


def render_holder(stats, *, live_streams=None, live_queries=None) -> str:
    """Exposition text for one StatsHolder: counters (`_total`), rates
    (gauge), gauges, histograms. `live_streams` (optional set) filters
    counter/rate series to streams that still exist, like GetStats;
    `live_queries` (optional set of query ids, ANY status — a
    crash-looped FAILED query must keep its evidence) likewise bounds
    the QUERY_LABEL_COUNTERS series so deleted queries don't grow the
    exposition forever."""
    lines: list[str] = []
    for metric in PER_STREAM_COUNTERS:
        name = f"{PREFIX}_{metric}" \
            if metric.endswith("_total") else f"{PREFIX}_{metric}_total"
        _header(lines, name, "counter", metric)
        for stream, v in sorted(stats.stream_stat_getall(metric).items()):
            # "_"-prefixed labels are process-scoped pseudo-streams
            # (kernel_recompiles{stream="_process"}),
            # QUERY_LABEL_COUNTERS series are labeled by query id, and
            # FAMILY_LABEL_COUNTERS by a closed kernel-family
            # vocabulary: none is in the stream namespace, so the
            # STREAM liveness filter must not drop them — query-
            # labeled series are bounded by query existence instead
            if not stream.startswith("_") \
                    and metric not in FAMILY_LABEL_COUNTERS \
                    and metric not in LOCK_LABEL_COUNTERS:
                if metric in QUERY_LABEL_COUNTERS:
                    if (live_queries is not None
                            and stream not in live_queries):
                        continue
                elif (live_streams is not None
                        and stream not in live_streams):
                    continue
            lines.append(_series(name, {"stream": stream}, v))
    for fam in STAT_FAMILIES:
        name = f"{PREFIX}_{fam.name}_rate"
        _header(lines, name, "gauge", fam.name)
        for key in stats.stat_keys(fam.name):
            # ONLY the reserved overflow fold is exempt from liveness
            # filtering: the bounded-cardinality aggregate must stay
            # visible exactly when the cap engages (a broader "_"
            # exemption would let "_"-named entities render forever)
            if key != TS_OVERFLOW_LABEL:
                if fam.scope == "stream" and live_streams is not None \
                        and key not in live_streams:
                    continue
                if fam.scope == "query" and live_queries is not None \
                        and key not in live_queries:
                    continue
            lines.append(_series(name, {fam.scope: key},
                                 stats.stat_rate(fam.name, key)))
    # the multi-interval ladder of every stream-scoped family in one
    # place: stream_rate{stream,metric,interval} — cardinality bounded
    # by the per-family series cap (TS_MAX_LABELS overflow fold), 3
    # intervals per (stream, family) pair
    name = f"{PREFIX}_stream_rate"
    _header(lines, name, "gauge", "stream_rate")
    for fam in families_for_scope("stream"):
        for key in stats.stat_keys(fam.name):
            if live_streams is not None and key not in live_streams \
                    and key != TS_OVERFLOW_LABEL:
                continue
            for interval in INTERVAL_NAMES:
                lines.append(_series(
                    name, {"stream": key, "metric": fam.name,
                           "interval": interval},
                    stats.stat_rate(fam.name, key, interval)))
    gauges = stats.gauges_snapshot()
    for metric in GAUGES:
        entries = sorted((label, v) for (m, label), v in gauges.items()
                         if m == metric)
        if not entries:
            continue
        name = f"{PREFIX}_{metric}"
        _header(lines, name, "gauge", metric)
        for label, v in entries:
            if metric == "device_arena_bytes" and label:
                # two-dimension gauge (ISSUE 18): the registry key is
                # "qid/plane" (plane names never contain "/"; query
                # ids may, so split from the right)
                qid, _, plane = label.rpartition("/")
                labels = {"query": qid, "plane": plane}
            elif label:
                labels = {_gauge_label_key(metric): label}
            else:
                labels = {}
            lines.append(_series(name, labels, v))
    hists = stats.histograms_snapshot()
    seen_types: set[str] = set()
    for (metric, label), h in sorted(hists.items()):
        name = f"{PREFIX}_{metric}"
        if metric not in seen_types:
            _header(lines, name, "histogram", metric)
            seen_types.add(metric)
        lkey = HIST_LABEL_KEYS.get(metric, "label")
        base = {lkey: label} if label else {}
        cum, total_sum, count = h.snapshot()
        for bound, c in zip(h.bounds, cum):
            lines.append(_series(f"{name}_bucket",
                                 {**base, "le": _fmt(bound)}, c))
        lines.append(_series(f"{name}_bucket", {**base, "le": "+Inf"},
                             count))
        lines.append(_series(f"{name}_sum", base, total_sum))
        lines.append(_series(f"{name}_count", base, count))
    return "\n".join(lines) + "\n"


def _gauge_label_key(metric: str) -> str:
    if metric.startswith(("pipeline_", "query_")) \
            or metric in ("crash_loop_open", "device_hbm_bytes"):
        return "query"
    if metric in ("sub_backlog", "credit_inflight"):
        return "subscription"
    if metric == "replica_ack_lag":
        return "follower"
    if metric == "placer_node_score":
        return "node"
    return "label"


# TTL cache for the store-footprint walk: found by hstream-analyze
# (blocking-hot) — the walk ran on EVERY scrape, so a store with many
# segment files turned each /metrics hit into an unbounded stat storm.
# One walk per root per TTL bounds the scrape path; footprint moves
# slowly, 5s staleness is fine. Concurrent scrapers cannot race a cold
# walk: render_metrics serializes whole scrapes under the holder's
# scrape_lock, so at most one walk runs per expiry.
_DIR_BYTES_TTL_S = 5.0
_dir_bytes_cache: dict[str, tuple[float, tuple[int, int]]] = {}
_dir_bytes_lock = threading.Lock()


def _store_dir_bytes(root: str) -> tuple[int, int]:
    """(segment bytes, wal bytes) under a native store root; cached
    for _DIR_BYTES_TTL_S so scrape cost stays O(live subsystems)."""
    now = time.monotonic()
    with _dir_bytes_lock:
        hit = _dir_bytes_cache.get(root)
        if hit is not None and now - hit[0] < _DIR_BYTES_TTL_S:
            return hit[1]
    seg = wal = 0
    try:
        # analyze: ok blocking-hot — deliberate: one cold walk per TTL
        for dirpath, _dirs, files in os.walk(root):
            for f in files:
                try:
                    # analyze: ok blocking-hot — bounded by the TTL cache
                    size = os.path.getsize(os.path.join(dirpath, f))
                except OSError:
                    continue
                if "wal" in f.lower():
                    wal += size
                else:
                    seg += size
    except OSError:
        pass
    with _dir_bytes_lock:
        # stamp AFTER the walk so a slow walk doesn't eat into the TTL
        _dir_bytes_cache[root] = (time.monotonic(), (seg, wal))
    return seg, wal


def sample_gauges(ctx) -> None:
    """Sample live subsystems into the holder's gauge registry. Called
    at scrape time — a scrape's cost is proportional to the number of
    live queries/subscriptions, never to ingest volume."""
    stats = ctx.stats
    # running query tasks: pipeline occupancy + reorder depth
    tasks = dict(getattr(ctx, "running_queries", {}))
    stats.gauge_set("running_queries", "", len(tasks))
    live_q: set[tuple[str, str]] = set()
    for qid, task in tasks.items():
        pipe = getattr(task, "_pipe", None)
        if pipe is None:
            continue
        try:
            st = pipe.stats()
            occ = max(st.get("encode_occupancy", 0.0),
                      st.get("step_occupancy", 0.0))
            stats.gauge_set("pipeline_occupancy", qid, occ)
            stats.gauge_set("pipeline_reorder_depth", qid, pipe.pending)
            live_q.add(("pipeline_occupancy", qid))
            live_q.add(("pipeline_reorder_depth", qid))
        except Exception:  # noqa: BLE001 — a task tearing down mid-
            continue       # scrape must not fail the scrape
    _drop_stale(stats, ("pipeline_occupancy", "pipeline_reorder_depth"),
                live_q)
    # subscriptions: backlog + credits in flight
    live_s: set[tuple[str, str]] = set()
    for rt in getattr(ctx, "subscriptions").list():
        try:
            tail = ctx.store.tail_lsn(rt.logid)
            stats.gauge_set("sub_backlog", rt.sub_id,
                            max(0, tail - rt.committed_lsn))
            stats.gauge_set("credit_inflight", rt.sub_id,
                            rt.credit_inflight())
            live_s.add(("sub_backlog", rt.sub_id))
            live_s.add(("credit_inflight", rt.sub_id))
        except Exception:  # noqa: BLE001
            continue
    _drop_stale(stats, ("sub_backlog", "credit_inflight"), live_s)
    # flow ladder state
    flow = getattr(ctx, "flow", None)
    if flow is not None:
        stats.gauge_set("overload_level", "",
                        flow.overload.effective_level())
    # replica ack lag (leader only)
    follower_status = getattr(ctx.store, "follower_status", None)
    live_f: set[tuple[str, str]] = set()
    if follower_status is not None:
        try:
            for f in follower_status():
                stats.gauge_set("replica_ack_lag", f["addr"],
                                f["behind"])
                live_f.add(("replica_ack_lag", f["addr"]))
        except Exception:  # noqa: BLE001
            pass
    _drop_stale(stats, ("replica_ack_lag",), live_f)
    # leadership epoch + producer-dedup footprint (ISSUE 9): sampled
    # from the leader store's status so a scrape answers "what epoch
    # does this node serve at" without an admin round trip
    leader_status = getattr(ctx.store, "leader_status", None)
    if leader_status is not None:
        try:
            ls = leader_status()
            stats.gauge_set("replica_epoch", "", ls["epoch"])
            stats.gauge_set("dedup_window_size", "", ls["dedup_window"])
        except Exception:  # noqa: BLE001 — a closing store must not
            pass           # fail the scrape
    # event-time freshness + health verdicts (ISSUE 13): per-query
    # watermark/lag gauges and the OK/DEGRADED/STALLED rollup — all
    # host-mirror values, zero device work (server/health.py owns the
    # thresholds and the query_stalled transition journal)
    try:
        from hstream_tpu.server.health import sample_health

        sample_health(ctx)
    except Exception:  # noqa: BLE001 — a half-built context (tests
        pass           # construct bare ones) must not fail the scrape
    # retire rate ladders whose entity is gone (ISSUE 15, the
    # _drop_stale discipline for family series): a deleted stream /
    # subscription / query must stop rendering AND free its
    # TS_MAX_LABELS cap slot, or entity churn folds every new entity
    # into the overflow series. Each scope fails open independently
    # (a half-built test context must not fail the scrape); "live"
    # is defined ONCE (cluster.live_entity_keys) for the sweep, the
    # admin stats verb, and the render filters alike.
    from hstream_tpu.stats.cluster import live_entity_keys

    for scope in ("stream", "subscription", "query"):
        try:
            stats.stat_drop_stale(scope, live_entity_keys(ctx, scope))
        except Exception:  # noqa: BLE001
            pass
    # device cost plane (ISSUE 18): exact per-query/per-plane arena
    # bytes folded from each executor's live device arrays — nbytes
    # metadata reads only, zero dispatches — plus the process total
    # and the backend allocator cross-check where one exists
    try:
        from hstream_tpu.stats.devicecost import sample_device_gauges

        sample_device_gauges(ctx)
    except Exception:  # noqa: BLE001 — a half-built context must not
        pass           # fail the scrape
    # node load axes for the federation fold (ISSUE 15): process rss +
    # append-front queue depth — the same numbers NodeStatsReport and
    # the periodic node_load_report event carry
    from hstream_tpu.stats.cluster import rss_bytes

    stats.gauge_set("node_rss_bytes", "", rss_bytes())
    # placer node scores (ISSUE 17): one gauge series per cluster node
    # with a fresh published record — the load fold the placement
    # decisions actually rank on, so an operator can see WHY a node
    # won. Stale nodes drop off the exposition with their records.
    placer = getattr(ctx, "placer", None)
    live_n: set[tuple[str, str]] = set()
    if placer is not None:
        try:
            for node, score in placer.scores().items():
                stats.gauge_set("placer_node_score", node, score)
                live_n.add(("placer_node_score", node))
        except Exception:  # noqa: BLE001 — a closing placer must not
            pass           # fail the scrape
    _drop_stale(stats, ("placer_node_score",), live_n)
    front = getattr(ctx, "append_front", None)
    if front is not None:
        try:
            stats.gauge_set("append_inflight", "",
                            front.stats().get("in_flight", 0))
        except Exception:  # noqa: BLE001 — a closing front must not
            pass           # fail the scrape
    # durable store footprint (native store roots at a directory)
    root = getattr(ctx.store, "root", None) \
        or getattr(getattr(ctx.store, "local", None), "root", None)
    if root:
        seg, wal = _store_dir_bytes(str(root))
        stats.gauge_set("store_segment_bytes", "", seg)
        stats.gauge_set("store_wal_bytes", "", wal)
    # event_journal_size is a gauge_fn sampler registered by the
    # ServerContext — gauges_snapshot() calls it at render time


def _drop_stale(stats, metrics: tuple[str, ...],
                live: set[tuple[str, str]]) -> None:
    """Drop gauge series whose subsystem (query, subscription,
    follower) went away, so /metrics reflects the live topology."""
    for metric in metrics:
        for label in stats.gauge_labels(metric):
            if (metric, label) not in live:
                stats.gauge_drop(metric, label)


def render_metrics(ctx) -> str:
    """One scrape: sample live subsystems, render the full exposition.
    Whole-scrape serialization (holder.scrape_lock): concurrent
    scrapers otherwise race sample_gauges' stale-series sweep against
    each other and intermittently drop live gauges."""
    from hstream_tpu.stats.cluster import live_entity_keys

    with ctx.stats.scrape_lock:
        sample_gauges(ctx)
        try:
            live = live_entity_keys(ctx, "stream")
        except Exception:  # noqa: BLE001
            live = None
        try:
            queries = live_entity_keys(ctx, "query")
        except Exception:  # noqa: BLE001 — fail open, like streams
            queries = None
        return render_holder(ctx.stats, live_streams=live,
                             live_queries=queries)


CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def serve_exporter(ctx, host: str = "0.0.0.0", port: int = 9464):
    """Standalone scrape endpoint on the SERVER process (the
    `--metrics-port` flag): /metrics (Prometheus text) + /events
    (journal JSON) straight off the live context — no gRPC hop, so it
    keeps answering even when the RPC workers are saturated. Returns
    the httpd; caller owns shutdown. Port 0 picks a free port."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
            from urllib.parse import parse_qs, urlsplit

            parts = urlsplit(self.path)
            if parts.path.rstrip("/") == "/metrics":
                try:
                    body = render_metrics(ctx).encode()
                except Exception as e:  # noqa: BLE001 — scrape boundary
                    self._send(500, f"# scrape failed: {e}\n".encode())
                    return
                self._send(200, body, CONTENT_TYPE)
            elif parts.path.rstrip("/") == "/events":
                q = parse_qs(parts.query)
                try:
                    events = ctx.events.query(
                        kind=(q.get("kind") or [None])[0],
                        since=int((q.get("since") or [0])[0]),
                        limit=int((q.get("limit") or [100])[0]))
                except ValueError as e:
                    self._send(400, f"bad query param: {e}\n".encode())
                    return
                self._send(200, json.dumps(events).encode(),
                           "application/json")
            else:
                self._send(404, b"only /metrics and /events live here\n")

        def _send(self, code: int, body: bytes,
                  ctype: str = "text/plain") -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet
            pass

    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="metrics-exporter")
    t.start()
    return httpd
