"""Per-stream stats: counters + multi-level time-series rate ladders.

Reference: a C++ stats library with thread-local `PerStreamStats`
(sharded counters aggregated on demand) and folly MultiLevelTimeSeries
rates, where the metric registry is an X-macro `.inc` file so adding a
metric is one line (common/clib/stats.h:80-118,
common/include/per_stream_time_series.inc:24-40).

Here the counter registry is the list below and the rate-ladder
registry is the declarative family table (stats/families.py — the
`.inc` analogue, machine-checked by the analyzer's registry pass); the
holder keeps per-thread counter shards aggregated on read — the GIL
makes plain dict bumps atomic enough, but sharding keeps the write path
contention-free and mirrors the reference's aggregation shape. Rates
live in fixed-ring MultiLevelTimeSeries (stats/timeseries.py): 60x1s /
60x10s / 60x60s + all-time, O(1) add, exact windowed recounts.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict

from hstream_tpu.stats.families import (
    FAMILY_BY_NAME,
    STAT_FAMILIES,
    families_for_scope,
)
from hstream_tpu.stats.timeseries import (
    DEFAULT_LEVELS,
    INTERVAL_NAMES,
    MultiLevelTimeSeries,
    level_for_window,
)

# ---- metric registry (the .inc analogue: one line per metric) --------------

PER_STREAM_COUNTERS = [
    "append_payload_bytes",    # bytes appended (payload only)
    "append_total",            # append batches
    "append_failed",
    "append_throttled",        # appends refused by quota (flow control)
    "shed_total",              # requests refused by overload shedding
    "delivery_credit_waits",   # push deliveries paused at zero credit
    "record_payload_bytes",    # bytes read out by consumers/queries
    "record_total",            # records read
    "json_decode_native",      # JSON records through libjsondec batch dec
    "json_decode_fallback",    # JSON records through the Python per-record
                               # decode (no toolchain, or CLS_PY rows)
    "join_probe_dispatches",   # device interval-join probe kernel launches
                               # (contract: one per join micro-batch)
    "change_rows_columnar",    # emitted aggregate rows that reached the
                               # sink as a ColumnarEmit batch (no dicts)
    "kernel_recompiles",       # XLA executable builds observed by the
                               # process-wide RetraceGuard listener
                               # (contract: zero in steady state)
    "query_restarts",          # supervisor-initiated query restarts
                               # (label: query id)
    "snapshot_fallbacks",      # restores that fell back past a corrupt
                               # snapshot slot (label: query id)
    "device_path_fallbacks",   # device-join / fused-close activations
                               # that degraded to the host reference
                               # path (label: source stream)
    "promotions",              # replica promotions driven through this
                               # server (label: "_store")
    "fenced_appends",          # mutations refused NOT_LEADER after the
                               # store was fenced (label: "_store")
    "append_deduped",          # producer-stamped appends answered from
                               # the dedup window (retry landed exactly
                               # once; label: stream)
    "append_columnar_rows",    # rows ingested through the framed
                               # columnar append path (bounds-check +
                               # handoff, no per-record protobuf)
    "late_drops",              # records dropped as late (past
                               # end/gap + grace at the pre-batch
                               # watermark), host-mirror count
                               # (label: query id)
    "device_h2d_bytes",        # host->device bytes on the staging
                               # path (label: source stream)
    "device_d2h_bytes",        # device->host bytes on the close/
                               # changelog drain paths (label: source
                               # stream)
    "factory_recompiles",      # XLA executable builds attributed to
                               # the kernel family whose dispatch
                               # triggered them (label: step/close/
                               # probe/session)
    "lock_contention",         # traced-lock acquires that found the
                               # lock taken (locktrace witness armed;
                               # label: lock role name)
    "placement_decisions",     # placer decisions written onto
                               # scheduler/query/* — place, adopt, or
                               # rebalance offer (label: query id)
    "queries_adopted",         # queries this server claimed live via
                               # the heartbeat-lease CAS (try_adopt_
                               # live), boot adoption NOT included
                               # (label: query id)
    "read_extracts",           # pull-query serves that actually ran an
                               # executor peek (read-plane contract:
                               # ~one per view per close cycle, not one
                               # per reader; label: view name)
]

# stream-scoped rate families, in the (name, bucket-widths) tuple
# shape older consumers (GetStats, the __stats__ virtual table) walk;
# the declaration itself lives in stats/families.py — subscription- and
# query-scoped families are reached through the stat_* API only
PER_STREAM_TIME_SERIES = [
    (f.name, tuple(w for w, _n in DEFAULT_LEVELS))
    for f in families_for_scope("stream")
]

# Gauges: point-in-time values sampled from live subsystems. Direct
# sets (gauge_set) and scrape-time sampling callbacks (gauge_fn) share
# one registry; the label dimension is the subsystem's natural key
# (query id, subscription id, follower address, or "" for singletons).
GAUGES = [
    "pipeline_occupancy",     # per running query: encode/step busy frac
    "pipeline_reorder_depth", # per running query: staged-but-unstepped
    "sub_backlog",            # per subscription: tail - committed LSNs
    "credit_inflight",        # per subscription: delivery credits out
    "overload_level",         # shed ladder: 0 admit / 1 defer / 2 reject
    "replica_ack_lag",        # per follower: oplog entries behind
    "store_segment_bytes",    # durable store data footprint on disk
    "store_wal_bytes",        # durable store write-ahead-log footprint
    "running_queries",        # live query tasks on this server
    "event_journal_size",     # entries currently held by the journal
    "crash_loop_open",        # per query: 1 while the supervisor's
                              # crash-loop breaker holds it FAILED
    "replica_epoch",          # leadership epoch of the replicated
                              # store this server fronts
    "dedup_window_size",      # producer-dedup seqs remembered across
                              # all producers (bounded per producer)
    "query_watermark_ms",     # per query: event-time watermark
                              # (absolute ms) of the query's executor
    "query_watermark_lag_ms", # per query: wall clock - watermark (the
                              # Dataflow watermark-lag discipline: how
                              # stale is the answer a reader sees)
    "query_health_level",     # per query: 0 OK / 1 DEGRADED /
                              # 2 STALLED (the health-plane verdict)
    "node_rss_bytes",         # resident set size of this server
                              # process (the federation load signal's
                              # memory axis), sampled at scrape
    "append_inflight",        # framed appends submitted to the append
                              # front but not yet completed (queue
                              # depth across the lanes / completion
                              # queue), sampled at scrape
    "mesh_shards",            # per query: key-axis shard count of the
                              # mesh the executor runs on (absent for
                              # single-chip queries), sampled at scrape
    "placer_node_score",      # per cluster node: the placer's load
                              # score folded from the node's published
                              # record (lower = preferred), sampled at
                              # scrape while node records are fresh
    "device_hbm_bytes",       # per query: device bytes held by the
                              # query's live arenas/stores (exact
                              # nbytes fold), sampled at scrape with
                              # zero added dispatches (ISSUE 18)
    "device_arena_bytes",     # per query+plane ("qid/plane" label,
                              # split at render): device bytes of one
                              # named arena/store plane
    "device_hbm_total_bytes", # process total of device_hbm_bytes
                              # across all live queries
    "device_hbm_backend_bytes",  # bytes-in-use per the backend's own
                              # memory_stats() where the platform
                              # provides it (absent on CPU) — the
                              # allocator-side cross-check of the fold
    "read_cache_hit_ratio",   # read plane: (hits+shared)/(all versioned
                              # serves) of the snapshot cache, sampled
                              # at scrape
    "read_cache_bytes",       # read plane: bytes held by the snapshot +
                              # shared-encode LRU (budget via
                              # --read-cache-bytes), sampled at scrape
]

# Fixed-bucket latency histograms (Prometheus-style cumulative buckets);
# upper bounds in milliseconds, +Inf implied. One label per family:
# `stream` for the RPC families, `stage` for pipeline stage timings.
LATENCY_BUCKETS_MS = (
    0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
    1000.0, 2500.0, 5000.0, 10000.0)

# freshness latencies span a wider range than RPCs (a healthy pipeline
# sits in the tens of ms; a stalled one drifts toward minutes), so the
# freshness families get their own bucket ladder topping out at 60s
FRESHNESS_BUCKETS_MS = (
    1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
    5000.0, 10000.0, 30000.0, 60000.0)

HISTOGRAMS = [
    # name, bucket upper bounds (ms), label key
    ("append_latency_ms", LATENCY_BUCKETS_MS, "stream"),
    ("fetch_latency_ms", LATENCY_BUCKETS_MS, "subscription"),
    ("sql_execute_latency_ms", LATENCY_BUCKETS_MS, "stmt"),
    ("stage_latency_ms", LATENCY_BUCKETS_MS, "stage"),
    # event-time freshness plane (ISSUE 13): how stale is the answer a
    # reader sees, and where the milliseconds live
    ("emit_latency_ms", FRESHNESS_BUCKETS_MS, "query"),
    ("append_visible_latency_ms", FRESHNESS_BUCKETS_MS, "consumer"),
    ("freshness_lag_ms", FRESHNESS_BUCKETS_MS, "stage"),
    # per-kernel-family host dispatch time (step/close/probe/session)
    ("kernel_dispatch_ms", LATENCY_BUCKETS_MS, "family"),
    # per-kernel-family DEVICE execution time (ISSUE 18): fenced
    # block-until-ready pairs on a deterministic 1/N dispatch sample
    # (--device-time-sample), next to the host-wall series above
    ("kernel_device_ms", LATENCY_BUCKETS_MS, "family"),
    # lock-order witness ledger (ISSUE 14): time spent waiting for /
    # holding each named traced lock, armed runs only
    ("lock_wait_ms", LATENCY_BUCKETS_MS, "lock"),
    ("lock_hold_ms", LATENCY_BUCKETS_MS, "lock"),
]

_HIST_BUCKETS = {name: buckets for name, buckets, _label in HISTOGRAMS}
HIST_LABEL_KEYS = {name: label for name, _b, label in HISTOGRAMS}

# per-metric label-series ceiling: RPC labels come from request fields
# (a failed Append still observes its latency), so a client looping
# over random stream names must not grow /metrics without bound —
# past the cap new labels fold into one overflow series
HIST_MAX_LABELS = 512
HIST_OVERFLOW_LABEL = "_overflow"

# the rate-ladder series maps get the same ceiling: a client looping
# over random stream names (a failed Append still notes its bytes)
# must not grow the series map — or /metrics — without bound; past the
# cap new keys fold into one overflow series per family
TS_MAX_LABELS = HIST_MAX_LABELS
TS_OVERFLOW_LABEL = HIST_OVERFLOW_LABEL


class Histogram:
    """Fixed-bucket latency histogram (Prometheus shape): cumulative
    bucket counts rendered at exposition time, plus sum and count for
    the `_sum`/`_count` series. Observe takes the lock — histograms sit
    on RPC boundaries, not per-record hot loops."""

    __slots__ = ("bounds", "counts", "sum", "count", "_lock")

    def __init__(self, bounds: tuple[float, ...]):
        self.bounds = tuple(float(b) for b in bounds)
        self.counts = [0] * (len(self.bounds) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """(cumulative bucket counts incl. +Inf, sum, count)."""
        with self._lock:
            counts = list(self.counts)
            total_sum, total = self.sum, self.count
        cum = []
        running = 0
        for c in counts:
            running += c
            cum.append(running)
        return cum, total_sum, total

    def percentile(self, q: float) -> float | None:
        """Bucket-interpolated percentile estimate (None while empty).
        Within a bucket the value is linearly interpolated; the +Inf
        bucket reports its lower bound (the largest finite edge)."""
        cum, _s, total = self.snapshot()
        if total == 0:
            return None
        rank = q / 100.0 * total
        prev_cum = 0
        for i, c in enumerate(cum):
            if c >= rank:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                if i >= len(self.bounds):
                    return self.bounds[-1]
                hi = self.bounds[i]
                in_bucket = c - prev_cum
                frac = ((rank - prev_cum) / in_bucket) if in_bucket else 1.0
                return lo + (hi - lo) * frac
            prev_cum = c
        return self.bounds[-1]


class _Shard:
    __slots__ = ("counters", "owner")

    def __init__(self, owner: threading.Thread | None = None) -> None:
        self.counters: dict[tuple[str, str], int] = defaultdict(int)
        self.owner = owner


class StatsHolder:
    """newStatsHolder analogue: per-thread counter shards + shared
    time-series, aggregated on read (stats.h:80-118). Shards whose
    owning thread has exited are folded into a retired aggregate on
    read, so short-lived threads (per-query tasks, gRPC workers being
    recycled) cannot grow the shard list forever."""

    def __init__(self) -> None:
        self._local = threading.local()
        self._shards: list[_Shard] = []
        self._shards_lock = threading.Lock()
        self._retired: dict[tuple[str, str], int] = defaultdict(int)
        self._series: dict[tuple[str, str], MultiLevelTimeSeries] = {}
        self._series_lock = threading.Lock()
        # gauges: direct values + scrape-time sampling callbacks; both
        # keyed (metric, label). A dead callback (its subsystem went
        # away) is dropped at the next snapshot instead of erroring.
        self._gauges: dict[tuple[str, str], float] = {}
        self._gauge_fns: dict[tuple[str, str], object] = {}
        self._gauge_lock = threading.Lock()
        # serializes whole scrapes (sample + render): concurrent
        # scrapers (gateway /metrics, --metrics-port exporter, admin
        # verb) share the gauge registry, and an unserialized stale-
        # series sweep could drop a live series a sibling just sampled
        self.scrape_lock = threading.Lock()
        self._hists: dict[tuple[str, str], Histogram] = {}
        self._hist_lock = threading.Lock()

    def _shard(self) -> _Shard:
        sh = getattr(self._local, "shard", None)
        if sh is None:
            sh = _Shard(threading.current_thread())
            self._local.shard = sh
            with self._shards_lock:
                self._shards.append(sh)
        return sh

    def _fold_dead(self) -> tuple[list[_Shard], dict[tuple[str, str], int]]:
        """Fold dead threads' shards into the retired aggregate; return
        (live shards, retired snapshot) captured under one lock so a
        shard can never be counted both live and retired. A dead thread
        can no longer write its shard, so the fold loses no increments."""
        with self._shards_lock:
            live = []
            for sh in self._shards:
                if sh.owner is not None and not sh.owner.is_alive():
                    for key, v in sh.counters.items():
                        self._retired[key] += v
                else:
                    live.append(sh)
            self._shards = live
            return list(live), dict(self._retired)

    # ---- counters ----
    def stream_stat_add(self, metric: str, stream: str, value: int = 1
                        ) -> None:
        if metric not in PER_STREAM_COUNTERS:
            raise KeyError(f"unregistered counter {metric!r}")
        self._shard().counters[(metric, stream)] += value

    def stream_stat_get(self, metric: str, stream: str) -> int:
        shards, retired = self._fold_dead()
        total = retired.get((metric, stream), 0)
        return total + sum(sh.counters.get((metric, stream), 0)
                           for sh in shards)

    def stream_stat_getall(self, metric: str) -> dict[str, int]:
        shards, retired = self._fold_dead()
        out: dict[str, int] = defaultdict(int)
        for (m, stream), v in retired.items():
            if m == metric:
                out[stream] += v
        for sh in shards:
            for (m, stream), v in list(sh.counters.items()):
                if m == metric:
                    out[stream] += v
        return dict(out)

    # ---- rate ladders (declarative stat families) ----
    def _family_series(self, family: str, key: str
                       ) -> MultiLevelTimeSeries:
        """The (family, key) ladder, created from the family table on
        first write. Past TS_MAX_LABELS keys per family, new keys fold
        into the one overflow series — the series map (and /metrics)
        stays bounded no matter what key junk a client sends."""
        if family not in FAMILY_BY_NAME:
            raise KeyError(f"unregistered stat family {family!r}")
        k = (family, key)
        with self._series_lock:
            ts = self._series.get(k)
            if ts is None:
                n = sum(1 for (f, _key) in self._series if f == family)
                if n >= TS_MAX_LABELS:
                    k = (family, TS_OVERFLOW_LABEL)
                    ts = self._series.get(k)
                    if ts is not None:
                        return ts
                ts = MultiLevelTimeSeries()
                self._series[k] = ts
            return ts

    def stat_add(self, family: str, key: str, value: float = 1.0,
                 now: float | None = None) -> None:
        """THE family write path (the reference's `.inc` bump): one
        O(1) ladder add. Call sites are machine-checked against the
        family table by the analyzer's `registry-family` rule."""
        self._family_series(family, key).add(value, now)

    def _peek_series(self, family: str, key: str
                     ) -> MultiLevelTimeSeries | None:
        """Read-only lookup: monitoring reads must not allocate/retain
        state on the holder. An UNREGISTERED family raises the same
        KeyError `_family_series` does: a typo'd dashboard query must
        not read as a silent zero."""
        if family not in FAMILY_BY_NAME:
            raise KeyError(f"unregistered stat family {family!r}")
        with self._series_lock:
            return self._series.get((family, key))

    def stat_rate(self, family: str, key: str, interval="1min",
                  now: float | None = None) -> float:
        ts = self._peek_series(family, key)
        return 0.0 if ts is None else ts.rate(interval, now)

    def stat_sum(self, family: str, key: str, interval="1min",
                 now: float | None = None) -> float:
        ts = self._peek_series(family, key)
        return 0.0 if ts is None else ts.sum(interval, now)

    def stat_avg(self, family: str, key: str, interval="1min",
                 now: float | None = None) -> float:
        ts = self._peek_series(family, key)
        return 0.0 if ts is None else ts.avg(interval, now)

    def stat_count(self, family: str, key: str, interval="1min",
                   now: float | None = None) -> int:
        ts = self._peek_series(family, key)
        return 0 if ts is None else ts.count(interval, now)

    def stat_ladder(self, family: str, key: str,
                    now: float | None = None) -> dict[str, float]:
        """Every interval's rate + all-time sum/count for one series
        (zeros when the key has never been written)."""
        ts = self._peek_series(family, key)
        if ts is None:
            # same shape ladder() returns, derived from the declared
            # interval set so a level rename cannot fork cold keys
            return {**dict.fromkeys(INTERVAL_NAMES, 0.0),
                    "total": 0.0, "total_count": 0.0}
        return ts.ladder(now)

    def stat_keys(self, family: str) -> list[str]:
        """Keys with a live ladder for `family` (exposition and the
        federation fold walk this instead of the series map)."""
        if family not in FAMILY_BY_NAME:
            raise KeyError(f"unregistered stat family {family!r}")
        with self._series_lock:
            return sorted({k for (f, k) in self._series if f == family})

    def stat_drop_stale(self, scope: str, live: set[str]) -> None:
        """Drop every ladder of `scope`-scoped families whose entity
        no longer exists — the gauge `_drop_stale` discipline for the
        family series, run at scrape time. This is also what frees
        TS_MAX_LABELS cap slots: without it, entity churn would
        permanently fill a family's cap with retired keys and fold
        every NEW entity into the overflow series. ONLY the reserved
        overflow fold is exempt — a broader "_" exemption would let a
        client churning "_"-named entities exhaust the cap forever."""
        fams = {f.name for f in families_for_scope(scope)}
        with self._series_lock:
            stale = [k for k in self._series
                     if k[0] in fams and k[1] != TS_OVERFLOW_LABEL
                     and k[1] not in live]
            for k in stale:
                del self._series[k]

    # back-compat shims over the family API (older call sites/tests;
    # `window_s` picks the narrowest level ladder covering it)
    def _ts(self, metric: str, stream: str) -> MultiLevelTimeSeries:
        return self._family_series(metric, stream)

    def time_series_add(self, metric: str, stream: str, value: float
                        ) -> None:
        self.stat_add(metric, stream, value)

    def time_series_get_rate(self, metric: str, stream: str,
                             window_s: int | None = None) -> float:
        return self._family_series(metric, stream).rate(
            level_for_window(window_s or 60))

    def time_series_streams(self, metric: str) -> list[str]:
        return self.stat_keys(metric)

    def time_series_peek_rate(self, metric: str, stream: str,
                              window_s: int | None = None) -> float:
        ts = self._peek_series(metric, stream)
        if ts is None:
            return 0.0
        return ts.rate(level_for_window(window_s or 60))

    # ---- gauges ----
    def gauge_set(self, metric: str, label: str, value: float) -> None:
        if metric not in GAUGES:
            raise KeyError(f"unregistered gauge {metric!r}")
        with self._gauge_lock:
            self._gauges[(metric, label)] = float(value)

    def gauge_fn(self, metric: str, label: str, fn) -> None:
        """Register a scrape-time sampler: fn() -> float. Re-registering
        the same (metric, label) replaces the previous sampler."""
        if metric not in GAUGES:
            raise KeyError(f"unregistered gauge {metric!r}")
        with self._gauge_lock:
            self._gauge_fns[(metric, label)] = fn

    def gauge_drop(self, metric: str, label: str) -> None:
        """Remove a gauge value/sampler (its subsystem went away)."""
        with self._gauge_lock:
            self._gauges.pop((metric, label), None)
            self._gauge_fns.pop((metric, label), None)

    def gauge_labels(self, metric: str) -> list[str]:
        """Labels currently held for one gauge metric (values + fns)."""
        with self._gauge_lock:
            return sorted({label for (m, label) in
                           list(self._gauges) + list(self._gauge_fns)
                           if m == metric})

    def gauges_snapshot(self) -> dict[tuple[str, str], float]:
        """All gauges: direct values plus sampled callbacks. A sampler
        that raises is dropped (its subsystem died between scrapes) —
        monitoring never propagates subsystem errors."""
        with self._gauge_lock:
            out = dict(self._gauges)
            fns = list(self._gauge_fns.items())
        dead = []
        for key, fn in fns:
            try:
                out[key] = float(fn())
            except Exception:  # noqa: BLE001 — scrape must survive
                dead.append(key)
        if dead:
            with self._gauge_lock:
                for key in dead:
                    self._gauge_fns.pop(key, None)
        return out

    # ---- histograms ----
    def _hist(self, metric: str, label: str) -> Histogram:
        if metric not in _HIST_BUCKETS:
            raise KeyError(f"unregistered histogram {metric!r}")
        key = (metric, label)
        with self._hist_lock:
            h = self._hists.get(key)
            if h is None:
                n = sum(1 for (m, _l) in self._hists if m == metric)
                if n >= HIST_MAX_LABELS:
                    key = (metric, HIST_OVERFLOW_LABEL)
                    h = self._hists.get(key)
                    if h is not None:
                        return h
                h = Histogram(_HIST_BUCKETS[metric])
                self._hists[key] = h
            return h

    def observe(self, metric: str, label: str, value_ms: float) -> None:
        self._hist(metric, label).observe(value_ms)

    def histograms_snapshot(self) -> dict[tuple[str, str], Histogram]:
        with self._hist_lock:
            return dict(self._hists)

    def histogram_percentile(self, metric: str, label: str,
                             q: float) -> float | None:
        """Percentile estimate over every series of `metric` when label
        is ""; otherwise the one labeled series. None while empty."""
        if metric not in _HIST_BUCKETS:
            raise KeyError(f"unregistered histogram {metric!r}")
        with self._hist_lock:
            if label:
                hists = [h for k, h in self._hists.items()
                         if k == (metric, label)]
            else:
                hists = [h for (m, _l), h in self._hists.items()
                         if m == metric]
        if not hists:
            return None
        if len(hists) == 1:
            return hists[0].percentile(q)
        merged = Histogram(_HIST_BUCKETS[metric])
        for h in hists:
            with h._lock:
                for i, c in enumerate(h.counts):
                    merged.counts[i] += c
                merged.sum += h.sum
                merged.count += h.count
        return merged.percentile(q)

    # ---- convenience for the append/read hot paths ----
    def note_append(self, stream: str, n_records: int, n_bytes: int) -> None:
        self.stream_stat_add("append_total", stream)
        self.stream_stat_add("append_payload_bytes", stream, n_bytes)
        self.stat_add("append_in_bytes", stream, float(n_bytes))
        self.stat_add("append_in_records", stream, float(n_records))

    def note_read(self, stream: str, n_records: int, n_bytes: int) -> None:
        self.stream_stat_add("record_total", stream, n_records)
        self.stream_stat_add("record_payload_bytes", stream, n_bytes)
        self.stat_add("record_bytes", stream, float(n_bytes))
        self.stat_add("read_out_records", stream, float(n_records))
