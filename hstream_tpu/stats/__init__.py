"""Per-stream stats: counters + multi-level time-series rates.

Reference: a C++ stats library with thread-local `PerStreamStats`
(sharded counters aggregated on demand) and folly MultiLevelTimeSeries
rates, where the metric registry is an X-macro `.inc` file so adding a
metric is one line (common/clib/stats.h:80-118,
common/include/per_stream_time_series.inc:24-40).

Here the registry is the two lists below (same one-line property); the
holder keeps per-thread counter shards aggregated on read — the GIL
makes plain dict bumps atomic enough, but sharding keeps the write path
contention-free and mirrors the reference's aggregation shape.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict

# ---- metric registry (the .inc analogue: one line per metric) --------------

PER_STREAM_COUNTERS = [
    "append_payload_bytes",    # bytes appended (payload only)
    "append_total",            # append batches
    "append_failed",
    "append_throttled",        # appends refused by quota (flow control)
    "shed_total",              # requests refused by overload shedding
    "delivery_credit_waits",   # push deliveries paused at zero credit
    "record_payload_bytes",    # bytes read out by consumers/queries
    "record_total",            # records read
]

PER_STREAM_TIME_SERIES = [
    # name, bucket seconds per level (reference: 1s/10s/60s multi-level)
    ("append_in_bytes", (1, 10, 60)),
    ("append_in_records", (1, 10, 60)),
    ("record_bytes", (1, 10, 60)),
]

_TS_LEVELS = {name: levels for name, levels in PER_STREAM_TIME_SERIES}


class TimeSeries:
    """Sliding-window rate estimator: ring of 1s buckets, queried over
    any of the registered level windows (MultiLevelTimeSeries shape)."""

    def __init__(self, max_window_s: int = 60):
        self._max = max_window_s
        self._buckets: dict[int, float] = {}
        self._lock = threading.Lock()

    def add(self, value: float, now: float | None = None) -> None:
        sec = int(now if now is not None else time.time())
        with self._lock:
            self._buckets[sec] = self._buckets.get(sec, 0.0) + value
            if len(self._buckets) > self._max * 2:
                cutoff = sec - self._max
                for k in [k for k in self._buckets if k < cutoff]:
                    del self._buckets[k]

    def rate(self, window_s: int, now: float | None = None) -> float:
        """Per-second rate over the trailing window."""
        nowi = int(now if now is not None else time.time())
        lo = nowi - window_s
        with self._lock:
            total = sum(v for s, v in self._buckets.items()
                        if lo < s <= nowi)
        return total / max(window_s, 1)


class _Shard:
    __slots__ = ("counters", "owner")

    def __init__(self, owner: threading.Thread | None = None) -> None:
        self.counters: dict[tuple[str, str], int] = defaultdict(int)
        self.owner = owner


class StatsHolder:
    """newStatsHolder analogue: per-thread counter shards + shared
    time-series, aggregated on read (stats.h:80-118). Shards whose
    owning thread has exited are folded into a retired aggregate on
    read, so short-lived threads (per-query tasks, gRPC workers being
    recycled) cannot grow the shard list forever."""

    def __init__(self) -> None:
        self._local = threading.local()
        self._shards: list[_Shard] = []
        self._shards_lock = threading.Lock()
        self._retired: dict[tuple[str, str], int] = defaultdict(int)
        self._series: dict[tuple[str, str], TimeSeries] = {}
        self._series_lock = threading.Lock()

    def _shard(self) -> _Shard:
        sh = getattr(self._local, "shard", None)
        if sh is None:
            sh = _Shard(threading.current_thread())
            self._local.shard = sh
            with self._shards_lock:
                self._shards.append(sh)
        return sh

    def _fold_dead(self) -> tuple[list[_Shard], dict[tuple[str, str], int]]:
        """Fold dead threads' shards into the retired aggregate; return
        (live shards, retired snapshot) captured under one lock so a
        shard can never be counted both live and retired. A dead thread
        can no longer write its shard, so the fold loses no increments."""
        with self._shards_lock:
            live = []
            for sh in self._shards:
                if sh.owner is not None and not sh.owner.is_alive():
                    for key, v in sh.counters.items():
                        self._retired[key] += v
                else:
                    live.append(sh)
            self._shards = live
            return list(live), dict(self._retired)

    # ---- counters ----
    def stream_stat_add(self, metric: str, stream: str, value: int = 1
                        ) -> None:
        if metric not in PER_STREAM_COUNTERS:
            raise KeyError(f"unregistered counter {metric!r}")
        self._shard().counters[(metric, stream)] += value

    def stream_stat_get(self, metric: str, stream: str) -> int:
        shards, retired = self._fold_dead()
        total = retired.get((metric, stream), 0)
        return total + sum(sh.counters.get((metric, stream), 0)
                           for sh in shards)

    def stream_stat_getall(self, metric: str) -> dict[str, int]:
        shards, retired = self._fold_dead()
        out: dict[str, int] = defaultdict(int)
        for (m, stream), v in retired.items():
            if m == metric:
                out[stream] += v
        for sh in shards:
            for (m, stream), v in list(sh.counters.items()):
                if m == metric:
                    out[stream] += v
        return dict(out)

    # ---- time series ----
    def _ts(self, metric: str, stream: str) -> TimeSeries:
        if metric not in _TS_LEVELS:
            raise KeyError(f"unregistered time series {metric!r}")
        key = (metric, stream)
        with self._series_lock:
            ts = self._series.get(key)
            if ts is None:
                ts = TimeSeries(max(_TS_LEVELS[metric]))
                self._series[key] = ts
            return ts

    def time_series_add(self, metric: str, stream: str, value: float
                        ) -> None:
        self._ts(metric, stream).add(value)

    def time_series_get_rate(self, metric: str, stream: str,
                             window_s: int | None = None) -> float:
        levels = _TS_LEVELS[metric]
        return self._ts(metric, stream).rate(window_s or levels[-1])

    def time_series_peek_rate(self, metric: str, stream: str,
                              window_s: int | None = None) -> float:
        """Read-only rate: 0.0 when no series exists — monitoring reads
        must not allocate/retain state on the holder."""
        with self._series_lock:
            ts = self._series.get((metric, stream))
        if ts is None:
            return 0.0
        return ts.rate(window_s or _TS_LEVELS[metric][-1])

    # ---- convenience for the append/read hot paths ----
    def note_append(self, stream: str, n_records: int, n_bytes: int) -> None:
        self.stream_stat_add("append_total", stream)
        self.stream_stat_add("append_payload_bytes", stream, n_bytes)
        ts = self._ts("append_in_bytes", stream)
        ts.add(float(n_bytes))
        self._ts("append_in_records", stream).add(float(n_records))

    def note_read(self, stream: str, n_records: int, n_bytes: int) -> None:
        self.stream_stat_add("record_total", stream, n_records)
        self.stream_stat_add("record_payload_bytes", stream, n_bytes)
        self._ts("record_bytes", stream).add(float(n_bytes))
