"""Bounded structured event journal: operator-significant transitions.

Counters and gauges answer "how much"; the journal answers "what
happened and when" — the discrete transitions an operator greps for
during an incident: shed-ladder changes, degraded appends, query
adoption/restart/death, snapshot persist failures. The reference keeps
these in unstructured logDebug lines; here they are structured entries
in a fixed-capacity ring, queryable via admin `events` and the
gateway's ``GET /events``.

Entries are dicts: {seq, ts_ms, kind, message, **fields}. `seq` is a
process-monotone cursor so a poller can resume with ``since`` instead
of re-reading the window. The ring drops the oldest entry on overflow —
appending is O(1) and never blocks the subsystem reporting the event.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any

# The kind vocabulary (the journal's .inc analogue): append() rejects
# unregistered kinds so the queryable surface stays enumerable.
EVENT_KINDS = [
    "shed_level",        # overload ladder transition (admit/defer/reject)
    "degraded_append",   # replicated ack fell short of the quorum
    "follower_down",     # a store follower stopped acking
    "leader_change",     # a follower accepted a new leader id
    "query_adopted",     # boot-time takeover of a dead owner's query
    "query_restarted",   # operator RestartQuery
    "query_died",        # task hit CONNECTION_ABORT
    "snapshot_failed",   # background state persist failed
    "query_restart_scheduled",  # supervisor queued a restart (backoff)
    "crash_loop_open",   # K failures in W seconds -> breaker FAILED
    "snapshot_corrupt",  # restore skipped a corrupt snapshot slot
    "checkpoint_corrupt",  # checkpoint store recovered from bad bytes
    "fault_injected",    # a chaos fault site fired
    "adoption_lost",     # lost the CAS race adopting a query
    "replica_fenced",    # a stale leader was rejected by epoch (or
                         # THIS leader learned it was fenced)
    "replica_promoted",  # a replica was raised to leadership
    "replica_ack_timeout",  # a follower-ack deadline expired; the
                            # append degraded honestly
    "query_stalled",     # the health plane's verdict for a query
                         # crossed into STALLED (backlog with no
                         # watermark progress, crash loop, or a dead
                         # unowned task) — the machine-readable signal
                         # failover adoption and the placer gate on
    "lock_cycle",        # the runtime lock-order witness (locktrace)
                         # saw both directions of a lock pair — a
                         # potential deadlock reported WITHOUT needing
                         # the unlucky schedule (GoodLock)
    "node_load_report",  # periodic per-node load fold (stats/cluster):
                         # per-stream append rates, query health
                         # counts, append-front depth, rss — THE
                         # machine-readable load signal the thousand-
                         # query placer gates on (ROADMAP item 2)
    "placement_decision",  # the placer wrote a decision onto
                           # scheduler/query/*: placed a new query,
                           # live-adopted a lapsed owner's query, or
                           # offered one away in a rebalance — with
                           # the machine-readable reason + scores
    "flightrec_written",   # the flight recorder snapshotted a
                           # postmortem bundle for a query (first
                           # STALLED verdict of an episode, or the
                           # crash-loop breaker opening) — the pointer
                           # an operator follows to GET
                           # /queries/<id>/flightrec
]


class EventJournal:
    """Fixed-capacity ring of structured events; thread-safe."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(int(capacity), 1)
        self._ring: deque[dict[str, Any]] = deque(maxlen=self.capacity)
        self._seq = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def append(self, kind: str, message: str, **fields: Any) -> int:
        """Record one event; returns its seq. Fields must be
        JSON-serializable (they travel through admin/HTTP as JSON)."""
        if kind not in EVENT_KINDS:
            raise KeyError(f"unregistered event kind {kind!r}")
        entry = {"kind": kind, "message": message,
                 "ts_ms": int(time.time() * 1000), **fields}
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._ring.append(entry)
            return self._seq

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def query(self, *, kind: str | None = None, since: int = 0,
              limit: int = 100) -> list[dict[str, Any]]:
        """Newest-last slice of the window: entries with seq > since,
        optionally one kind, capped at the LAST `limit` matches."""
        with self._lock:
            entries = list(self._ring)
        out = [dict(e) for e in entries
               if e["seq"] > since and (kind is None or e["kind"] == kind)]
        return out[-max(int(limit), 1):]
