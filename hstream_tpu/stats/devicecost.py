"""The device cost plane (ISSUE 18).

Every earlier telemetry plane measures the HOST side of the pipeline;
this module makes the device a first-class subsystem of /metrics:

* **HBM arena accounting** — executors expose `device_plane_bytes()`
  (pure `nbytes` metadata reads over their live arena/store arrays, no
  dispatch, no fetch); `sample_device_gauges` folds them per query and
  per plane into the `device_hbm_bytes` / `device_arena_bytes` gauges
  at scrape time, plus a process total cross-checked against the
  backend's own `memory_stats()` where the platform provides one.

* **Compiled-program inventory** — `PROGRAMS` wraps the single funnel
  every jit/pjit/pmap build passes through
  (`jax._src.compiler.compile_or_get_cached`) and records one row per
  distinct lowered module: kernel family (the dispatching thread's
  `kernel_family` scope — jit compiles synchronously inside the
  triggering call), shape key (crc32 of the MLIR module text), compile
  milliseconds, and `cost_analysis()` flops / bytes-accessed when the
  backend reports them. The wrapper degrades to a no-op if the private
  seam moves; the recompile *counters* (PR 12) keep working either way.

* **Per-dispatch device time** — `DEVICE_TIME` is the deterministic
  1/N sampler `common.tracing.kernel_family` consults: on a sampled
  dispatch the inputs are fenced (block-until-ready before the body),
  then a second block-until-ready bounds the device execution time into
  the `kernel_device_ms{family}` histogram next to the host-wall
  `kernel_dispatch_ms`. Disarmed cost is ONE attribute read + one
  branch (the FAULTS / FlowGovernor / locktrace discipline), and the
  disarmed sampler records ZERO state — `bench.py --smoke` gates both.
"""

from __future__ import annotations

import threading
import time
import weakref
import zlib
from collections import OrderedDict, deque

# ---- HBM arena accounting ---------------------------------------------------


# contract: dispatches<=0 fetches<=0
def plane_bytes(planes) -> dict[str, int]:
    """Per-plane device bytes of a {name: array} mapping — `nbytes` is
    shape metadata, so the walk costs zero dispatches and zero
    transfers however large the arenas are."""
    out: dict[str, int] = {}
    for name, arr in dict(planes).items():
        nb = getattr(arr, "nbytes", None)
        if nb:
            out[str(name)] = int(nb)
    return out


def backend_hbm_bytes() -> int | None:
    """Bytes-in-use reported by the backend's own allocator
    (`memory_stats()`), or None where the platform gives none (CPU).
    The cross-check axis for the per-plane fold: the two agree up to
    allocator slack and non-arena residents (compiled programs,
    staging buffers)."""
    try:
        import jax

        devs = jax.local_devices()
        if not devs:
            return None
        stats = devs[0].memory_stats()
        if not stats:
            return None
        return int(stats.get("bytes_in_use", 0)) or None
    except Exception:  # noqa: BLE001 — accounting must never throw
        return None


def sample_device_gauges(ctx) -> None:
    """Scrape-time fold of every live query's arena bytes into the
    device gauges (called from prometheus.sample_gauges under the
    scrape lock). Cost is O(live planes) attribute reads — zero device
    work — and stale per-query series are swept like every other
    query-labeled gauge."""
    stats = ctx.stats
    tasks = dict(getattr(ctx, "running_queries", {}))
    live: set[tuple[str, str]] = set()
    total = 0
    for qid, task in tasks.items():
        fn = getattr(task, "device_plane_bytes", None)
        if fn is None:
            continue
        try:
            planes = fn()
        except Exception:  # noqa: BLE001 — a task tearing down mid-
            continue       # scrape must not fail the scrape
        q_total = 0
        for plane, nb in sorted(planes.items()):
            key = f"{qid}/{plane}"
            stats.gauge_set("device_arena_bytes", key, nb)
            live.add(("device_arena_bytes", key))
            q_total += nb
        stats.gauge_set("device_hbm_bytes", qid, q_total)
        live.add(("device_hbm_bytes", qid))
        total += q_total
    from hstream_tpu.stats.prometheus import _drop_stale

    _drop_stale(stats, ("device_arena_bytes", "device_hbm_bytes"), live)
    stats.gauge_set("device_hbm_total_bytes", "", total)
    backend = backend_hbm_bytes()
    if backend is not None:
        stats.gauge_set("device_hbm_backend_bytes", "", backend)


def query_hbm_bytes(ctx, qid: str) -> dict:
    """{total, planes} for one query — the flight recorder's HBM page
    and the admin surface's per-query answer."""
    task = dict(getattr(ctx, "running_queries", {})).get(qid)
    fn = getattr(task, "device_plane_bytes", None) if task else None
    if fn is None:
        return {"total": 0, "planes": {}}
    try:
        planes = {k: int(v) for k, v in sorted(fn().items())}
    except Exception:  # noqa: BLE001
        return {"total": 0, "planes": {}}
    return {"total": sum(planes.values()), "planes": planes}


# ---- compiled-program inventory ---------------------------------------------


class ProgramInventory:
    """Process-wide catalog of every XLA executable built in this
    process, keyed by shape key (crc32 of the lowered MLIR module
    text — two calls over the same shapes share one row; a new shape
    is a new row). Bounded LRU: past MAX_ROWS the oldest row folds
    into the `evicted` count rather than growing without bound."""

    MAX_ROWS = 512

    def __init__(self):
        self._rows: "OrderedDict[str, dict]" = OrderedDict()
        self._lock = threading.Lock()
        self._installed = False
        self._install_failed = False
        self.evicted = 0

    def install(self) -> bool:
        """Wrap the compile funnel once (idempotent). Returns False and
        leaves the inventory empty-but-harmless if the private seam is
        absent in this jax build."""
        with self._lock:
            if self._installed:
                return True
            if self._install_failed:
                return False
            try:
                from jax._src import compiler as _compiler

                orig = _compiler.compile_or_get_cached
            except Exception:  # noqa: BLE001 — private seam moved:
                self._install_failed = True    # degrade, don't break
                return False
            inv = self

            def _record_and_compile(*args, **kwargs):
                t0 = time.perf_counter()
                exe = orig(*args, **kwargs)
                try:
                    inv._record(exe,
                                (time.perf_counter() - t0) * 1e3, args)
                except Exception:  # noqa: BLE001 — inventory plumbing
                    pass           # must never break a compile
                return exe

            _compiler.compile_or_get_cached = _record_and_compile
            self._installed = True
            return True

    def _record(self, exe, compile_ms: float, args) -> None:
        from hstream_tpu.common.tracing import current_kernel_family

        name = None
        try:
            hm = exe.hlo_modules()
            if hm:
                name = hm[0].name
        except Exception:  # noqa: BLE001
            pass
        key = None
        try:
            # args[1] is the lowered MLIR module at every pxla call
            # site; its text embeds every shape, so the crc IS the
            # shape key
            if len(args) > 1 and args[1] is not None:
                key = f"{zlib.crc32(str(args[1]).encode()):08x}"
        except Exception:  # noqa: BLE001
            pass
        if key is None:
            key = f"name:{name or 'unknown'}"
        flops = bytes_accessed = None
        try:
            ca = exe.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            if ca:
                flops = ca.get("flops")
                bytes_accessed = ca.get("bytes accessed")
        except Exception:  # noqa: BLE001 — cost analysis is
            pass           # best-effort per backend
        family = current_kernel_family()
        now_ms = time.time() * 1e3
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                while len(self._rows) >= self.MAX_ROWS:
                    self._rows.popitem(last=False)
                    self.evicted += 1
                row = {"shape_key": key, "name": name or "?",
                       "family": family or "", "compiles": 0,
                       "compile_ms": 0.0, "flops": None,
                       "bytes_accessed": None,
                       "first_unix_ms": round(now_ms, 1)}
                self._rows[key] = row
            else:
                self._rows.move_to_end(key)
            row["compiles"] += 1
            row["compile_ms"] = round(row["compile_ms"] + compile_ms, 3)
            if family:
                row["family"] = family
            if flops is not None:
                row["flops"] = float(flops)
            if bytes_accessed is not None:
                row["bytes_accessed"] = float(bytes_accessed)
            row["last_unix_ms"] = round(now_ms, 1)

    def rows(self) -> list[dict]:
        """Newest-compiled last (the LRU order), each row a plain
        JSON-ready dict."""
        with self._lock:
            return [dict(r) for r in self._rows.values()]

    def summary(self) -> dict:
        with self._lock:
            rows = list(self._rows.values())
            return {
                "programs": len(rows),
                "evicted": self.evicted,
                "installed": self._installed,
                "total_compile_ms": round(
                    sum(r["compile_ms"] for r in rows), 3),
                "total_compiles": sum(r["compiles"] for r in rows),
            }


PROGRAMS = ProgramInventory()


# ---- per-dispatch device time -----------------------------------------------


class DeviceTimeSampler:
    """Deterministic 1/N device-time sampling for kernel_family scopes.

    `active` is a plain attribute (False while disarmed) — the
    disarmed hot-path cost inside `kernel_family` is one attribute
    read + one branch, and the disarmed sampler holds ZERO state (no
    tick counters, no sample rings): `bench.py --smoke` gates both.
    Armed, every Nth dispatch per family is measured as a fenced
    block-until-ready pair; the milliseconds land in the bounded
    per-family rings (bench attribution) and in every registered stats
    sink's `kernel_device_ms{family}` histogram."""

    MAX_SAMPLES = 256

    def __init__(self):
        self.active = False
        self.rate = 0
        self._counts: dict[str, int] = {}
        self._samples: dict[str, deque] = {}
        self._sinks: list = []  # weakrefs: torn-down holders must die
        self._lock = threading.Lock()

    def arm(self, rate: int) -> None:
        with self._lock:
            self.rate = max(1, int(rate))
            self.active = True

    def disarm(self) -> None:
        with self._lock:
            self.active = False
            self.rate = 0

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._samples.clear()

    def add_sink(self, stats) -> None:
        with self._lock:
            if not any(ref() is stats for ref in self._sinks):
                self._sinks.append(weakref.ref(stats))

    # contract: dispatches<=0 fetches<=0
    def tick(self, family: str) -> bool:
        """The deterministic sampling decision: true on every Nth
        dispatch of the family. Only ever called armed."""
        with self._lock:
            c = self._counts.get(family, 0) + 1
            self._counts[family] = c
            return self.rate > 0 and c % self.rate == 0

    # contract: dispatches<=0 fetches<=1
    def fence(self, ready) -> None:
        """Drain in-flight device work on the dispatch's values so the
        timed region covers only the sampled dispatch — the sampled
        path's ONE sanctioned pre-body sync."""
        import jax

        jax.block_until_ready(ready())

    # contract: dispatches<=0 fetches<=1
    def measure(self, family: str, ready, t0: float) -> None:
        """Post-body half of a sampled dispatch: block on the results
        and record the fenced wall time as device milliseconds."""
        import jax

        jax.block_until_ready(ready())
        self.record(family, (time.perf_counter() - t0) * 1e3)

    # contract: dispatches<=0 fetches<=0
    def record(self, family: str, ms: float) -> None:
        with self._lock:
            ring = self._samples.get(family)
            if ring is None:
                ring = deque(maxlen=self.MAX_SAMPLES)
                self._samples[family] = ring
            ring.append(float(ms))
            sinks = list(self._sinks)
        dead = []
        for ref in sinks:
            stats = ref()
            if stats is None:
                dead.append(ref)
                continue
            try:
                stats.observe("kernel_device_ms", family, float(ms))
            except Exception:  # noqa: BLE001 — metrics plumbing must
                pass           # never fail a dispatch
        if dead:
            with self._lock:
                for ref in dead:
                    if ref in self._sinks:
                        self._sinks.remove(ref)

    def state(self) -> dict:
        """Everything the sampler remembers — the disarmed-witness
        gate asserts this is empty after a disarmed run."""
        with self._lock:
            return {"counts": dict(self._counts),
                    "samples": {k: len(v)
                                for k, v in self._samples.items()}}

    def percentiles(self) -> dict[str, dict[str, float]]:
        """family -> {count, p50, p99} over the bounded sample rings
        (the bench's device_time_ms attribution)."""
        out: dict[str, dict[str, float]] = {}
        with self._lock:
            rings = {k: sorted(v) for k, v in self._samples.items() if v}
        for fam, xs in rings.items():
            n = len(xs)
            out[fam] = {
                "count": n,
                "p50": round(xs[n // 2], 4),
                "p99": round(xs[min(n - 1, (n * 99) // 100)], 4),
            }
        return out


DEVICE_TIME = DeviceTimeSampler()
