"""Cluster stats federation: per-node load reports, merged on demand.

Reference: ``hadmin server stats`` asks every node for its stats holder
and prints one merged table; the Overview endpoint does the same over
HTTP (SURVEY layer 2, §2.1). Our reproduction had per-NODE stats only —
nothing answered "which host is hot" for the thousand-query placer
(ROADMAP item 2), whose placement decisions gate on exactly the numbers
folded here.

Three pieces:

  * ``node_report(ctx)`` folds THIS node's StatsHolder into one
    JSON-able dict: per-stream rate ladders (every stream-scoped
    family x 1min/10min/1h + all-time), per-query health level +
    watermark lag + emit p99, node-wide kernel-dispatch p99,
    append-front queue depth, arena/pipeline occupancy, and rss.
  * ``collect_cluster(ctx, peers)`` fans out the protopatch-evolved
    ``ClusterStats`` RPC to explicit ``--peers`` (full HStreamApi
    servers), falling back per-address to the ``StoreReplica`` face so
    bare follower processes answer too; with no peers given it asks
    the replicated store's followers. Unreachable nodes come back as
    an ``error`` row — a dead peer must be VISIBLE in the merged
    table, not silently absent.
  * ``LoadReporter`` journals a periodic ``node_load_report`` event —
    THE machine-readable load signal placement/failover adoption gate
    on: bounded (top-K streams by 1min byte rate), cheap (one holder
    fold per period), and queryable via ``admin events --kind
    node_load_report`` / ``GET /events``.
"""

from __future__ import annotations

import json
import os
import threading
import time

from hstream_tpu.stats.families import families_for_scope
from hstream_tpu.stats.timeseries import INTERVAL_NAMES

# streams carried by the periodic journal event, by 1min byte rate —
# the event rides a bounded ring; an unbounded stream list would turn
# a wide topology into journal churn (the FULL ladder stays available
# via the ClusterStats RPC / admin cluster-stats on demand)
LOAD_REPORT_TOP_STREAMS = 8

DEFAULT_LOAD_REPORT_INTERVAL_S = 30.0

_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_bytes() -> int:
    """Current resident set size of this process. /proc when the
    platform has it (linux), peak-rss via resource otherwise — a load
    signal, not an accounting number."""
    try:
        with open("/proc/self/statm", encoding="ascii") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource
        import sys

        # ru_maxrss unit differs by platform: bytes on macOS (where
        # this fallback actually runs — no /proc), kilobytes elsewhere
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return rss if sys.platform == "darwin" else rss * 1024
    except Exception:  # noqa: BLE001 — a load report must not fail
        return 0


def live_entity_keys(ctx, scope: str) -> set[str]:
    """THE definition of "live" for one stat-family scope — shared by
    the admin `stats` verb, the scrape-time ``stat_drop_stale`` sweep,
    and the exposition's liveness filters, so they cannot drift apart.
    Raises whatever the underlying registry raises; callers choose
    fail-open vs skip."""
    if scope == "stream":
        keys = set(ctx.streams.find_streams())
        # materialized views are live READ endpoints: pull queries feed
        # stream-scoped families (read_out_records, read_extracts) keyed
        # by view name, which must survive the liveness sweep until the
        # view itself is dropped (ISSUE 20 read plane)
        try:
            keys.update(ctx.views.names())
        except Exception:  # noqa: BLE001 — bare test contexts
            pass
        return keys
    if scope == "subscription":
        return {rt.sub_id for rt in ctx.subscriptions.list()}
    if scope == "query":
        return {q.query_id for q in ctx.persistence.get_queries()}
    raise KeyError(f"unknown stat scope {scope!r}")


def _stream_ladders(stats, now: float | None = None) -> dict:
    """stream -> family -> {1min,10min,1h,total,total_count}."""
    out: dict[str, dict] = {}
    for fam in families_for_scope("stream"):
        for key in stats.stat_keys(fam.name):
            out.setdefault(key, {})[fam.name] = \
                stats.stat_ladder(fam.name, key, now)
    return out


def _query_health(ctx) -> dict:
    """qid -> {health_level, verdict, watermark_lag_ms, emit_p99_ms}.
    Health comes from the ISSUE 13 plane; a half-built context (tests
    construct bare ones) reports no queries rather than failing."""
    out: dict[str, dict] = {}
    try:
        from hstream_tpu.server import health as _health

        for qid, h in _health.evaluate_all(ctx).items():
            out[qid] = {
                "verdict": h.get("verdict"),
                "health_level": h.get("level",
                                      {"OK": 0, "DEGRADED": 1,
                                       "STALLED": 2}.get(
                                          h.get("verdict"), 0)),
                "watermark_lag_ms": h.get("watermark_lag_ms"),
                "emit_p99_ms": ctx.stats.histogram_percentile(
                    "emit_latency_ms", qid, 99),
            }
    except Exception:  # noqa: BLE001 — the report must not fail
        pass
    return out


def node_report(ctx) -> dict:
    """Fold this node's holder + live subsystems into one load report
    (host-mirror reads only: zero dispatches, zero fetches)."""
    from hstream_tpu.server import scheduler

    stats = ctx.stats
    store = ctx.store
    role = "leader" if hasattr(store, "follower_status") else "single"
    front = getattr(ctx, "append_front", None)
    front_stats = {}
    if front is not None:
        try:
            front_stats = front.stats()
        except Exception:  # noqa: BLE001
            front_stats = {}
    # arena occupancy: staged-but-unstepped batches across running
    # query pipelines (the host mirror of device arena pressure)
    arena_pending = 0
    # device HBM footprint: live arena/store bytes across every running
    # query's executor planes (ISSUE 18) — nbytes metadata reads only
    device_hbm = 0
    for task in list(getattr(ctx, "running_queries", {}).values()):
        pipe = getattr(task, "_pipe", None)
        if pipe is not None:
            try:
                arena_pending += int(pipe.pending)
            except Exception:  # noqa: BLE001
                pass
        fn = getattr(task, "device_plane_bytes", None)
        if fn is not None:
            try:
                device_hbm += sum(fn().values())
            except Exception:  # noqa: BLE001
                pass
    return {
        "node": scheduler.node_name(ctx),
        "addr": f"{ctx.host}:{ctx.port}",
        "role": role,
        "ts_ms": int(time.time() * 1000),
        "rss_bytes": rss_bytes(),
        "device_hbm_bytes": device_hbm,
        "running_queries": len(getattr(ctx, "running_queries", {})),
        "append_inflight": int(front_stats.get("in_flight", 0)),
        "append_front": front_stats,
        "arena_pending_batches": arena_pending,
        "dispatch_p99_ms": stats.histogram_percentile(
            "kernel_dispatch_ms", "", 99),
        "streams": _stream_ladders(stats),
        "queries": _query_health(ctx),
    }


def load_report_fields(ctx) -> dict:
    """The bounded journal shape of ``node_report`` (top-K streams,
    health counts instead of the per-query map)."""
    full = node_report(ctx)
    streams = full["streams"]
    ranked = sorted(
        streams,
        key=lambda s: streams[s].get("append_in_bytes",
                                     {}).get("1min", 0.0),
        reverse=True)
    top = {s: {fam: {"1min": lad.get("1min", 0.0),
                     "10min": lad.get("10min", 0.0)}
               for fam, lad in streams[s].items()}
           for s in ranked[:LOAD_REPORT_TOP_STREAMS]}
    levels = [q.get("health_level", 0)
              for q in full["queries"].values()]
    return {
        "node": full["node"],
        "addr": full["addr"],
        "role": full["role"],
        "rss_bytes": full["rss_bytes"],
        "device_hbm_bytes": full.get("device_hbm_bytes", 0),
        "running_queries": full["running_queries"],
        "append_inflight": full["append_inflight"],
        "arena_pending_batches": full["arena_pending_batches"],
        "dispatch_p99_ms": full["dispatch_p99_ms"],
        "streams": top,
        "streams_total": len(streams),
        "health": {"ok": sum(1 for v in levels if v == 0),
                   "degraded": sum(1 for v in levels if v == 1),
                   "stalled": sum(1 for v in levels if v == 2)},
    }


# ---- placer node records (ISSUE 17) ----------------------------------------

# Per-node load records in the CAS-versioned config store, keyed
# ``cluster/nodes/<node>``. The journal's node_load_report events are
# per-PROCESS rings — a peer's placer can't read them — so placement
# runs off these shared records instead: every armed placer publishes
# its own node's fold each tick, and every placer ranks ALL fresh
# records when it decides. Same bounded shape as the journal event,
# plus the placement-eligibility axes (epoch, heartbeat, shed level,
# fenced flag).
NODE_RECORD_PREFIX = "cluster/nodes/"


def node_record_fields(ctx) -> dict:
    """The placement view of this node: load_report_fields minus the
    per-stream ladders (scores don't rank on them), plus eligibility
    signals."""
    fields = load_report_fields(ctx)
    fields.pop("streams", None)
    fields["ts_ms"] = int(time.time() * 1000)
    fields["hb_ms"] = fields["ts_ms"]
    fields["epoch"] = getattr(ctx, "boot_epoch", 0)
    flow = getattr(ctx, "flow", None)
    fields["shed_level"] = 0 if flow is None \
        else int(flow.overload.effective_level())
    fields["fenced"] = bool(
        getattr(ctx.store, "fenced_by", None) is not None)
    return fields


def publish_node_record(ctx) -> dict | None:
    """Write this node's record to ``cluster/nodes/<node>``; the write
    doubles as the node's cluster-level heartbeat. Read-modify-write
    CAS (single writer per node, but a racing admin/test write must
    not wedge the publisher). Returns the published fields, or None
    when every retry lost."""
    from hstream_tpu.store.versioned import VersionMismatch

    fields = node_record_fields(ctx)
    key = NODE_RECORD_PREFIX + fields["node"]
    value = json.dumps(fields).encode()
    for _ in range(4):
        cur = ctx.config.get(key)
        try:
            ctx.config.put(key, value,
                           base_version=None if cur is None else cur[0])
            return fields
        except VersionMismatch:
            continue
    return None


def cluster_node_records(ctx) -> dict[str, dict]:
    """node name -> last published record, every node that ever
    published on this store (callers filter by heartbeat age)."""
    out: dict[str, dict] = {}
    for key in ctx.config.keys():
        if not key.startswith(NODE_RECORD_PREFIX):
            continue
        cur = ctx.config.get(key)
        if cur is None:
            continue
        try:
            rec = json.loads(cur[1])
        except ValueError:
            continue
        out[key[len(NODE_RECORD_PREFIX):]] = rec
    return out


# ---- RPC glue --------------------------------------------------------------


def report_to_pb(report: dict):
    """One node's dict -> NodeStatsReport (scalars structured, the
    deep ladders as a JSON detail blob — the admin merge re-parses)."""
    from hstream_tpu.proto import api_pb2 as pb

    return pb.NodeStatsReport(
        node=str(report.get("node", "")),
        role=str(report.get("role", "")),
        ts_ms=int(report.get("ts_ms", 0)),
        rss_bytes=int(report.get("rss_bytes", 0)),
        running_queries=int(report.get("running_queries", 0)),
        append_inflight=int(report.get("append_inflight", 0)),
        report=json.dumps(report))


def report_from_pb(msg) -> dict:
    try:
        out = json.loads(msg.report) if msg.report else {}
    except ValueError:
        out = {}
    out.setdefault("node", msg.node)
    out.setdefault("role", msg.role)
    out.setdefault("rss_bytes", msg.rss_bytes)
    out.setdefault("running_queries", msg.running_queries)
    out.setdefault("append_inflight", msg.append_inflight)
    return out


def _fetch_peer(addr: str, timeout: float) -> dict:
    """One peer's report over ClusterStats: the full HStreamApi face
    first, the bare StoreReplica face (follower processes) second."""
    import grpc

    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub, StoreReplicaStub

    last_err: Exception | None = None
    for stub_cls in (HStreamApiStub, StoreReplicaStub):
        try:
            with grpc.insecure_channel(addr) as ch:
                resp = stub_cls(ch).ClusterStats(
                    pb.ClusterStatsRequest(), timeout=timeout)
            reports = list(resp.reports)
            if reports:
                out = report_from_pb(reports[0])
                out.setdefault("addr", addr)
                return out
            last_err = RuntimeError("empty ClusterStats response")
        except grpc.RpcError as e:  # try the other service face
            last_err = e
    detail = getattr(last_err, "details", lambda: None)() \
        or str(last_err)
    return {"node": addr, "addr": addr, "role": "unreachable",
            "error": detail}


def collect_cluster(ctx, peers: list[str] | None = None,
                    timeout: float = 5.0) -> list[dict]:
    """This node's report + one report per peer. Explicit peers win;
    otherwise a replication leader asks its followers. Peers answer
    concurrently (one thread per address, bounded by the peer list) so
    one dead node costs ONE timeout, not len(peers) of them."""
    reports = [node_report(ctx)]
    if not peers:
        status = getattr(ctx.store, "follower_status", None)
        if status is not None:
            try:
                peers = [f["addr"] for f in status()]
            except Exception:  # noqa: BLE001
                peers = []
    if not peers:
        return reports
    out: list[dict | None] = [None] * len(peers)

    def fetch(i: int, addr: str) -> None:
        out[i] = _fetch_peer(addr, timeout)

    threads = [threading.Thread(target=fetch, args=(i, a), daemon=True)
               for i, a in enumerate(peers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout + 1.0)
    for i, addr in enumerate(peers):
        reports.append(out[i] or {"node": addr, "addr": addr,
                                  "role": "unreachable",
                                  "error": "fan-out timed out"})
    return reports


def merge_rows(reports: list[dict],
               interval: str = "1min") -> list[dict]:
    """The admin `cluster-stats` table: one node summary row per node,
    then one row per (node, stream) with the family rates at every
    interval — rates are per-node by construction (each node folds its
    OWN holder), so the merge is a concatenation keyed (node, stream),
    never a lossy re-aggregation."""
    if interval not in INTERVAL_NAMES:
        raise KeyError(f"unknown interval {interval!r} "
                       f"(one of {INTERVAL_NAMES})")
    rows: list[dict] = []
    for rep in reports:
        row = {"node": rep.get("node"), "stream": "(node)",
               "role": rep.get("role"),
               "rss_mb": round(rep.get("rss_bytes", 0) / 1e6, 1),
               "queries": rep.get("running_queries", 0),
               "append_inflight": rep.get("append_inflight", 0)}
        if rep.get("error"):
            row["error"] = rep["error"]
        rows.append(row)
    for rep in reports:
        for stream in sorted(rep.get("streams", {})):
            ladders = rep["streams"][stream]
            row = {"node": rep.get("node"), "stream": stream,
                   "role": rep.get("role")}
            for fam in families_for_scope("stream"):
                lad = ladders.get(fam.name)
                if lad is None:
                    continue
                row[f"{fam.name}_{interval}"] = \
                    round(lad.get(interval, 0.0), 3)
                row[f"{fam.name}_total"] = lad.get("total", 0.0)
            rows.append(row)
    return rows


class LoadReporter:
    """Periodic ``node_load_report`` journal events off a daemon
    thread: one bounded holder fold per interval, first report at
    start so a fresh boot is immediately visible to the placer."""

    def __init__(self, ctx, interval_s: float =
                 DEFAULT_LOAD_REPORT_INTERVAL_S):
        self.ctx = ctx
        self.interval_s = max(float(interval_s), 0.5)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="load-reporter", daemon=True)

    def start(self) -> None:
        """Called AFTER the server's port is bound (server/main.serve):
        the boot report carries the node's real identity — on an
        ephemeral port, a reporter started at context construction
        would journal a phantom `host:0` node the placer can't match
        to any later report."""
        self._thread.start()

    def emit(self) -> int:
        """Journal one report now; returns its seq (0 on failure —
        load reporting must never take the server down)."""
        try:
            fields = load_report_fields(self.ctx)
            return self.ctx.events.append(
                "node_load_report",
                f"node {fields['node']}: "
                f"{fields['running_queries']} queries, "
                f"rss {fields['rss_bytes'] // 1_000_000}MB, "
                f"{fields['streams_total']} active streams",
                **fields)
        except Exception:  # noqa: BLE001
            return 0

    def _run(self) -> None:
        self.emit()  # boot-time baseline
        while not self._stop.wait(self.interval_s):
            self.emit()

    def stop(self) -> None:
        self._stop.set()
        if self._thread.ident is not None:  # never started: no join
            self._thread.join(timeout=2.0)
