"""NativeLogStore: ctypes binding over the embedded C++ segment-log store
(cpp/nstore.cpp). Implements the frozen LogStore/LogReader API
(store/api.py) with durable group-commit appends, zlib batch
compression, trim gaps, and a persistent metadata KV.

Blocking reader calls release the GIL (ctypes foreign calls), so a
server thread blocked in read() does not stall Python — the property the
reference gets from Haskell green threads over its FFI
(hstream-store HStream/Store/Internal/Foreign.hs:41-61).

The async append path (AsyncAppender) exposes the C++ completion queue
as concurrent futures: the asyncio-facing analogue of the reference's
append callback + hs_try_putmvar pattern (cbits/logdevice
hs_writer.cpp:36-45).
"""

from __future__ import annotations

import ctypes as C
import json
import struct
import threading
from concurrent.futures import Future
from typing import Sequence

from hstream_tpu.common.errors import LogNotFound, StoreError
from hstream_tpu.store.api import (
    LSN_MAX,
    LSN_MIN,
    Compression,
    DataBatch,
    GapRecord,
    GapType,
    LogAttrs,
    LogReader,
    LogStore,
    ReadResult,
)
from hstream_tpu.store.build import build

_lib = None
_lib_lock = threading.Lock()


def _load():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        lib = C.CDLL(build())
        lib.ns_open.restype = C.c_void_p
        lib.ns_open.argtypes = [C.c_char_p, C.c_char_p]
        lib.ns_close.argtypes = [C.c_void_p]
        lib.ns_set_sync_interval.argtypes = [C.c_void_p, C.c_int64]
        lib.ns_set_seg_bytes.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_create_log.argtypes = [C.c_void_p, C.c_uint64, C.c_char_p,
                                      C.c_char_p]
        lib.ns_remove_log.argtypes = [C.c_void_p, C.c_uint64, C.c_char_p]
        lib.ns_log_exists.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_list_logs.restype = C.c_int64
        lib.ns_list_logs.argtypes = [C.c_void_p, C.POINTER(C.c_uint64),
                                     C.c_int64]
        lib.ns_log_attrs.restype = C.c_int64
        lib.ns_log_attrs.argtypes = [C.c_void_p, C.c_uint64, C.c_char_p,
                                     C.c_int64]
        lib.ns_append_batch.restype = C.c_int64
        lib.ns_append_batch.argtypes = [
            C.c_void_p, C.c_uint64, C.c_char_p, C.POINTER(C.c_uint32),
            C.c_uint32, C.c_int, C.c_int, C.c_char_p, C.c_int64]
        lib.ns_append_async.argtypes = [
            C.c_void_p, C.c_uint64, C.c_char_p, C.POINTER(C.c_uint32),
            C.c_uint32, C.c_int, C.c_uint64]
        lib.ns_poll_completions.restype = C.c_int64
        lib.ns_poll_completions.argtypes = [
            C.c_void_p, C.POINTER(C.c_uint64), C.POINTER(C.c_int64),
            C.c_int64, C.c_int64]
        lib.ns_tail_lsn.restype = C.c_int64
        lib.ns_tail_lsn.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_trim.argtypes = [C.c_void_p, C.c_uint64, C.c_int64,
                                C.c_char_p]
        lib.ns_trim_point.restype = C.c_int64
        lib.ns_trim_point.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_find_time.restype = C.c_int64
        lib.ns_find_time.argtypes = [C.c_void_p, C.c_uint64, C.c_int64]
        lib.ns_is_log_empty.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_meta_put.argtypes = [C.c_void_p, C.c_char_p, C.c_char_p,
                                    C.c_int64]
        lib.ns_meta_get.restype = C.c_int64
        lib.ns_meta_get.argtypes = [C.c_void_p, C.c_char_p, C.c_char_p,
                                    C.c_int64]
        lib.ns_meta_delete.argtypes = [C.c_void_p, C.c_char_p]
        lib.ns_meta_list.restype = C.c_int64
        lib.ns_meta_list.argtypes = [C.c_void_p, C.c_char_p, C.c_char_p,
                                     C.c_int64]
        lib.ns_meta_cas.argtypes = [C.c_void_p, C.c_char_p, C.c_char_p,
                                    C.c_int64, C.c_char_p, C.c_int64]
        lib.ns_reader_new.restype = C.c_void_p
        lib.ns_reader_new.argtypes = [C.c_void_p]
        lib.ns_reader_free.argtypes = [C.c_void_p]
        lib.ns_reader_start.argtypes = [C.c_void_p, C.c_uint64, C.c_int64,
                                        C.c_int64]
        lib.ns_reader_stop.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_reader_is_reading.argtypes = [C.c_void_p, C.c_uint64]
        lib.ns_reader_set_timeout.argtypes = [C.c_void_p, C.c_int64]
        lib.ns_reader_read.restype = C.c_int64
        lib.ns_reader_read.argtypes = [C.c_void_p, C.c_int64, C.c_char_p,
                                       C.c_int64]
        _lib = lib
        return lib


def _pack_payloads(payloads: Sequence[bytes]):
    lens = (C.c_uint32 * len(payloads))(*[len(p) for p in payloads])
    return b"".join(bytes(p) for p in payloads), lens


class NativeLogStore(LogStore):
    """Durable embedded store rooted at a directory."""

    def __init__(self, root: str, *, sync_interval_ms: int = 2,
                 segment_bytes: int | None = None):
        self.root = str(root)  # observability: segment/WAL size gauges
        self._lib = _load()
        err = C.create_string_buffer(256)
        self._h = self._lib.ns_open(str(root).encode(), err)
        if not self._h:
            raise StoreError(f"open_store({root!r}): "
                             f"{err.value.decode(errors='replace')}")
        self._lib.ns_set_sync_interval(self._h, sync_interval_ms)
        if segment_bytes is not None:
            self._lib.ns_set_seg_bytes(self._h, segment_bytes)
        self._closed = False
        self._appender: AsyncAppender | None = None
        self._appender_lock = threading.Lock()

    # ---- lifecycle ----
    def create_log(self, logid: int, attrs: LogAttrs | None = None) -> None:
        a = attrs or LogAttrs()
        blob = json.dumps({"replication_factor": a.replication_factor,
                           "backlog_seconds": a.backlog_seconds,
                           "extras": a.extras}).encode()
        err = C.create_string_buffer(256)
        if self._lib.ns_create_log(self._h, logid, blob, err) != 0:
            raise StoreError(f"create_log {logid}: {err.value.decode()}")

    def remove_log(self, logid: int) -> None:
        err = C.create_string_buffer(256)
        if self._lib.ns_remove_log(self._h, logid, err) != 0:
            raise LogNotFound(f"log {logid}")

    def log_exists(self, logid: int) -> bool:
        return bool(self._lib.ns_log_exists(self._h, logid))

    def list_logs(self) -> list[int]:
        cap = 1024
        while True:
            out = (C.c_uint64 * cap)()
            n = self._lib.ns_list_logs(self._h, out, cap)
            if n <= cap:
                return sorted(out[i] for i in range(n))
            cap = n

    def log_attrs(self, logid: int) -> LogAttrs:
        cap = 8192
        out = C.create_string_buffer(cap)
        n = self._lib.ns_log_attrs(self._h, logid, out, cap)
        if n < 0:
            raise LogNotFound(f"log {logid}")
        try:
            d = json.loads(out.raw[:n].decode())
        except ValueError:
            d = {}
        return LogAttrs(replication_factor=d.get("replication_factor", 1),
                        backlog_seconds=d.get("backlog_seconds", 0),
                        extras=d.get("extras", {}))

    # ---- append ----
    def append_batch(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE, *,
                     append_time_ms: int | None = None) -> int:
        if not payloads:
            raise StoreError("empty batch")
        buf, lens = _pack_payloads(payloads)
        err = C.create_string_buffer(256)
        lsn = self._lib.ns_append_batch(
            self._h, logid, buf, lens, len(payloads),
            1 if compression == Compression.ZLIB else 0, 1, err,
            append_time_ms or 0)
        if lsn < 0:
            msg = err.value.decode()
            if "not found" in msg:
                raise LogNotFound(f"log {logid}")
            raise StoreError(f"append {logid}: {msg}")
        return lsn

    def append_async(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE
                     ) -> "Future[int]":
        """Queue an append; the returned future resolves to the LSN after
        the batch is durably written (C++ completion queue)."""
        if self._appender is None:
            # locked: two tasks racing first use must share ONE appender
            # (two would collide token counters on the one C++ queue)
            with self._appender_lock:
                if self._appender is None:
                    self._appender = AsyncAppender(self)
        return self._appender.submit(logid, payloads, compression)

    # ---- introspection ----
    def tail_lsn(self, logid: int) -> int:
        n = self._lib.ns_tail_lsn(self._h, logid)
        if n < 0:
            raise LogNotFound(f"log {logid}")
        return n

    def trim(self, logid: int, up_to_lsn: int) -> None:
        err = C.create_string_buffer(256)
        if self._lib.ns_trim(self._h, logid, up_to_lsn, err) != 0:
            raise LogNotFound(f"log {logid}")

    def trim_point(self, logid: int) -> int:
        n = self._lib.ns_trim_point(self._h, logid)
        if n < 0:
            raise LogNotFound(f"log {logid}")
        return n

    def find_time(self, logid: int, ts_ms: int) -> int:
        n = self._lib.ns_find_time(self._h, logid, ts_ms)
        if n < 0:
            raise LogNotFound(f"log {logid}")
        return n

    def is_log_empty(self, logid: int) -> bool:
        n = self._lib.ns_is_log_empty(self._h, logid)
        if n < 0:
            raise LogNotFound(f"log {logid}")
        return bool(n)

    # ---- reading ----
    def new_reader(self, max_logs: int = 1) -> "NativeLogReader":
        return NativeLogReader(self)

    # ---- metadata KV ----
    def meta_put(self, key: str, value: bytes) -> None:
        self._lib.ns_meta_put(self._h, key.encode(), bytes(value),
                              len(value))

    def meta_get(self, key: str) -> bytes | None:
        cap = 64 * 1024
        while True:
            out = C.create_string_buffer(cap)
            n = self._lib.ns_meta_get(self._h, key.encode(), out, cap)
            if n < 0:
                return None
            if n <= cap:
                return out.raw[:n]
            cap = n

    def meta_delete(self, key: str) -> None:
        self._lib.ns_meta_delete(self._h, key.encode())

    def meta_list(self, prefix: str) -> list[str]:
        cap = 256 * 1024
        while True:
            out = C.create_string_buffer(cap)
            n = self._lib.ns_meta_list(self._h, prefix.encode(), out, cap)
            if n <= cap:
                s = out.raw[:n].decode()
                return s.split("\n") if s else []
            cap = n

    def meta_cas(self, key: str, expected: bytes | None,
                 value: bytes) -> bool:
        exp = b"" if expected is None else bytes(expected)
        explen = -1 if expected is None else len(exp)
        return bool(self._lib.ns_meta_cas(self._h, key.encode(), exp,
                                          explen, bytes(value), len(value)))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            if self._appender is not None:
                self._appender.close()
            self._lib.ns_close(self._h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class AsyncAppender:
    """Bridges the C++ append completion queue to concurrent futures
    (awaitable from asyncio via wrap_future)."""

    def __init__(self, store: NativeLogStore):
        self._store = store
        self._lock = threading.Lock()
        self._next_token = 1
        self._futures: dict[int, Future] = {}
        self._stop = False
        self._drainer = threading.Thread(target=self._drain, daemon=True)
        self._drainer.start()

    def submit(self, logid: int, payloads: Sequence[bytes],
               compression: Compression) -> "Future[int]":
        buf, lens = _pack_payloads(payloads)
        fut: Future = Future()
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._futures[token] = fut
        rc = self._store._lib.ns_append_async(
            self._store._h, logid, buf, lens, len(payloads),
            1 if compression == Compression.ZLIB else 0, token)
        if rc != 0:
            with self._lock:
                self._futures.pop(token, None)
            fut.set_exception(StoreError("store is closing"))
        return fut

    def _drain(self) -> None:
        maxn = 256
        tokens = (C.c_uint64 * maxn)()
        lsns = (C.c_int64 * maxn)()
        while not self._stop:
            n = self._store._lib.ns_poll_completions(
                self._store._h, tokens, lsns, maxn, 100)
            for i in range(n):
                with self._lock:
                    fut = self._futures.pop(tokens[i], None)
                if fut is None:
                    continue
                if lsns[i] > 0:
                    fut.set_result(lsns[i])
                else:
                    fut.set_exception(StoreError("async append failed"))

    def close(self) -> None:
        self._stop = True
        self._drainer.join(timeout=2)
        with self._lock:
            for fut in self._futures.values():
                if not fut.done():
                    fut.set_exception(StoreError("store closed"))
            self._futures.clear()


class NativeLogReader(LogReader):
    def __init__(self, store: NativeLogStore):
        self._store = store
        self._rh = store._lib.ns_reader_new(store._h)
        self._cap = 4 * 1024 * 1024

    def start_reading(self, logid: int, from_lsn: int = LSN_MIN,
                      until_lsn: int = LSN_MAX) -> None:
        if self._store._lib.ns_reader_start(self._rh, logid, from_lsn,
                                            until_lsn) != 0:
            raise LogNotFound(f"log {logid}")

    def stop_reading(self, logid: int) -> None:
        self._store._lib.ns_reader_stop(self._rh, logid)

    def is_reading(self, logid: int) -> bool:
        return bool(self._store._lib.ns_reader_is_reading(self._rh, logid))

    def set_timeout(self, timeout_ms: int) -> None:
        self._store._lib.ns_reader_set_timeout(self._rh, timeout_ms)

    def read(self, max_records: int) -> list[ReadResult]:
        while True:
            buf = C.create_string_buffer(self._cap)
            n = self._store._lib.ns_reader_read(self._rh, max_records, buf,
                                                self._cap)
            if n < 0:
                self._cap = -n
                continue
            return self._parse(buf.raw[:n])

    def _parse(self, data: bytes) -> list[ReadResult]:
        out: list[ReadResult] = []
        off = 0
        while off < len(data):
            kind = data[off]
            off += 1
            if kind == 0:
                logid, lsn, tm, nrecs = struct.unpack_from("<QqqI", data,
                                                           off)
                off += 28
                lens = struct.unpack_from(f"<{nrecs}I", data, off)
                off += 4 * nrecs
                payloads = []
                for ln in lens:
                    payloads.append(data[off:off + ln])
                    off += ln
                out.append(DataBatch(logid=logid, lsn=lsn,
                                     payloads=tuple(payloads),
                                     append_time_ms=tm))
            else:
                logid, gt, lo, hi = struct.unpack_from("<QBqq", data, off)
                off += 25
                out.append(GapRecord(logid, GapType(gt), lo, hi))
        return out

    def __del__(self):
        try:
            self._store._lib.ns_reader_free(self._rh)
        except Exception:
            pass
