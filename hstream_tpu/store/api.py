"""Log-store interface: the frozen API both backends implement.

Capability parity with the reference's store layer (hstream-store):
  * logs addressed by integer logid, records by monotonically increasing LSN
  * batch append: one LSN covers a whole compressed batch
    (cbits/logdevice/hs_writer.cpp batch path)
  * batched reads that surface *gap records* (trims, holes) instead of
    silently skipping (cbits/logdevice/hs_reader.cpp)
  * trim / find_time / is_log_empty / tail_lsn introspection
    (include/hs_logdevice.h)
  * a small metadata KV that the stream namespace tree and versioned
    configs are built on (reference keeps these in LogDevice's logsconfig
    and VersionedConfigStore — hs_logconfigtypes.cpp,
    hs_versioned_config_store.cpp)

Backends: `MemLogStore` (tests, mock-store analogue) and `NativeLogStore`
(C++ embedded segment log via ctypes).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Iterator, Sequence

LSN_MIN = 1
LSN_MAX = (1 << 63) - 1
LSN_INVALID = 0


class Compression(enum.Enum):
    NONE = 0
    ZLIB = 1


class GapType(enum.Enum):
    TRIM = 0      # records below the trim point
    HOLE = 1      # lost records (storage failure)
    DATALOSS = 2


@dataclass(frozen=True)
class DataBatch:
    """One appended batch: a single LSN covering `payloads` records.

    `batch_index` of record i within the batch is simply i; the pair
    (lsn, i) is the stable record address (RecordId in the API plane).
    """

    logid: int
    lsn: int
    payloads: tuple[bytes, ...]
    append_time_ms: int = 0


@dataclass(frozen=True)
class GapRecord:
    logid: int
    gap_type: GapType
    lo_lsn: int
    hi_lsn: int


ReadResult = DataBatch | GapRecord


@dataclass
class LogAttrs:
    replication_factor: int = 1
    backlog_seconds: int = 0  # 0 = keep forever
    extras: dict[str, str] = field(default_factory=dict)


class LogReader:
    """Batched reader over one or more logs.

    Usage: start_reading(logid, from_lsn, until_lsn), then read(max) which
    blocks up to the configured timeout and returns up to `max` items, each
    a DataBatch or a GapRecord (gap semantics preserved from the reference:
    a trimmed range surfaces as GapRecord(TRIM) exactly once).
    """

    def start_reading(self, logid: int, from_lsn: int = LSN_MIN,
                      until_lsn: int = LSN_MAX) -> None:
        raise NotImplementedError

    def stop_reading(self, logid: int) -> None:
        raise NotImplementedError

    def is_reading(self, logid: int) -> bool:
        raise NotImplementedError

    def set_timeout(self, timeout_ms: int) -> None:
        """-1 = block forever; 0 = non-blocking; >0 = max wait."""
        raise NotImplementedError

    def read(self, max_records: int) -> list[ReadResult]:
        raise NotImplementedError


class LogStore:
    """A durable collection of append-only logs + a metadata KV."""

    # ---- log lifecycle ----
    def create_log(self, logid: int, attrs: LogAttrs | None = None) -> None:
        raise NotImplementedError

    def remove_log(self, logid: int) -> None:
        raise NotImplementedError

    def log_exists(self, logid: int) -> bool:
        raise NotImplementedError

    def list_logs(self) -> list[int]:
        raise NotImplementedError

    def log_attrs(self, logid: int) -> LogAttrs:
        raise NotImplementedError

    # ---- append ----
    def append(self, logid: int, payload: bytes,
               compression: Compression = Compression.NONE) -> int:
        """Append one record; returns its LSN (batch of size 1)."""
        return self.append_batch(logid, [payload], compression)

    def append_batch(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE, *,
                     append_time_ms: int | None = None) -> int:
        """Append a batch under a single LSN; returns that LSN.
        `append_time_ms` overrides the local wall-clock stamp —
        replication passes the leader's stamp so every replica agrees
        on find_time/backlog answers."""
        raise NotImplementedError

    # ---- introspection ----
    def tail_lsn(self, logid: int) -> int:
        """LSN of the last released record (LSN_INVALID if empty)."""
        raise NotImplementedError

    def trim(self, logid: int, up_to_lsn: int) -> None:
        """Remove records with lsn <= up_to_lsn."""
        raise NotImplementedError

    def trim_point(self, logid: int) -> int:
        raise NotImplementedError

    def find_time(self, logid: int, ts_ms: int) -> int:
        """Smallest LSN whose append time >= ts_ms (tail+1 if none)."""
        raise NotImplementedError

    def is_log_empty(self, logid: int) -> bool:
        raise NotImplementedError

    # ---- reading ----
    def new_reader(self, max_logs: int = 1) -> LogReader:
        raise NotImplementedError

    # ---- metadata KV (namespace tree, versioned configs) ----
    def meta_put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def meta_get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def meta_delete(self, key: str) -> None:
        raise NotImplementedError

    def meta_list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def meta_cas(self, key: str, expected: bytes | None, value: bytes) -> bool:
        """Compare-and-set for versioned configs (reference:
        hs_versioned_config_store.cpp). Returns True on success."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class CheckpointStore:
    """Maps (customer_id, logid) -> LSN, the durable consumer progress.

    Reference: three backends (file / RSM log / ZK) in
    cbits/logdevice/hs_checkpoint.cpp; we provide memory / file / log.
    """

    def get(self, customer_id: str, logid: int) -> int | None:
        raise NotImplementedError

    def update(self, customer_id: str, logid: int, lsn: int) -> None:
        self.update_multi(customer_id, {logid: lsn})

    def update_multi(self, customer_id: str, ckps: dict[int, int]) -> None:
        raise NotImplementedError

    def remove(self, customer_id: str) -> None:
        raise NotImplementedError

    def all_for(self, customer_id: str) -> dict[int, int]:
        raise NotImplementedError
