from hstream_tpu.store.api import (
    LSN_MIN,
    LSN_MAX,
    Compression,
    DataBatch,
    GapRecord,
    GapType,
    LogAttrs,
    LogStore,
    LogReader,
    CheckpointStore,
)
from hstream_tpu.store.memstore import MemLogStore
from hstream_tpu.store.streams import StreamApi, StreamType
from hstream_tpu.store.checkpoint import (
    MemCheckpointStore,
    FileCheckpointStore,
    LogCheckpointStore,
    CheckpointedReader,
)

__all__ = [
    "LSN_MIN",
    "LSN_MAX",
    "Compression",
    "DataBatch",
    "GapRecord",
    "GapType",
    "LogAttrs",
    "LogStore",
    "LogReader",
    "CheckpointStore",
    "MemLogStore",
    "StreamApi",
    "StreamType",
    "MemCheckpointStore",
    "FileCheckpointStore",
    "LogCheckpointStore",
    "CheckpointedReader",
]


def open_store(uri: str | None = None, *,
               sync_interval_ms: int | None = None,
               segment_bytes: int | None = None) -> LogStore:
    """Open a log store. `None` or "mem://" gives the in-memory backend;
    "file:///path" (or a bare path) opens the native embedded store.
    `sync_interval_ms` tunes the native group-commit fsync cadence,
    `segment_bytes` the segment roll size (ignored by the mem backend)."""
    if uri is None or uri == "mem://":
        return MemLogStore()
    path = uri[len("file://"):] if uri.startswith("file://") else uri
    from hstream_tpu.store.native import NativeLogStore

    kw = {}
    if sync_interval_ms is not None:
        kw["sync_interval_ms"] = sync_interval_ms
    if segment_bytes is not None:
        kw["segment_bytes"] = segment_bytes
    return NativeLogStore(path, **kw)
