"""Multi-host store replication over DCN.

The reference's storage tier is a replicated LogDevice cluster: the
server takes a ``replicate-factor`` flag and the whole cbits layer binds
a store that survives node loss (reference hstream/app/server.hs:83-90,
hstream-store/include/hs_logdevice.h). The embedded store here is
single-node, so this module adds the replication layer:

  * every mutating store op (append/trim/create/remove/meta) becomes an
    entry in a durable **op-log** — a reserved log inside the local
    store itself, so the replication stream is recoverable from disk;
  * the **leader** applies ops locally, then per-follower sender
    threads stream op-log entries IN ORDER over gRPC (DCN); a follower
    response always carries its applied sequence, so a lagging or
    rejoining follower is caught up from the leader's op-log — the
    same path as steady-state replication;
  * **followers** apply entries deterministically to their own local
    store; starting from the same initial state, replicas are
    byte-identical (same LSNs, same segments' logical content);
  * appends ack once ``replication_factor - 1`` followers (or every
    live follower, whichever is fewer) have applied the entry —
    availability over strict durability when nodes are down, with the
    degradation logged (LogDevice instead re-routes to other nodes of
    a larger cluster);
  * reads stay local on any replica (gap semantics are the local
    store's own).

Leadership is **epoch-fenced** (ISSUE 9): every Replicate/ack carries a
monotone epoch persisted in store meta. ``Promote`` (the admin
``promote`` verb, or an optional lease-timeout auto-promotion gated
behind ``--auto-promote-lease-ms``) raises a follower's epoch and makes
it the leader; from then on every replica rejects entries from any
lower epoch — a partitioned stale leader is *fenced*, its post-
partition appends land nowhere but its own local store, and its
clients get a typed ``NotLeaderError`` carrying the new leader's
address hint. The promotion rule is "most caught up wins": the caller
picks the replica with the highest ``(epoch, applied_seq)`` (node id
as the deterministic tiebreak); a dueling same-epoch promotion
resolves the same way on first contact. The demoted node rejoins as a
follower through the existing catch-up path — unless it applied
local-only entries while partitioned, in which case the divergence
guard halts it loudly for re-bootstrap (those appends were never
quorum-acked).

Idempotent appends ride the same machinery: a producer-stamped entry
(``producer_id``/``producer_seq`` on the replicated ``LogEntry``)
updates a bounded per-producer dedup window *during apply*, on every
replica, so the window is a deterministic function of the op-log and a
retry that straddles a promotion is answered by the new leader with
the original LSN (store/dedup.py).
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from concurrent import futures
from typing import Sequence

import grpc

from hstream_tpu.common import locktrace
from hstream_tpu.common.backoff import jittered_backoff
from hstream_tpu.common.errors import (
    NotLeaderError,
    ReplicaDivergence,
    StoreIOError,
)
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import StoreReplicaStub, add_store_replica_to_server
from hstream_tpu.store import dedup
from hstream_tpu.store.api import Compression, LogAttrs, LogStore

log = get_logger("replica")

# reserved logid holding the replication op-log inside each local store
OPLOG_ID = (1 << 61) + 7

# default follower-ack deadline; per-store override via the
# --replica-ack-timeout-ms flag (ReplicatedStore ack_timeout_s)
_ACK_TIMEOUT_S = 5.0
# idle-leader heartbeat cadence: zero-entry Replicates keep the
# follower's leader lease fresh AND discover fencing promptly (an idle
# stale leader must not linger unfenced until its next real append)
_HEARTBEAT_S = 1.0

# store-meta keys for the replicated leadership state
META_EPOCH = "replica/epoch"
META_LEADER_ID = "replica/leader_id"
META_LEADER_HINT = "replica/leader_hint"
META_IS_LEADER = "replica/is_leader"


def load_epoch(store: LogStore) -> int:
    raw = store.meta_get(META_EPOCH)
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def _store_epoch(store: LogStore, epoch: int) -> None:
    # single-writer plane: only THIS process writes its own follower
    # meta, under _lock, after the caller's epoch ladder decided the
    # value — a CAS loop here could only race itself
    # analyze: ok cas-blind-meta-write
    store.meta_put(META_EPOCH, str(int(epoch)).encode())
# follower reconnect backoff: jittered exponential from _RETRY_S up to
# _RETRY_CAP_S — a flapping follower must not spin the leader's sender
# thread hot (ISSUE 8); reset only once a Replicate is ACKED (a peer
# that merely accepts connections keeps backing off)
_RETRY_S = 0.2
_RETRY_CAP_S = 5.0
_RETRY_JITTER = 0.25


def _encode_entry(e: pb.LogEntry) -> bytes:
    return e.SerializeToString()


def _decode_entry(b: bytes) -> pb.LogEntry:
    return pb.LogEntry.FromString(b)


def _apply(store: LogStore, e: pb.LogEntry) -> None:
    """Apply one op to a local store. Deterministic AND idempotent:
    every replica applies the same entries in the same order, and
    re-applying an entry after a crash in the apply/log window is a
    no-op (appends are guarded by expect_lsn; the other ops are
    naturally idempotent)."""
    if FAULTS.active:  # chaos probe; one branch when disarmed
        FAULTS.point("store.oplog.apply")
    if e.op == pb.OP_APPEND:
        if e.expect_lsn:
            tail = store.tail_lsn(e.logid)
            if tail >= e.expect_lsn:
                # already applied (crash between apply and log): still
                # (re)record the producer stamp — record() is
                # idempotent and the dedup window must cover every
                # applied entry
                if e.producer_id:
                    dedup.record(store, e.producer_id, e.producer_seq,
                                 e.expect_lsn, len(e.payloads))
                return
            if tail != e.expect_lsn - 1:
                # checked BEFORE mutating: appending first and then
                # discovering the wrong LSN would land garbage that
                # every retry of this entry compounds
                raise ReplicaDivergence(
                    f"replica diverged: log {e.logid} tail is {tail}, "
                    f"entry expects lsn {e.expect_lsn}")
        lsn = store.append_batch(e.logid, list(e.payloads),
                                 Compression(e.compression),
                                 append_time_ms=e.append_time_ms or None)
        if e.expect_lsn and lsn != e.expect_lsn:
            raise ReplicaDivergence(
                f"replica diverged: append to log {e.logid} landed at "
                f"lsn {lsn}, expected {e.expect_lsn}")
        if e.producer_id:
            # the dedup window is maintained AS PART OF applying the
            # entry, on every replica: deterministic from the op-log,
            # so a promoted follower already knows every stamped
            # append its prefix contains
            dedup.record(store, e.producer_id, e.producer_seq,
                         lsn, len(e.payloads))
    elif e.op == pb.OP_TRIM:
        store.trim(e.logid, e.trim_lsn)
    elif e.op == pb.OP_CREATE_LOG:
        if not store.log_exists(e.logid):
            store.create_log(e.logid, LogAttrs(
                replication_factor=e.replication_factor or 1,
                backlog_seconds=e.backlog_seconds))
    elif e.op == pb.OP_REMOVE_LOG:
        if store.log_exists(e.logid):
            store.remove_log(e.logid)
    elif e.op == pb.OP_META_PUT:
        store.meta_put(e.meta_key, e.meta_value)
    elif e.op == pb.OP_META_DELETE:
        store.meta_delete(e.meta_key)
    else:  # unknown op from a newer leader: fail loudly, don't diverge
        raise ValueError(f"unknown replication op {e.op}")


def _stable_node_id(store: LogStore) -> str:
    nid = store.meta_get("replica/node_id")
    if nid is None:
        nid = f"leader-{uuid.uuid4().hex[:10]}".encode()
        # first-boot identity stamp on a store no peer can reach yet
        # (the server opens the store before serving)
        # analyze: ok cas-blind-meta-write
        store.meta_put("replica/node_id", nid)
    return nid.decode()


def _reconcile(store: LogStore) -> None:
    """Crash recovery for the apply/log window: ops are serialized, so
    at most the LAST op-log entry can be logged-but-unapplied (leader
    logs first) — re-apply it; idempotence makes this safe when it DID
    apply."""
    tail = store.tail_lsn(OPLOG_ID)
    if not tail:
        return
    reader = store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(OPLOG_ID, tail, tail)
    for item in reader.read(4):
        if hasattr(item, "payloads"):
            for p in item.payloads:
                e = _decode_entry(p)
                e.seq = item.lsn
                if e.op == pb.OP_APPEND and not e.expect_lsn:
                    # no idempotence marker: re-applying could
                    # duplicate the batch — skipping risks at most one
                    # missing apply, which the seq handshake surfaces
                    log.warning("skipping reconcile of unverifiable "
                                "append at seq %d", e.seq)
                    continue
                _apply(store, e)
    reader.stop_reading(OPLOG_ID)


class _Follower:
    """Leader-side sender for one follower: an in-order stream of
    op-log entries driven by the follower's acked sequence."""

    def __init__(self, addr: str, owner: "ReplicatedStore"):
        self.addr = addr
        self.owner = owner
        self.acked_seq = 0
        self.alive = False
        # reconnect backoff state: attempt count since the last ACKED
        # Replicate (not merely the last good connect) + the wait the
        # next failure will schedule (tests assert growth and the
        # cap). Jitter is seeded per follower so a chaos run replays
        # the same wait sequence.
        self.connect_attempts = 0
        self.last_backoff_s = 0.0
        self._jitter = random.Random(addr)
        self._thread = threading.Thread(
            target=self._run, name=f"repl-{addr}", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _backoff(self) -> float:
        """Jittered exponential reconnect wait: base * 2^attempt capped
        at _RETRY_CAP_S, +/- _RETRY_JITTER so a fleet of senders
        doesn't reconnect in lockstep."""
        wait = jittered_backoff(
            self.connect_attempts, base=_RETRY_S, cap=_RETRY_CAP_S,
            jitter=_RETRY_JITTER, rng=self._jitter)
        self.connect_attempts += 1
        self.last_backoff_s = wait
        return wait

    def _run(self) -> None:
        owner = self.owner
        while not owner._stop.is_set():
            if owner.fenced_by is not None:
                # leadership lost: stop streaming (the follower would
                # fence every entry anyway); park on the backoff so
                # close() still tears the thread down promptly
                if owner._stop.wait(self._backoff()):
                    return
                continue
            try:
                if FAULTS.active:  # chaos: provoke a connect failure
                    FAULTS.point("store.follower.connect")
                with grpc.insecure_channel(self.addr) as ch:
                    stub = StoreReplicaStub(ch)
                    info = stub.ReplicaInfo(pb.ReplicaInfoRequest(),
                                            timeout=owner.ack_timeout_s)
                    if info.epoch > owner.epoch:
                        # the cluster moved on without us: fence BEFORE
                        # streaming a single stale entry
                        owner._fence(info.epoch, info.leader_hint)
                        continue
                    self.acked_seq = info.applied_seq
                    if not self.alive:
                        log.info("follower %s up at seq %d", self.addr,
                                 self.acked_seq)
                    self.alive = True
                    with owner._cond:
                        owner._cond.notify_all()
                    self._stream(stub)
            except Exception as e:  # noqa: BLE001 — any failure (RPC,
                # local read, decode) must keep the retry loop alive and
                # the follower marked down, never kill the sender thread
                # with alive stuck True
                if self.alive:
                    log.warning("follower %s down: %s", self.addr,
                                e.code() if isinstance(e, grpc.RpcError)
                                else e)
                    journal = getattr(owner, "journal", None)
                    if journal is not None:
                        try:
                            journal.append(
                                "follower_down",
                                f"store follower {self.addr} stopped "
                                f"acking at seq {self.acked_seq}",
                                follower=self.addr,
                                acked_seq=self.acked_seq)
                        except Exception:  # noqa: BLE001
                            pass
                self.alive = False
                with owner._cond:
                    owner._cond.notify_all()
                if owner._stop.wait(self._backoff()):
                    return
        self.alive = False

    def _heartbeat(self, stub) -> None:
        """Zero-entry Replicate: refreshes the follower's leader lease
        and discovers fencing even when the leader is idle."""
        if FAULTS.active:  # chaos: lose the heartbeat (lease expiry)
            FAULTS.point("replica.heartbeat.drop")
        owner = self.owner
        resp = stub.Replicate(
            pb.ReplicateRequest(entries=[], leader_id=owner.node_id,
                                epoch=owner.epoch,
                                leader_hint=owner.client_addr),
            timeout=owner.ack_timeout_s)
        if resp.fenced:
            owner._fence(resp.epoch, resp.leader_hint)
            raise StoreIOError("fenced by follower heartbeat")

    def _stream(self, stub) -> None:
        owner = self.owner
        reader = owner.local.new_reader()
        reader.set_timeout(0)
        pos = 0  # next seq the persistent reader is positioned at
        last_send = time.monotonic()
        try:
            while not owner._stop.is_set():
                with owner._cond:
                    while (self.acked_seq >= owner._seq
                           and not owner._stop.is_set()
                           and time.monotonic() - last_send
                           < _HEARTBEAT_S):
                        owner._cond.wait(0.5)
                    if owner._stop.is_set():
                        return
                if self.acked_seq >= owner.oplog_seq:
                    self._heartbeat(stub)
                    last_send = time.monotonic()
                    continue
                want = self.acked_seq + 1
                if pos != want:
                    if pos:
                        reader.stop_reading(OPLOG_ID)
                    reader.start_reading(OPLOG_ID, want)
                    pos = want
                entries = []
                gap_hi = 0
                for item in reader.read(64):
                    if hasattr(item, "payloads"):
                        for p in item.payloads:
                            e = _decode_entry(p)
                            e.seq = item.lsn  # seq IS the op-log LSN
                            entries.append(e)
                    elif hasattr(item, "hi_lsn"):
                        gap_hi = max(gap_hi, item.hi_lsn)
                if gap_hi and (not entries
                               or entries[0].seq != want):
                    # the follower is below the op-log trim point:
                    # catch-up cannot reconstruct those ops. Stop
                    # replicating to it — operator re-bootstraps the
                    # replica from a copy of a live store.
                    log.error(
                        "follower %s needs entries up to seq %d but "
                        "the op-log is trimmed to %d; re-bootstrap "
                        "this replica", self.addr, gap_hi,
                        self.owner.local.trim_point(OPLOG_ID))
                    raise StoreIOError("follower below op-log trim")
                if not entries:
                    continue
                pos = entries[-1].seq + 1
                if FAULTS.active:  # chaos: drop the ack RPC
                    FAULTS.point("store.follower.ack")
                resp = stub.Replicate(
                    pb.ReplicateRequest(entries=entries,
                                        leader_id=owner.node_id,
                                        epoch=owner.epoch,
                                        leader_hint=owner.client_addr),
                    timeout=owner.ack_timeout_s)
                last_send = time.monotonic()
                if resp.fenced:
                    # a higher epoch holds this follower: we are the
                    # stale leader — stop immediately, record who to
                    # redirect clients to, never mark these entries
                    # acked
                    owner._fence(resp.epoch, resp.leader_hint)
                    raise StoreIOError(
                        f"fenced by {self.addr} at epoch {resp.epoch}")
                # the follower's word is authoritative: a lagging
                # applied seq rewinds the stream (e.g. it restarted
                # from older disk)
                self.acked_seq = resp.applied_seq
                # real streaming progress: only now does the reconnect
                # schedule start over — a half-broken peer that answers
                # ReplicaInfo but fails every Replicate must keep
                # backing off, not retry at the floor forever
                self.connect_attempts = 0
                self.last_backoff_s = 0.0
                with owner._cond:
                    owner._cond.notify_all()
        finally:
            if pos:
                reader.stop_reading(OPLOG_ID)


class ReplicatedStore(LogStore):
    """Leader-side LogStore: applies locally + replicates to followers.

    Mutations go through the durable op-log; reads and introspection are
    the local store's. ``append_batch`` blocks until the entry is
    applied on min(replication_factor-1, live followers) replicas."""

    def __init__(self, local: LogStore, followers: Sequence[str], *,
                 replication_factor: int = 2,
                 node_id: str | None = None,
                 ack_timeout_s: float | None = None,
                 client_addr: str = ""):
        self.local = local
        # stable across restarts (persisted in the local store) AND
        # unique per store: a follower rejects entries from a second
        # leader by id, which only works if ids differ between stores
        # but SURVIVE a leader restart
        self.node_id = node_id or _stable_node_id(local)
        self.replication_factor = max(int(replication_factor), 1)
        # follower-ack deadline (--replica-ack-timeout-ms); module
        # default kept monkeypatchable for tests
        self.ack_timeout_s = (float(ack_timeout_s) if ack_timeout_s
                              else _ACK_TIMEOUT_S)
        # leadership epoch: persisted in store meta, so a store
        # promoted while serving as a follower opens here already
        # holding the promoted epoch
        self.epoch = load_epoch(local)
        # (epoch, leader_hint) once a higher epoch fences this leader;
        # every further mutation raises NotLeaderError with the hint
        self.fenced_by: tuple[int, str] | None = None
        self.fenced_appends = 0
        # where clients reach THIS leader (serve() sets host:port);
        # rides every Replicate so followers can hand it out as the
        # leader hint
        self.client_addr = client_addr
        # optional StatsHolder (bound by ServerContext, like journal)
        self.stats = None
        self._stop = threading.Event()
        # condition over a named traced re-entrant lock (ISSUE 14):
        # the op-log sequence, sender wakeups, and ack waits all
        # rendezvous here — the leader half of the witness graph
        self._cond = threading.Condition(locktrace.rlock("replica.oplog"))
        self._broken: BaseException | None = None
        # durability introspection: status of the most recent acked
        # append ("replicated" | "degraded:followers_down" |
        # "degraded:timeout") + a monotone degraded counter, so callers
        # can assert what an ack actually meant instead of trusting the
        # normal return (ISSUE 1: a timed-out ack used to look fully
        # replicated)
        self.last_ack_status: str = "replicated"
        self.degraded_appends: int = 0
        # optional event journal (stats.events.EventJournal): the server
        # context attaches one so degraded acks / follower loss become
        # queryable operator events, not just log lines
        self.journal = None
        self._async_pool = futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="repl-ack")
        self._ops_since_trim = 0
        if not local.log_exists(OPLOG_ID):
            local.create_log(OPLOG_ID)
        _reconcile(local)  # crash in the log/apply window: replay last
        self._seq = local.tail_lsn(OPLOG_ID)  # durable across restarts
        self._followers = [_Follower(a, self) for a in followers]
        for f in self._followers:
            f.start()

    # ---- replication core --------------------------------------------------

    def _check_broken(self) -> None:
        if self._broken is not None:
            raise StoreIOError(
                f"replicated store is in a broken state (an op was "
                f"logged but failed to apply locally): {self._broken}")

    def _check_leader(self) -> None:
        """Refuse mutations once fenced: raising BEFORE the local
        log+apply keeps the stale leader's store from diverging
        further, and the hint redirects the caller to the new leader.
        """
        fenced = self.fenced_by
        if fenced is None:
            return
        epoch, hint = fenced
        with self._cond:
            self.fenced_appends += 1
        stats = self.stats
        if stats is not None:
            try:
                stats.stream_stat_add("fenced_appends", "_store")
            except Exception:  # noqa: BLE001 — metrics must not alter
                pass           # the refusal
        raise NotLeaderError(
            f"store leadership lost: fenced by epoch {epoch} "
            f"(this node held epoch {self.epoch})",
            leader_hint=hint or None)

    def _fence(self, epoch: int, leader_hint: str) -> None:
        """A replica answered with a higher epoch: this node is no
        longer the leader. Idempotent; keeps the HIGHEST fencing epoch
        seen (dueling promotions converge on the winner's hint)."""
        with self._cond:
            if self.fenced_by is not None and self.fenced_by[0] >= epoch:
                return
            self.fenced_by = (int(epoch), leader_hint or "")
            self._cond.notify_all()
        log.error("store leader %s FENCED by epoch %d (own epoch %d); "
                  "clients redirected to %r", self.node_id, epoch,
                  self.epoch, leader_hint)
        journal = self.journal
        if journal is not None:
            try:
                journal.append(
                    "replica_fenced",
                    f"leader {self.node_id} (epoch {self.epoch}) fenced "
                    f"by epoch {epoch}; leader hint {leader_hint!r}",
                    epoch=int(epoch), own_epoch=self.epoch,
                    leader_hint=leader_hint or None)
            except Exception:  # noqa: BLE001 — journaling is best-effort
                pass

    def _log_and_apply(self, entry: pb.LogEntry) -> int:
        """The one critical section: durably log the op, apply it
        locally, wake the sender threads. Returns the op's seq.
        Caller holds nothing; broken-state on apply failure."""
        self._check_leader()
        self._check_broken()
        with self._cond:
            return self._log_apply_locked(entry)

    def _log_apply_locked(self, entry: pb.LogEntry) -> int:
        """Caller holds _cond (and has run the leader/broken checks)."""
        if entry.op == pb.OP_APPEND:
            # stamp idempotence + time BEFORE logging, under the
            # lock: replicas must land the append at this LSN with
            # this timestamp
            entry.expect_lsn = self.local.tail_lsn(entry.logid) + 1
            if not entry.append_time_ms:
                entry.append_time_ms = int(time.time() * 1000)
        seq = self.local.append(OPLOG_ID, _encode_entry(entry))
        self._seq = seq
        try:
            _apply(self.local, entry)
        except Exception as e:  # noqa: BLE001
            # the op is durably logged (followers WILL apply it) but
            # this replica didn't: refusing further mutations beats
            # silent divergence
            self._broken = e
            log.error("leader apply failed at seq %d: %s", seq, e)
            raise
        self._cond.notify_all()
        return seq

    def _replicate(self, entry: pb.LogEntry, *, wait: bool = True) -> None:
        seq = self._log_and_apply(entry)
        if wait:
            self._wait_acks(seq)

    def follower_status(self) -> list[dict]:
        """Per-follower liveness/lag plus the store-level ack status on
        every entry, so one call answers both "who is behind" and "was
        the last ack degraded"."""
        # found by hstream-analyze (lock-guard): _seq is written under
        # _cond by _log_and_apply/meta_cas on appender threads; reading
        # it unlocked here could report a lag computed from a stale seq
        seq = self.oplog_seq
        return [{"addr": f.addr, "alive": f.alive,
                 "acked_seq": f.acked_seq,
                 "behind": max(0, seq - f.acked_seq),
                 "last_ack_status": self.last_ack_status,
                 "degraded_appends": self.degraded_appends}
                for f in self._followers]

    def leader_status(self) -> dict:
        """Store-level leadership state for the admin `replicas` verb:
        epoch, fencing, ack-timeout tuning, dedup-window footprint."""
        fenced = self.fenced_by
        return {"node_id": self.node_id, "epoch": self.epoch,
                "fenced": fenced is not None,
                "fenced_by_epoch": fenced[0] if fenced else None,
                "leader_hint": fenced[1] if fenced else None,
                "fenced_appends": self.fenced_appends,
                "ack_timeout_ms": int(self.ack_timeout_s * 1000),
                "dedup_window": dedup.window_size(self.local)}

    def promote_follower(self, target: str, *,
                         leader_addr: str | None = None) -> dict:
        """Planned handoff: promote `target` to epoch+1, fence THIS
        leader immediately (clients get the hint instead of a stale
        ack), and SEAL the remaining followers at the new epoch so
        none of them acks another of this leader's entries during the
        handoff window. The admin `promote` verb rides this; leader-
        death promotion goes straight to the replicas (admin CLI
        ``promote --replicas``)."""
        new_epoch = self.epoch + 1
        hint = leader_addr or target
        with grpc.insecure_channel(target) as ch:
            resp = StoreReplicaStub(ch).Promote(
                pb.PromoteRequest(epoch=new_epoch, leader_addr=hint,
                                  promoted_by=self.node_id),
                timeout=self.ack_timeout_s)
        sealed: list[str] = []
        if resp.ok:
            self._fence(resp.epoch, hint)
            sealed = seal_replicas(
                [f.addr for f in self._followers if f.addr != target],
                epoch=int(resp.epoch), leader_id=resp.node_id,
                leader_hint=hint, timeout=self.ack_timeout_s)
            journal = self.journal
            if journal is not None:
                try:
                    journal.append(
                        "replica_promoted",
                        f"follower {target} promoted to epoch "
                        f"{resp.epoch} by {self.node_id}; this leader "
                        f"is fenced, {len(sealed)} peer(s) sealed",
                        target=target, epoch=int(resp.epoch),
                        applied_seq=int(resp.applied_seq))
                except Exception:  # noqa: BLE001
                    pass
        return {"ok": bool(resp.ok), "epoch": int(resp.epoch),
                "applied_seq": int(resp.applied_seq),
                "node_id": resp.node_id, "target": target,
                "sealed": sealed}

    @property
    def oplog_seq(self) -> int:
        with self._cond:
            return self._seq

    # ---- LogStore: mutations (replicated) ----------------------------------

    def create_log(self, logid: int, attrs: LogAttrs | None = None) -> None:
        a = attrs or LogAttrs()
        self._replicate(pb.LogEntry(
            op=pb.OP_CREATE_LOG, logid=logid,
            replication_factor=a.replication_factor,
            backlog_seconds=a.backlog_seconds))

    def remove_log(self, logid: int) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_REMOVE_LOG, logid=logid))

    def append_batch(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE, *,
                     append_time_ms: int | None = None) -> int:
        entry = pb.LogEntry(op=pb.OP_APPEND, logid=logid,
                            payloads=[bytes(p) for p in payloads],
                            compression=compression.value,
                            append_time_ms=append_time_ms or 0)
        seq = self._log_and_apply(entry)
        self._wait_acks(seq)
        self._maybe_trim_oplog()
        return entry.expect_lsn

    def append_batch_dedup(self, logid: int, payloads: Sequence[bytes],
                           compression: Compression = Compression.NONE,
                           *, producer_id: str, producer_seq: int
                           ) -> tuple[int, int, bool]:
        """Producer-stamped append: returns (lsn, n_records,
        was_duplicate). The dedup lookup and the log+apply share ONE
        critical section, and the stamp rides the replicated entry, so
        a racing retry can never double-log and every replica derives
        the same window (store/dedup.py)."""
        self._check_leader()
        self._check_broken()
        entry = pb.LogEntry(op=pb.OP_APPEND, logid=logid,
                            payloads=[bytes(p) for p in payloads],
                            compression=compression.value,
                            producer_id=producer_id,
                            producer_seq=int(producer_seq))
        with self._cond:
            hit = dedup.lookup(self.local, producer_id, producer_seq)
            if hit is not None:
                return hit[0], hit[1], True
            seq = self._log_apply_locked(entry)
        self._wait_acks(seq)
        self._maybe_trim_oplog()
        return entry.expect_lsn, len(payloads), False

    def _maybe_trim_oplog(self) -> None:
        """Reclaim op-log space every so often: entries every follower
        has applied are never needed again (a rejoining follower below
        the trim point is unrecoverable by catch-up and must be
        re-bootstrapped from a copy — the trade LogDevice also makes
        with trimmed logs). A permanently-dead follower pins the op-log
        until the operator removes it from --replicate."""
        self._ops_since_trim += 1
        if self._ops_since_trim < 512 or not self._followers:
            return
        self._ops_since_trim = 0
        if not all(f.alive for f in self._followers):
            return
        low = min(f.acked_seq for f in self._followers)
        if low > self.local.trim_point(OPLOG_ID):
            self.local.trim(OPLOG_ID, low)

    def _wait_acks(self, seq: int) -> str:
        """Wait for the replication quorum; returns the DURABILITY the
        ack actually achieved — "replicated" when `need` followers
        applied the op, else a degraded status ("degraded:
        followers_down" / "degraded:timeout"). A degraded return is
        recorded (last_ack_status, degraded_appends) so callers and
        tests can assert it instead of mistaking availability for full
        replication."""
        status = self._wait_acks_inner(seq)
        # under the lock: async-append pool threads and callers wait
        # acks concurrently, and a lost increment would undercount
        # degraded events exactly when the cluster is degraded
        with self._cond:
            self.last_ack_status = status
            if status != "replicated":
                self.degraded_appends += 1
        if status != "replicated" and self.journal is not None:
            # an expired ack deadline gets its own event kind (ISSUE 9:
            # the timeout used to only degrade silently); follower-down
            # degradation keeps the generic kind
            kind = ("replica_ack_timeout" if status == "degraded:timeout"
                    else "degraded_append")
            try:
                self.journal.append(
                    kind, f"append acked {status} at seq {seq}",
                    status=status, seq=seq,
                    ack_timeout_ms=int(self.ack_timeout_s * 1000))
            except Exception:  # noqa: BLE001 — journaling must not
                pass           # affect append durability semantics
        return status

    def _wait_acks_inner(self, seq: int) -> str:
        if not self._followers:
            return "replicated"
        need = min(self.replication_factor - 1, len(self._followers))
        if need <= 0:
            return "replicated"
        deadline = time.monotonic() + self.ack_timeout_s
        with self._cond:
            while True:
                acked = sum(1 for f in self._followers
                            if f.acked_seq >= seq)
                if acked >= need:
                    return "replicated"
                live = sum(1 for f in self._followers if f.alive)
                if acked >= live:
                    if live < need:
                        log.warning(
                            "replication degraded: %d/%d followers "
                            "live; seq %d acked by %d", live,
                            len(self._followers), seq, acked)
                        return "degraded:followers_down"
                if time.monotonic() > deadline:
                    log.warning(
                        "replication ack timeout at seq %d (%d/%d)",
                        seq, acked, need)
                    return "degraded:timeout"
                self._cond.wait(0.2)

    def trim(self, logid: int, up_to_lsn: int) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_TRIM, logid=logid,
                                    trim_lsn=up_to_lsn))

    def meta_put(self, key: str, value: bytes) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_META_PUT, meta_key=key,
                                    meta_value=value), wait=False)

    def meta_delete(self, key: str) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_META_DELETE, meta_key=key),
                        wait=False)

    def meta_cas(self, key: str, expected: bytes | None,
                 value: bytes) -> bool:
        # CAS decided on the leader (the single sequencer), replicated
        # as its winning put. CAS + op-log append stay in ONE critical
        # section: two racing winners must log their puts in decision
        # order, or the earlier value would overwrite the later one on
        # every replica.
        self._check_leader()
        self._check_broken()
        with self._cond:
            ok = self.local.meta_cas(key, expected, value)
            if ok:
                seq = self.local.append(OPLOG_ID, _encode_entry(
                    pb.LogEntry(op=pb.OP_META_PUT, meta_key=key,
                                meta_value=value)))
                self._seq = seq
                self._cond.notify_all()
        return ok

    # ---- LogStore: reads/introspection (local) -----------------------------

    def log_exists(self, logid: int) -> bool:
        return self.local.log_exists(logid)

    def list_logs(self) -> list[int]:
        return [l for l in self.local.list_logs() if l != OPLOG_ID]

    def log_attrs(self, logid: int) -> LogAttrs:
        return self.local.log_attrs(logid)

    def tail_lsn(self, logid: int) -> int:
        return self.local.tail_lsn(logid)

    def trim_point(self, logid: int) -> int:
        return self.local.trim_point(logid)

    def find_time(self, logid: int, ts_ms: int) -> int:
        return self.local.find_time(logid, ts_ms)

    def is_log_empty(self, logid: int) -> bool:
        return self.local.is_log_empty(logid)

    def new_reader(self, max_logs: int = 1):
        return self.local.new_reader(max_logs)

    def meta_get(self, key: str) -> bytes | None:
        return self.local.meta_get(key)

    def meta_list(self, prefix: str) -> list[str]:
        return self.local.meta_list(prefix)

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for f in self._followers:
            f._thread.join(timeout=2)
        self._async_pool.shutdown(wait=True)
        self.local.close()

    # async append parity with the native store (sink fast path): the
    # local log+apply happens inline (cheap), but the follower-ack wait
    # moves to a pool thread so the caller keeps its bounded-in-flight
    # pipelining instead of serializing on a DCN round trip per batch
    def append_async(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE):
        entry = pb.LogEntry(op=pb.OP_APPEND, logid=logid,
                            payloads=[bytes(p) for p in payloads],
                            compression=compression.value)
        seq = self._log_and_apply(entry)
        lsn = entry.expect_lsn

        def waiter() -> int:
            self._wait_acks(seq)
            self._maybe_trim_oplog()
            return lsn

        return self._async_pool.submit(waiter)


def replica_info(addr: str, timeout: float = 2.0):
    """ReplicaInfo from one replica, or None when unreachable."""
    try:
        with grpc.insecure_channel(addr) as ch:
            return StoreReplicaStub(ch).ReplicaInfo(
                pb.ReplicaInfoRequest(), timeout=timeout)
    except grpc.RpcError:
        return None


def best_replica(addrs: Sequence[str], timeout: float = 2.0
                 ) -> tuple[str, tuple[int, int, str]] | None:
    """The most-caught-up reachable replica: highest
    (epoch, applied_seq, node_id) — the promotion rule. Returns
    (addr, key) or None when nothing answers."""
    best: tuple[str, tuple[int, int, str]] | None = None
    for addr in addrs:
        info = replica_info(addr, timeout)
        if info is None:
            continue
        key = (int(info.epoch), int(info.applied_seq), info.node_id)
        if best is None or key > best[1]:
            best = (addr, key)
    return best


def seal_replicas(addrs: Sequence[str], *, epoch: int, leader_id: str,
                  leader_hint: str, timeout: float = 5.0) -> list[str]:
    """Zero-entry Replicate at `epoch` to each replica: the receivers
    accept the new (epoch, leader) binding and from then on reject the
    old leader's entries by epoch. This closes the promotion window in
    which a not-yet-contacted follower would still ACK a stale
    leader's append (an ack the new leader could never honor).
    Best-effort: returns the addrs that acknowledged; an unreachable
    replica is sealed by the new leader's first contact instead."""
    sealed: list[str] = []
    for addr in addrs:
        try:
            with grpc.insecure_channel(addr) as ch:
                StoreReplicaStub(ch).Replicate(
                    pb.ReplicateRequest(entries=[], leader_id=leader_id,
                                        epoch=epoch,
                                        leader_hint=leader_hint),
                    timeout=timeout)
            sealed.append(addr)
        except grpc.RpcError:
            continue
    return sealed


def promote_best(addrs: Sequence[str], *, leader_addr: str | None = None,
                 promoted_by: str = "operator",
                 timeout: float = 5.0) -> dict:
    """Leader-death promotion (admin CLI ``promote --replicas``): pick
    the most-caught-up reachable replica, promote it to
    max(observed epochs) + 1, and seal the remaining reachable
    replicas at that epoch (none of them may ack a resurfacing stale
    leader afterwards). Raises StoreIOError when no replica
    answers."""
    infos = {addr: replica_info(addr, timeout) for addr in addrs}
    live = {a: i for a, i in infos.items() if i is not None}
    if not live:
        raise StoreIOError(f"no replica reachable among {list(addrs)}")
    best_addr = max(live, key=lambda a: (int(live[a].epoch),
                                         int(live[a].applied_seq),
                                         live[a].node_id))
    new_epoch = max(int(i.epoch) for i in live.values()) + 1
    hint = leader_addr or best_addr
    with grpc.insecure_channel(best_addr) as ch:
        resp = StoreReplicaStub(ch).Promote(
            pb.PromoteRequest(epoch=new_epoch, leader_addr=hint,
                              promoted_by=promoted_by),
            timeout=timeout)
    sealed = []
    if resp.ok:
        sealed = seal_replicas(
            [a for a in live if a != best_addr],
            epoch=int(resp.epoch), leader_id=resp.node_id,
            leader_hint=hint, timeout=timeout)
    return {"ok": bool(resp.ok), "target": best_addr,
            "epoch": int(resp.epoch),
            "applied_seq": int(resp.applied_seq),
            "node_id": resp.node_id, "sealed": sealed,
            "unreachable": sorted(set(addrs) - set(live))}


class FollowerService:
    """Follower-side gRPC service: applies in-order entries to the
    local store; always answers with its applied sequence and epoch.

    Epoch fencing (ISSUE 9): the accepted leader binding is
    ``(epoch, leader_id)``, both durable in store meta. A request from
    a HIGHER epoch always wins (the old leader was deposed — journal
    ``leader_change``, demote self if promoted); a request from a
    LOWER epoch is answered ``fenced=True`` with the current epoch and
    leader hint, and nothing is applied — a partitioned stale leader
    cannot split-brain its followers. Same-epoch conflicts keep the
    PR 1 semantics (operator error -> FAILED_PRECONDITION), except
    between two same-epoch PROMOTED leaders (a dueling promotion),
    which resolves deterministically: the lexicographically higher
    node id wins on first contact."""

    def __init__(self, local: LogStore, *, node_id: str = "follower",
                 journal=None, listen_addr: str = "",
                 advertise_addr: str = "",
                 lease_timeout_s: float | None = None,
                 peers: Sequence[str] = ()):
        self.local = local
        self.node_id = node_id
        self.journal = journal  # optional stats.events.EventJournal
        self.listen_addr = listen_addr
        # client-facing address served as the leader hint if THIS
        # replica auto-promotes (where the operator will boot the SQL
        # server over the promoted store); without it the hint falls
        # back to the replica listen addr, which serves StoreReplica,
        # not HStreamApi — a followed client would then fail
        # UNIMPLEMENTED instead of reaching a SQL surface
        self.advertise_addr = advertise_addr
        # named traced lock (ISSUE 14): epoch/fencing/bind state — the
        # follower half of the replica witness graph
        self._lock = locktrace.lock("replica.follower")
        self._broken: BaseException | None = None
        # the accepted leader binding is DURABLE (store meta): a
        # restarted follower must keep rejecting a stale leader instead
        # of re-accepting whichever connects first after the restart
        raw = local.meta_get(META_LEADER_ID)
        self._leader_id: str | None = (raw.decode() if raw is not None
                                       else None)
        self._epoch = load_epoch(local)
        hint = local.meta_get(META_LEADER_HINT)
        self._leader_hint: str | None = (hint.decode() if hint else None)
        self._is_leader = local.meta_get(META_IS_LEADER) == b"1"
        self._last_leader_contact = time.monotonic()
        self._ops_since_trim = 0
        if not local.log_exists(OPLOG_ID):
            local.create_log(OPLOG_ID)
        _reconcile(local)
        # optional lease-timeout auto-promotion (gated behind the
        # --auto-promote-lease-ms flag): if the accepted leader goes
        # silent past the lease, promote self — but only after
        # checking that no reachable peer is more caught up (highest
        # (epoch, applied_seq, node_id) wins, same rule as admin
        # promote)
        if lease_timeout_s:
            # floor the lease well above the idle-heartbeat cadence:
            # heartbeats go out on a ~1.5s worst-case period (the
            # _HEARTBEAT_S threshold checked on a 0.5s cond poll), so
            # a smaller lease would fence a perfectly healthy idle
            # leader between two heartbeats
            floor = _HEARTBEAT_S * 3
            if lease_timeout_s < floor:
                log.warning(
                    "auto-promote lease %.2fs is below the heartbeat "
                    "floor; clamping to %.2fs", lease_timeout_s, floor)
                lease_timeout_s = floor
        self.lease_timeout_s = lease_timeout_s
        self.peers = [p for p in peers if p]
        self._stop_ev = threading.Event()
        self._lease_thread: threading.Thread | None = None
        if lease_timeout_s:
            self._lease_thread = threading.Thread(
                target=self._lease_loop, name=f"lease-{node_id}",
                daemon=True)
            self._lease_thread.start()

    def close(self) -> None:
        """Stop the lease monitor (serve_follower shutdown path)."""
        self._stop_ev.set()
        t = self._lease_thread
        if t is not None:
            t.join(timeout=5)

    @property
    def applied_seq(self) -> int:
        return self.local.tail_lsn(OPLOG_ID)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._is_leader

    def _journal_event(self, kind: str, message: str, **fields) -> None:
        if self.journal is None:
            return
        try:
            self.journal.append(kind, message, **fields)
        except Exception:  # noqa: BLE001 — journaling is best-effort
            pass

    def _accept_leader_locked(self, request) -> None:
        """Bind (epoch, leader_id, hint) from an accepted request;
        demotes a promoted self. Caller holds _lock."""
        was = (self._epoch, self._leader_id)
        if request.epoch > self._epoch:
            self._epoch = int(request.epoch)
            _store_epoch(self.local, self._epoch)
        # binding writes below are waived as single-writer: only this
        # follower writes its own durable meta, under _lock, after the
        # Replicate epoch ladder accepted the leader
        self._leader_id = request.leader_id
        self.local.meta_put(META_LEADER_ID, request.leader_id.encode())  # analyze: ok cas-blind-meta-write
        if request.leader_hint:
            self._leader_hint = request.leader_hint
            self.local.meta_put(META_LEADER_HINT,  # analyze: ok cas-blind-meta-write
                                request.leader_hint.encode())
        if self._is_leader:
            self._is_leader = False
            self.local.meta_put(META_IS_LEADER, b"0")  # analyze: ok cas-blind-meta-write
        self._journal_event(
            "leader_change",
            f"replica {self.node_id} accepted leader "
            f"{request.leader_id} at epoch {self._epoch} "
            f"(was {was[1]!r} at epoch {was[0]})",
            leader=request.leader_id, epoch=self._epoch)

    def _fenced_response(self, request) -> "pb.ReplicateResponse":
        """Reject a stale leader's entries by epoch. Caller holds
        _lock."""
        self._journal_event(
            "replica_fenced",
            f"replica {self.node_id} (epoch {self._epoch}) fenced "
            f"stale leader {request.leader_id!r} (epoch "
            f"{request.epoch}); {len(request.entries)} entries "
            f"rejected",
            stale_leader=request.leader_id,
            stale_epoch=int(request.epoch), epoch=self._epoch,
            entries=len(request.entries))
        return pb.ReplicateResponse(
            applied_seq=self.applied_seq, epoch=self._epoch,
            fenced=True, leader_hint=self._leader_hint or "")

    def Replicate(self, request, context):
        if FAULTS.active:  # chaos: network partition — this follower
            # is unreachable from its leader (the RPC fails before the
            # epoch/bind checks, exactly like a dropped link)
            FAULTS.point("replica.partition")
        with self._lock:
            if self._broken is not None:
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"replica diverged and refuses entries: "
                    f"{self._broken}")
            if request.epoch < self._epoch:
                # stale leader: reject by epoch, answer with who leads
                # now — nothing below this line runs for its entries
                return self._fenced_response(request)
            if request.epoch > self._epoch:
                self._accept_leader_locked(request)
            elif request.leader_id:
                if self._is_leader \
                        and request.leader_id != self.node_id:
                    # dueling same-epoch promotions: deterministic
                    # winner, no split-brain — higher node id leads,
                    # the other demotes and follows
                    if request.leader_id > self.node_id:
                        self._accept_leader_locked(request)
                    else:
                        return self._fenced_response(request)
                elif self._leader_id is None:
                    self._accept_leader_locked(request)
                elif self._leader_id != request.leader_id:
                    # two same-epoch leaders feeding one follower is
                    # operator error; acking both would silently
                    # diverge them
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"replica already follows "
                        f"{self._leader_id!r}, refusing entries from "
                        f"{request.leader_id!r}")
            self._last_leader_contact = time.monotonic()
            applied = self.applied_seq
            for e in request.entries:
                if e.seq and e.seq != applied + 1:
                    break  # out of order: answer with where we are
                # apply FIRST, log second: a failed apply must not
                # advance applied_seq (= op-log tail), or the leader
                # would skip the op forever and the replica silently
                # diverges. If apply succeeds but the op-log append
                # fails, re-applying on retry WOULD duplicate the op —
                # mark the replica broken (operator re-bootstraps it)
                # rather than diverge quietly either way.
                try:
                    _apply(self.local, e)
                except ReplicaDivergence as exc:
                    # the local store no longer matches the op-log:
                    # latch broken so EVERY further Replicate is
                    # refused with the divergence error (operator
                    # re-bootstraps) — a bare abort would let the
                    # leader retry into the same mismatch forever
                    self._broken = exc
                    log.error("replica %s DIVERGED at seq %d: %s",
                              self.node_id, e.seq, exc)
                    self._journal_event(
                        "replica_fenced",
                        f"replica {self.node_id} halted on divergence "
                        f"at seq {e.seq}: {exc}",
                        seq=int(e.seq))
                    context.abort(grpc.StatusCode.INTERNAL, str(exc))
                except Exception as exc:  # noqa: BLE001
                    log.error("replica %s: apply failed at seq %d: %s",
                              self.node_id, e.seq, exc)
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"apply failed at seq {e.seq}: {exc}")
                try:
                    applied = self.local.append(OPLOG_ID,
                                                _encode_entry(e))
                except Exception as exc:  # noqa: BLE001
                    self._broken = exc
                    log.error(
                        "replica %s BROKEN: op %d applied but not "
                        "logged: %s", self.node_id, e.seq, exc)
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"op-log append failed: {exc}")
                self._ops_since_trim += 1
            if self._ops_since_trim >= 512:
                # the follower's op-log only backs _reconcile (last
                # entry) and applied_seq (the tail): reclaim the rest
                self._ops_since_trim = 0
                if applied > 1:
                    self.local.trim(OPLOG_ID, applied - 1)
            return pb.ReplicateResponse(applied_seq=applied,
                                        epoch=self._epoch)

    def ReplicaInfo(self, request, context):
        with self._lock:
            # when leading, the hint is the CLIENT-facing address the
            # promotion recorded (where the SQL server over this store
            # serves), falling back to the replica listen addr
            return pb.ReplicaInfoResponse(
                applied_seq=self.applied_seq, is_leader=self._is_leader,
                node_id=self.node_id, epoch=self._epoch,
                leader_hint=(self._leader_hint or self.advertise_addr
                             or self.listen_addr
                             if self._is_leader
                             else self._leader_hint or ""))

    def ClusterStats(self, request, context):
        """Federation face of a BARE follower process (ISSUE 15): no
        stats holder lives here, so the report carries the load axes a
        follower has — role, op-log position, rss — keeping the node
        visible in the merged `admin cluster-stats` table instead of
        reading as unreachable."""
        import json as _json
        import time as _time

        from hstream_tpu.stats.cluster import rss_bytes

        with self._lock:
            applied, is_leader = self.applied_seq, self._is_leader
            epoch = self._epoch
        role = "leader" if is_leader else "follower"
        report = {"node": self.node_id, "addr": self.listen_addr,
                  "role": role, "ts_ms": int(_time.time() * 1000),
                  "rss_bytes": rss_bytes(), "running_queries": 0,
                  "append_inflight": 0, "applied_seq": applied,
                  "epoch": epoch, "streams": {}, "queries": {}}
        return pb.ClusterStatsResponse(reports=[pb.NodeStatsReport(
            node=self.node_id, role=role, ts_ms=report["ts_ms"],
            rss_bytes=report["rss_bytes"],
            report=_json.dumps(report))])

    # ---- promotion ---------------------------------------------------------

    def Promote(self, request, context):
        """Raise this replica to leadership at ``request.epoch``. The
        caller (admin promote / lease auto-promotion) is responsible
        for picking the most-caught-up candidate; the epoch guard here
        makes a raced second promotion at the same or a lower epoch a
        clean refusal instead of a second leader."""
        if FAULTS.active:  # chaos: widen the promotion race window
            FAULTS.point("replica.promote.race")
        with self._lock:
            if self._broken is not None:
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"replica diverged; refusing promotion: "
                    f"{self._broken}")
            if request.epoch <= self._epoch:
                return pb.PromoteResponse(
                    ok=False, epoch=self._epoch,
                    applied_seq=self.applied_seq, node_id=self.node_id)
            self._promote_locked(int(request.epoch),
                                 request.leader_addr,
                                 request.promoted_by or "operator")
            return pb.PromoteResponse(
                ok=True, epoch=self._epoch,
                applied_seq=self.applied_seq, node_id=self.node_id)

    def _promote_locked(self, epoch: int, leader_addr: str,
                        promoted_by: str) -> None:
        # monotonicity is the CALLER's guard (Promote refuses
        # epoch <= self._epoch before getting here; the lease loop
        # checks the same), so the assignment is bare by design
        self._epoch = epoch  # analyze: ok cas-epoch-nonmonotone
        _store_epoch(self.local, epoch)
        # promotion meta below is waived single-writer: own store,
        # under _lock, behind the caller's epoch guard
        self._is_leader = True
        self.local.meta_put(META_IS_LEADER, b"1")  # analyze: ok cas-blind-meta-write
        self._leader_id = self.node_id
        self.local.meta_put(META_LEADER_ID, self.node_id.encode())  # analyze: ok cas-blind-meta-write
        hint = (leader_addr or self.advertise_addr
                or self.listen_addr or "")
        self._leader_hint = hint or None
        if hint:
            self.local.meta_put(META_LEADER_HINT, hint.encode())  # analyze: ok cas-blind-meta-write
        # a ReplicatedStore later opened over this store must keep this
        # identity, so followers see one continuous leader
        self.local.meta_put("replica/node_id", self.node_id.encode())  # analyze: ok cas-blind-meta-write
        log.warning("replica %s PROMOTED to leader at epoch %d "
                    "(by %s; hint %r)", self.node_id, epoch,
                    promoted_by, hint)
        self._journal_event(
            "replica_promoted",
            f"replica {self.node_id} promoted to leader at epoch "
            f"{epoch} (by {promoted_by})",
            epoch=epoch, promoted_by=promoted_by,
            applied_seq=self.applied_seq)

    # ---- lease-timeout auto-promotion (flag-gated) -------------------------

    def _lease_loop(self) -> None:
        """Flag-gated self-promotion: when the accepted leader goes
        silent past the lease, promote — unless a reachable peer is
        more caught up (it will promote instead; highest
        (epoch, applied_seq, node_id) wins, the same rule the admin
        uses)."""
        lease = float(self.lease_timeout_s or 0)
        step = max(min(lease / 4.0, 1.0), 0.05)
        while not self._stop_ev.wait(step):
            with self._lock:
                if self._is_leader or self._leader_id is None:
                    continue  # nothing to take over yet
                silent = time.monotonic() - self._last_leader_contact
                if silent < lease:
                    continue
                my_epoch, my_seq = self._epoch, self.applied_seq
            # peer probes get a real RPC deadline, NOT the poll step
            # (which bottoms out at 50ms): a healthy more-caught-up
            # peer mistaken for unreachable under momentary jitter
            # would let a LESS caught-up replica seal the group and
            # strand that peer's quorum-acked entries
            best = best_replica(self.peers, timeout=max(step, 1.0))
            if best is not None and best[1] > (my_epoch, my_seq,
                                               self.node_id):
                continue  # a better-placed peer promotes instead
            new_epoch = max(my_epoch,
                            best[1][0] if best else my_epoch) + 1
            promoted = False
            with self._lock:
                if self._is_leader or self._epoch >= new_epoch:
                    continue  # raced: someone already moved the epoch
                if (time.monotonic() - self._last_leader_contact
                        < lease):
                    continue  # the leader came back mid-deliberation
                hint = self.advertise_addr or self.listen_addr
                self._promote_locked(new_epoch, hint, "lease-timeout")
                promoted = True
            if promoted:
                # outside the lock (RPC work): seal the peers at the
                # new epoch so none of them acks the silent leader if
                # it resurfaces mid-takeover
                seal_replicas(self.peers, epoch=new_epoch,
                              leader_id=self.node_id,
                              leader_hint=hint or "",
                              timeout=max(step, 1.0))


def serve_follower(local: LogStore, listen: str, *,
                   node_id: str = "follower",
                   advertise_addr: str = "",
                   lease_timeout_s: float | None = None,
                   peers: Sequence[str] = ()):
    """Start a follower replica service; returns (grpc server, svc).
    ``lease_timeout_s`` arms the flag-gated auto-promotion path;
    ``peers`` are the OTHER replicas consulted before self-promoting
    (most-caught-up wins); ``advertise_addr`` is the client-facing SQL
    address served as the leader hint if this replica promotes."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    svc = FollowerService(local, node_id=node_id, listen_addr=listen,
                          advertise_addr=advertise_addr,
                          lease_timeout_s=lease_timeout_s, peers=peers)
    add_store_replica_to_server(svc, server)
    server.add_insecure_port(listen)
    server.start()
    log.info("store replica follower %s listening on %s", node_id, listen)
    return server, svc


def follower_main(argv=None) -> None:
    """Run a follower store replica node:
    ``python -m hstream_tpu.store.replica --store DIR --listen ADDR``"""
    import argparse
    import signal
    import threading as _threading

    from hstream_tpu.store import open_store

    ap = argparse.ArgumentParser("hstream-tpu-store-replica")
    ap.add_argument("--store", required=True,
                    help="mem:// or a directory for the local store")
    ap.add_argument("--listen", required=True, metavar="HOST:PORT")
    ap.add_argument("--node-id", default="follower")
    ap.add_argument("--auto-promote-lease-ms", type=int, default=None,
                    help="OPT-IN auto-promotion: if the accepted "
                         "leader goes silent for this long, promote "
                         "self to leader (after checking --peers for "
                         "a more caught-up replica); off by default — "
                         "the safe default is operator-driven "
                         "`admin promote`")
    ap.add_argument("--peers", default="", metavar="ADDR,ADDR",
                    help="other replica addresses consulted before "
                         "auto-promotion (most-caught-up wins)")
    ap.add_argument("--advertise-addr", default="", metavar="ADDR",
                    help="client-facing SQL address served as the "
                         "leader hint if this replica auto-promotes "
                         "(where the operator boots the server over "
                         "the promoted store); defaults to --listen, "
                         "which serves StoreReplica only")
    args = ap.parse_args(argv)

    local = open_store(args.store)
    lease = (args.auto_promote_lease_ms / 1000.0
             if args.auto_promote_lease_ms else None)
    server, svc = serve_follower(
        local, args.listen, node_id=args.node_id,
        advertise_addr=args.advertise_addr,
        lease_timeout_s=lease,
        peers=[p.strip() for p in args.peers.split(",") if p.strip()])
    done = _threading.Event()

    def on_signal(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    done.wait()
    server.stop(grace=1)
    svc.close()
    local.close()


if __name__ == "__main__":
    follower_main()
