"""Multi-host store replication over DCN.

The reference's storage tier is a replicated LogDevice cluster: the
server takes a ``replicate-factor`` flag and the whole cbits layer binds
a store that survives node loss (reference hstream/app/server.hs:83-90,
hstream-store/include/hs_logdevice.h). The embedded store here is
single-node, so this module adds the replication layer:

  * every mutating store op (append/trim/create/remove/meta) becomes an
    entry in a durable **op-log** — a reserved log inside the local
    store itself, so the replication stream is recoverable from disk;
  * the **leader** applies ops locally, then per-follower sender
    threads stream op-log entries IN ORDER over gRPC (DCN); a follower
    response always carries its applied sequence, so a lagging or
    rejoining follower is caught up from the leader's op-log — the
    same path as steady-state replication;
  * **followers** apply entries deterministically to their own local
    store; starting from the same initial state, replicas are
    byte-identical (same LSNs, same segments' logical content);
  * appends ack once ``replication_factor - 1`` followers (or every
    live follower, whichever is fewer) have applied the entry —
    availability over strict durability when nodes are down, with the
    degradation logged (LogDevice instead re-routes to other nodes of
    a larger cluster);
  * reads stay local on any replica (gap semantics are the local
    store's own).

Leadership is static configuration (``--replica-role leader``); leader
election is the cluster scheduler's concern, not the storage layer's.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from concurrent import futures
from typing import Sequence

import grpc

from hstream_tpu.common.backoff import jittered_backoff
from hstream_tpu.common.errors import StoreIOError
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import StoreReplicaStub, add_store_replica_to_server
from hstream_tpu.store.api import Compression, LogAttrs, LogStore

log = get_logger("replica")

# reserved logid holding the replication op-log inside each local store
OPLOG_ID = (1 << 61) + 7

_ACK_TIMEOUT_S = 5.0
# follower reconnect backoff: jittered exponential from _RETRY_S up to
# _RETRY_CAP_S — a flapping follower must not spin the leader's sender
# thread hot (ISSUE 8); reset only once a Replicate is ACKED (a peer
# that merely accepts connections keeps backing off)
_RETRY_S = 0.2
_RETRY_CAP_S = 5.0
_RETRY_JITTER = 0.25


def _encode_entry(e: pb.LogEntry) -> bytes:
    return e.SerializeToString()


def _decode_entry(b: bytes) -> pb.LogEntry:
    return pb.LogEntry.FromString(b)


def _apply(store: LogStore, e: pb.LogEntry) -> None:
    """Apply one op to a local store. Deterministic AND idempotent:
    every replica applies the same entries in the same order, and
    re-applying an entry after a crash in the apply/log window is a
    no-op (appends are guarded by expect_lsn; the other ops are
    naturally idempotent)."""
    if FAULTS.active:  # chaos probe; one branch when disarmed
        FAULTS.point("store.oplog.apply")
    if e.op == pb.OP_APPEND:
        if e.expect_lsn and store.tail_lsn(e.logid) >= e.expect_lsn:
            return  # already applied (crash between apply and log)
        lsn = store.append_batch(e.logid, list(e.payloads),
                                 Compression(e.compression),
                                 append_time_ms=e.append_time_ms or None)
        if e.expect_lsn and lsn != e.expect_lsn:
            raise StoreIOError(
                f"replica diverged: append to log {e.logid} landed at "
                f"lsn {lsn}, expected {e.expect_lsn}")
    elif e.op == pb.OP_TRIM:
        store.trim(e.logid, e.trim_lsn)
    elif e.op == pb.OP_CREATE_LOG:
        if not store.log_exists(e.logid):
            store.create_log(e.logid, LogAttrs(
                replication_factor=e.replication_factor or 1,
                backlog_seconds=e.backlog_seconds))
    elif e.op == pb.OP_REMOVE_LOG:
        if store.log_exists(e.logid):
            store.remove_log(e.logid)
    elif e.op == pb.OP_META_PUT:
        store.meta_put(e.meta_key, e.meta_value)
    elif e.op == pb.OP_META_DELETE:
        store.meta_delete(e.meta_key)
    else:  # unknown op from a newer leader: fail loudly, don't diverge
        raise ValueError(f"unknown replication op {e.op}")


def _stable_node_id(store: LogStore) -> str:
    nid = store.meta_get("replica/node_id")
    if nid is None:
        nid = f"leader-{uuid.uuid4().hex[:10]}".encode()
        store.meta_put("replica/node_id", nid)
    return nid.decode()


def _reconcile(store: LogStore) -> None:
    """Crash recovery for the apply/log window: ops are serialized, so
    at most the LAST op-log entry can be logged-but-unapplied (leader
    logs first) — re-apply it; idempotence makes this safe when it DID
    apply."""
    tail = store.tail_lsn(OPLOG_ID)
    if not tail:
        return
    reader = store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(OPLOG_ID, tail, tail)
    for item in reader.read(4):
        if hasattr(item, "payloads"):
            for p in item.payloads:
                e = _decode_entry(p)
                e.seq = item.lsn
                if e.op == pb.OP_APPEND and not e.expect_lsn:
                    # no idempotence marker: re-applying could
                    # duplicate the batch — skipping risks at most one
                    # missing apply, which the seq handshake surfaces
                    log.warning("skipping reconcile of unverifiable "
                                "append at seq %d", e.seq)
                    continue
                _apply(store, e)
    reader.stop_reading(OPLOG_ID)


class _Follower:
    """Leader-side sender for one follower: an in-order stream of
    op-log entries driven by the follower's acked sequence."""

    def __init__(self, addr: str, owner: "ReplicatedStore"):
        self.addr = addr
        self.owner = owner
        self.acked_seq = 0
        self.alive = False
        # reconnect backoff state: attempt count since the last ACKED
        # Replicate (not merely the last good connect) + the wait the
        # next failure will schedule (tests assert growth and the
        # cap). Jitter is seeded per follower so a chaos run replays
        # the same wait sequence.
        self.connect_attempts = 0
        self.last_backoff_s = 0.0
        self._jitter = random.Random(addr)
        self._thread = threading.Thread(
            target=self._run, name=f"repl-{addr}", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def _backoff(self) -> float:
        """Jittered exponential reconnect wait: base * 2^attempt capped
        at _RETRY_CAP_S, +/- _RETRY_JITTER so a fleet of senders
        doesn't reconnect in lockstep."""
        wait = jittered_backoff(
            self.connect_attempts, base=_RETRY_S, cap=_RETRY_CAP_S,
            jitter=_RETRY_JITTER, rng=self._jitter)
        self.connect_attempts += 1
        self.last_backoff_s = wait
        return wait

    def _run(self) -> None:
        owner = self.owner
        while not owner._stop.is_set():
            try:
                if FAULTS.active:  # chaos: provoke a connect failure
                    FAULTS.point("store.follower.connect")
                with grpc.insecure_channel(self.addr) as ch:
                    stub = StoreReplicaStub(ch)
                    info = stub.ReplicaInfo(pb.ReplicaInfoRequest(),
                                            timeout=_ACK_TIMEOUT_S)
                    self.acked_seq = info.applied_seq
                    if not self.alive:
                        log.info("follower %s up at seq %d", self.addr,
                                 self.acked_seq)
                    self.alive = True
                    with owner._cond:
                        owner._cond.notify_all()
                    self._stream(stub)
            except Exception as e:  # noqa: BLE001 — any failure (RPC,
                # local read, decode) must keep the retry loop alive and
                # the follower marked down, never kill the sender thread
                # with alive stuck True
                if self.alive:
                    log.warning("follower %s down: %s", self.addr,
                                e.code() if isinstance(e, grpc.RpcError)
                                else e)
                    journal = getattr(owner, "journal", None)
                    if journal is not None:
                        try:
                            journal.append(
                                "follower_down",
                                f"store follower {self.addr} stopped "
                                f"acking at seq {self.acked_seq}",
                                follower=self.addr,
                                acked_seq=self.acked_seq)
                        except Exception:  # noqa: BLE001
                            pass
                self.alive = False
                with owner._cond:
                    owner._cond.notify_all()
                if owner._stop.wait(self._backoff()):
                    return
        self.alive = False

    def _stream(self, stub) -> None:
        owner = self.owner
        reader = owner.local.new_reader()
        reader.set_timeout(0)
        pos = 0  # next seq the persistent reader is positioned at
        try:
            while not owner._stop.is_set():
                with owner._cond:
                    while (self.acked_seq >= owner._seq
                           and not owner._stop.is_set()):
                        owner._cond.wait(0.5)
                    if owner._stop.is_set():
                        return
                want = self.acked_seq + 1
                if pos != want:
                    if pos:
                        reader.stop_reading(OPLOG_ID)
                    reader.start_reading(OPLOG_ID, want)
                    pos = want
                entries = []
                gap_hi = 0
                for item in reader.read(64):
                    if hasattr(item, "payloads"):
                        for p in item.payloads:
                            e = _decode_entry(p)
                            e.seq = item.lsn  # seq IS the op-log LSN
                            entries.append(e)
                    elif hasattr(item, "hi_lsn"):
                        gap_hi = max(gap_hi, item.hi_lsn)
                if gap_hi and (not entries
                               or entries[0].seq != want):
                    # the follower is below the op-log trim point:
                    # catch-up cannot reconstruct those ops. Stop
                    # replicating to it — operator re-bootstraps the
                    # replica from a copy of a live store.
                    log.error(
                        "follower %s needs entries up to seq %d but "
                        "the op-log is trimmed to %d; re-bootstrap "
                        "this replica", self.addr, gap_hi,
                        self.owner.local.trim_point(OPLOG_ID))
                    raise StoreIOError("follower below op-log trim")
                if not entries:
                    continue
                pos = entries[-1].seq + 1
                if FAULTS.active:  # chaos: drop the ack RPC
                    FAULTS.point("store.follower.ack")
                resp = stub.Replicate(
                    pb.ReplicateRequest(entries=entries,
                                        leader_id=owner.node_id),
                    timeout=_ACK_TIMEOUT_S)
                # the follower's word is authoritative: a lagging
                # applied seq rewinds the stream (e.g. it restarted
                # from older disk)
                self.acked_seq = resp.applied_seq
                # real streaming progress: only now does the reconnect
                # schedule start over — a half-broken peer that answers
                # ReplicaInfo but fails every Replicate must keep
                # backing off, not retry at the floor forever
                self.connect_attempts = 0
                self.last_backoff_s = 0.0
                with owner._cond:
                    owner._cond.notify_all()
        finally:
            if pos:
                reader.stop_reading(OPLOG_ID)


class ReplicatedStore(LogStore):
    """Leader-side LogStore: applies locally + replicates to followers.

    Mutations go through the durable op-log; reads and introspection are
    the local store's. ``append_batch`` blocks until the entry is
    applied on min(replication_factor-1, live followers) replicas."""

    def __init__(self, local: LogStore, followers: Sequence[str], *,
                 replication_factor: int = 2,
                 node_id: str | None = None):
        self.local = local
        # stable across restarts (persisted in the local store) AND
        # unique per store: a follower rejects entries from a second
        # leader by id, which only works if ids differ between stores
        # but SURVIVE a leader restart
        self.node_id = node_id or _stable_node_id(local)
        self.replication_factor = max(int(replication_factor), 1)
        self._stop = threading.Event()
        self._cond = threading.Condition()
        self._broken: BaseException | None = None
        # durability introspection: status of the most recent acked
        # append ("replicated" | "degraded:followers_down" |
        # "degraded:timeout") + a monotone degraded counter, so callers
        # can assert what an ack actually meant instead of trusting the
        # normal return (ISSUE 1: a timed-out ack used to look fully
        # replicated)
        self.last_ack_status: str = "replicated"
        self.degraded_appends: int = 0
        # optional event journal (stats.events.EventJournal): the server
        # context attaches one so degraded acks / follower loss become
        # queryable operator events, not just log lines
        self.journal = None
        self._async_pool = futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="repl-ack")
        self._ops_since_trim = 0
        if not local.log_exists(OPLOG_ID):
            local.create_log(OPLOG_ID)
        _reconcile(local)  # crash in the log/apply window: replay last
        self._seq = local.tail_lsn(OPLOG_ID)  # durable across restarts
        self._followers = [_Follower(a, self) for a in followers]
        for f in self._followers:
            f.start()

    # ---- replication core --------------------------------------------------

    def _check_broken(self) -> None:
        if self._broken is not None:
            raise StoreIOError(
                f"replicated store is in a broken state (an op was "
                f"logged but failed to apply locally): {self._broken}")

    def _log_and_apply(self, entry: pb.LogEntry) -> int:
        """The one critical section: durably log the op, apply it
        locally, wake the sender threads. Returns the op's seq.
        Caller holds nothing; broken-state on apply failure."""
        self._check_broken()
        with self._cond:
            if entry.op == pb.OP_APPEND:
                # stamp idempotence + time BEFORE logging, under the
                # lock: replicas must land the append at this LSN with
                # this timestamp
                entry.expect_lsn = self.local.tail_lsn(entry.logid) + 1
                if not entry.append_time_ms:
                    entry.append_time_ms = int(time.time() * 1000)
            seq = self.local.append(OPLOG_ID, _encode_entry(entry))
            self._seq = seq
            try:
                _apply(self.local, entry)
            except Exception as e:  # noqa: BLE001
                # the op is durably logged (followers WILL apply it) but
                # this replica didn't: refusing further mutations beats
                # silent divergence
                self._broken = e
                log.error("leader apply failed at seq %d: %s", seq, e)
                raise
            self._cond.notify_all()
        return seq

    def _replicate(self, entry: pb.LogEntry, *, wait: bool = True) -> None:
        seq = self._log_and_apply(entry)
        if wait:
            self._wait_acks(seq)

    def follower_status(self) -> list[dict]:
        """Per-follower liveness/lag plus the store-level ack status on
        every entry, so one call answers both "who is behind" and "was
        the last ack degraded"."""
        # found by hstream-analyze (lock-guard): _seq is written under
        # _cond by _log_and_apply/meta_cas on appender threads; reading
        # it unlocked here could report a lag computed from a stale seq
        seq = self.oplog_seq
        return [{"addr": f.addr, "alive": f.alive,
                 "acked_seq": f.acked_seq,
                 "behind": max(0, seq - f.acked_seq),
                 "last_ack_status": self.last_ack_status,
                 "degraded_appends": self.degraded_appends}
                for f in self._followers]

    @property
    def oplog_seq(self) -> int:
        with self._cond:
            return self._seq

    # ---- LogStore: mutations (replicated) ----------------------------------

    def create_log(self, logid: int, attrs: LogAttrs | None = None) -> None:
        a = attrs or LogAttrs()
        self._replicate(pb.LogEntry(
            op=pb.OP_CREATE_LOG, logid=logid,
            replication_factor=a.replication_factor,
            backlog_seconds=a.backlog_seconds))

    def remove_log(self, logid: int) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_REMOVE_LOG, logid=logid))

    def append_batch(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE, *,
                     append_time_ms: int | None = None) -> int:
        entry = pb.LogEntry(op=pb.OP_APPEND, logid=logid,
                            payloads=[bytes(p) for p in payloads],
                            compression=compression.value,
                            append_time_ms=append_time_ms or 0)
        seq = self._log_and_apply(entry)
        self._wait_acks(seq)
        self._maybe_trim_oplog()
        return entry.expect_lsn

    def _maybe_trim_oplog(self) -> None:
        """Reclaim op-log space every so often: entries every follower
        has applied are never needed again (a rejoining follower below
        the trim point is unrecoverable by catch-up and must be
        re-bootstrapped from a copy — the trade LogDevice also makes
        with trimmed logs). A permanently-dead follower pins the op-log
        until the operator removes it from --replicate."""
        self._ops_since_trim += 1
        if self._ops_since_trim < 512 or not self._followers:
            return
        self._ops_since_trim = 0
        if not all(f.alive for f in self._followers):
            return
        low = min(f.acked_seq for f in self._followers)
        if low > self.local.trim_point(OPLOG_ID):
            self.local.trim(OPLOG_ID, low)

    def _wait_acks(self, seq: int) -> str:
        """Wait for the replication quorum; returns the DURABILITY the
        ack actually achieved — "replicated" when `need` followers
        applied the op, else a degraded status ("degraded:
        followers_down" / "degraded:timeout"). A degraded return is
        recorded (last_ack_status, degraded_appends) so callers and
        tests can assert it instead of mistaking availability for full
        replication."""
        status = self._wait_acks_inner(seq)
        # under the lock: async-append pool threads and callers wait
        # acks concurrently, and a lost increment would undercount
        # degraded events exactly when the cluster is degraded
        with self._cond:
            self.last_ack_status = status
            if status != "replicated":
                self.degraded_appends += 1
        if status != "replicated" and self.journal is not None:
            try:
                self.journal.append(
                    "degraded_append",
                    f"append acked {status} at seq {seq}",
                    status=status, seq=seq)
            except Exception:  # noqa: BLE001 — journaling must not
                pass           # affect append durability semantics
        return status

    def _wait_acks_inner(self, seq: int) -> str:
        if not self._followers:
            return "replicated"
        need = min(self.replication_factor - 1, len(self._followers))
        if need <= 0:
            return "replicated"
        deadline = time.monotonic() + _ACK_TIMEOUT_S
        with self._cond:
            while True:
                acked = sum(1 for f in self._followers
                            if f.acked_seq >= seq)
                if acked >= need:
                    return "replicated"
                live = sum(1 for f in self._followers if f.alive)
                if acked >= live:
                    if live < need:
                        log.warning(
                            "replication degraded: %d/%d followers "
                            "live; seq %d acked by %d", live,
                            len(self._followers), seq, acked)
                        return "degraded:followers_down"
                if time.monotonic() > deadline:
                    log.warning(
                        "replication ack timeout at seq %d (%d/%d)",
                        seq, acked, need)
                    return "degraded:timeout"
                self._cond.wait(0.2)

    def trim(self, logid: int, up_to_lsn: int) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_TRIM, logid=logid,
                                    trim_lsn=up_to_lsn))

    def meta_put(self, key: str, value: bytes) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_META_PUT, meta_key=key,
                                    meta_value=value), wait=False)

    def meta_delete(self, key: str) -> None:
        self._replicate(pb.LogEntry(op=pb.OP_META_DELETE, meta_key=key),
                        wait=False)

    def meta_cas(self, key: str, expected: bytes | None,
                 value: bytes) -> bool:
        # CAS decided on the leader (the single sequencer), replicated
        # as its winning put. CAS + op-log append stay in ONE critical
        # section: two racing winners must log their puts in decision
        # order, or the earlier value would overwrite the later one on
        # every replica.
        self._check_broken()
        with self._cond:
            ok = self.local.meta_cas(key, expected, value)
            if ok:
                seq = self.local.append(OPLOG_ID, _encode_entry(
                    pb.LogEntry(op=pb.OP_META_PUT, meta_key=key,
                                meta_value=value)))
                self._seq = seq
                self._cond.notify_all()
        return ok

    # ---- LogStore: reads/introspection (local) -----------------------------

    def log_exists(self, logid: int) -> bool:
        return self.local.log_exists(logid)

    def list_logs(self) -> list[int]:
        return [l for l in self.local.list_logs() if l != OPLOG_ID]

    def log_attrs(self, logid: int) -> LogAttrs:
        return self.local.log_attrs(logid)

    def tail_lsn(self, logid: int) -> int:
        return self.local.tail_lsn(logid)

    def trim_point(self, logid: int) -> int:
        return self.local.trim_point(logid)

    def find_time(self, logid: int, ts_ms: int) -> int:
        return self.local.find_time(logid, ts_ms)

    def is_log_empty(self, logid: int) -> bool:
        return self.local.is_log_empty(logid)

    def new_reader(self, max_logs: int = 1):
        return self.local.new_reader(max_logs)

    def meta_get(self, key: str) -> bytes | None:
        return self.local.meta_get(key)

    def meta_list(self, prefix: str) -> list[str]:
        return self.local.meta_list(prefix)

    def close(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        for f in self._followers:
            f._thread.join(timeout=2)
        self._async_pool.shutdown(wait=True)
        self.local.close()

    # async append parity with the native store (sink fast path): the
    # local log+apply happens inline (cheap), but the follower-ack wait
    # moves to a pool thread so the caller keeps its bounded-in-flight
    # pipelining instead of serializing on a DCN round trip per batch
    def append_async(self, logid: int, payloads: Sequence[bytes]):
        entry = pb.LogEntry(op=pb.OP_APPEND, logid=logid,
                            payloads=[bytes(p) for p in payloads])
        seq = self._log_and_apply(entry)
        lsn = entry.expect_lsn

        def waiter() -> int:
            self._wait_acks(seq)
            self._maybe_trim_oplog()
            return lsn

        return self._async_pool.submit(waiter)


class FollowerService:
    """Follower-side gRPC service: applies in-order entries to the
    local store; always answers with its applied sequence."""

    def __init__(self, local: LogStore, *, node_id: str = "follower",
                 journal=None):
        self.local = local
        self.node_id = node_id
        self.journal = journal  # optional stats.events.EventJournal
        self._lock = threading.Lock()
        self._broken: BaseException | None = None
        # the accepted leader binding is DURABLE (store meta): a
        # restarted follower must keep rejecting a stale leader instead
        # of re-accepting whichever connects first after the restart
        raw = local.meta_get("replica/leader_id")
        self._leader_id: str | None = (raw.decode() if raw is not None
                                       else None)
        self._ops_since_trim = 0
        if not local.log_exists(OPLOG_ID):
            local.create_log(OPLOG_ID)
        _reconcile(local)

    @property
    def applied_seq(self) -> int:
        return self.local.tail_lsn(OPLOG_ID)

    def Replicate(self, request, context):
        with self._lock:
            if self._broken is not None:
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    f"replica diverged and refuses entries: "
                    f"{self._broken}")
            if request.leader_id:
                if self._leader_id is None:
                    self._leader_id = request.leader_id
                    self.local.meta_put("replica/leader_id",
                                        request.leader_id.encode())
                    if self.journal is not None:
                        try:
                            self.journal.append(
                                "leader_change",
                                f"replica {self.node_id} accepted "
                                f"leader {request.leader_id}",
                                leader=request.leader_id)
                        except Exception:  # noqa: BLE001
                            pass
                elif self._leader_id != request.leader_id:
                    # two leaders feeding one follower is operator
                    # error; acking both would silently diverge them
                    context.abort(
                        grpc.StatusCode.FAILED_PRECONDITION,
                        f"replica already follows "
                        f"{self._leader_id!r}, refusing entries from "
                        f"{request.leader_id!r}")
            applied = self.applied_seq
            for e in request.entries:
                if e.seq and e.seq != applied + 1:
                    break  # out of order: answer with where we are
                # apply FIRST, log second: a failed apply must not
                # advance applied_seq (= op-log tail), or the leader
                # would skip the op forever and the replica silently
                # diverges. If apply succeeds but the op-log append
                # fails, re-applying on retry WOULD duplicate the op —
                # mark the replica broken (operator re-bootstraps it)
                # rather than diverge quietly either way.
                try:
                    _apply(self.local, e)
                except Exception as exc:  # noqa: BLE001
                    log.error("replica %s: apply failed at seq %d: %s",
                              self.node_id, e.seq, exc)
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"apply failed at seq {e.seq}: {exc}")
                try:
                    applied = self.local.append(OPLOG_ID,
                                                _encode_entry(e))
                except Exception as exc:  # noqa: BLE001
                    self._broken = exc
                    log.error(
                        "replica %s BROKEN: op %d applied but not "
                        "logged: %s", self.node_id, e.seq, exc)
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"op-log append failed: {exc}")
                self._ops_since_trim += 1
            if self._ops_since_trim >= 512:
                # the follower's op-log only backs _reconcile (last
                # entry) and applied_seq (the tail): reclaim the rest
                self._ops_since_trim = 0
                if applied > 1:
                    self.local.trim(OPLOG_ID, applied - 1)
            return pb.ReplicateResponse(applied_seq=applied)

    def ReplicaInfo(self, request, context):
        return pb.ReplicaInfoResponse(applied_seq=self.applied_seq,
                                      is_leader=False,
                                      node_id=self.node_id)


def serve_follower(local: LogStore, listen: str, *,
                   node_id: str = "follower"):
    """Start a follower replica service; returns (grpc server, svc)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
    svc = FollowerService(local, node_id=node_id)
    add_store_replica_to_server(svc, server)
    server.add_insecure_port(listen)
    server.start()
    log.info("store replica follower %s listening on %s", node_id, listen)
    return server, svc


def follower_main(argv=None) -> None:
    """Run a follower store replica node:
    ``python -m hstream_tpu.store.replica --store DIR --listen ADDR``"""
    import argparse
    import signal
    import threading as _threading

    from hstream_tpu.store import open_store

    ap = argparse.ArgumentParser("hstream-tpu-store-replica")
    ap.add_argument("--store", required=True,
                    help="mem:// or a directory for the local store")
    ap.add_argument("--listen", required=True, metavar="HOST:PORT")
    ap.add_argument("--node-id", default="follower")
    args = ap.parse_args(argv)

    local = open_store(args.store)
    server, _svc = serve_follower(local, args.listen,
                                  node_id=args.node_id)
    done = _threading.Event()

    def on_signal(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, on_signal)
    signal.signal(signal.SIGTERM, on_signal)
    done.wait()
    server.stop(grace=1)
    local.close()


if __name__ == "__main__":
    follower_main()
