"""In-memory log store: the default test backend.

Plays the role the reference's MockStreamStore plays for its processing
tests (hstream-processing MockStreamStore.hs:30-160) but implements the
full LogStore interface — including gap records for trims, blocking
readers with timeouts, and the metadata KV — so everything above it
(streams, checkpoints, engine, server) runs unmodified against it.
"""

from __future__ import annotations

import bisect
import threading
import time
from typing import Sequence

from hstream_tpu.common.errors import LogNotFound, StoreError
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.store.api import (
    LSN_INVALID,
    LSN_MAX,
    LSN_MIN,
    Compression,
    DataBatch,
    GapRecord,
    GapType,
    LogAttrs,
    LogReader,
    LogStore,
    ReadResult,
)


class _Log:
    __slots__ = ("attrs", "lsns", "batches", "times", "next_lsn", "trim_lsn")

    def __init__(self, attrs: LogAttrs):
        self.attrs = attrs
        self.lsns: list[int] = []          # sorted LSNs of live batches
        self.batches: dict[int, DataBatch] = {}
        self.times: list[int] = []         # append_time_ms, parallel to lsns
        self.next_lsn = LSN_MIN
        self.trim_lsn = 0                  # highest trimmed LSN


class MemLogStore(LogStore):
    def __init__(self) -> None:
        self._logs: dict[int, _Log] = {}
        self._meta: dict[str, bytes] = {}
        self._lock = threading.RLock()
        self._data_cond = threading.Condition(self._lock)

    # ---- log lifecycle ----
    def create_log(self, logid: int, attrs: LogAttrs | None = None) -> None:
        with self._lock:
            if logid in self._logs:
                raise StoreError(f"log {logid} already exists")
            self._logs[logid] = _Log(attrs or LogAttrs())

    def remove_log(self, logid: int) -> None:
        with self._lock:
            if logid not in self._logs:
                raise LogNotFound(f"log {logid}")
            del self._logs[logid]

    def log_exists(self, logid: int) -> bool:
        with self._lock:
            return logid in self._logs

    def list_logs(self) -> list[int]:
        with self._lock:
            return sorted(self._logs)

    def log_attrs(self, logid: int) -> LogAttrs:
        return self._get(logid).attrs

    def _get(self, logid: int) -> _Log:
        with self._lock:
            log = self._logs.get(logid)
            if log is None:
                raise LogNotFound(f"log {logid}")
            return log

    # ---- append ----
    def append_batch(self, logid: int, payloads: Sequence[bytes],
                     compression: Compression = Compression.NONE, *,
                     append_time_ms: int | None = None) -> int:
        if not payloads:
            raise StoreError("empty batch")
        if FAULTS.active:  # chaos probe; one branch when disarmed
            FAULTS.point("store.append")
        with self._data_cond:
            log = self._get(logid)
            lsn = log.next_lsn
            log.next_lsn += 1
            now = append_time_ms or int(time.time() * 1000)
            log.lsns.append(lsn)
            log.times.append(now)
            log.batches[lsn] = DataBatch(
                logid=logid, lsn=lsn,
                payloads=tuple(bytes(p) for p in payloads),
                append_time_ms=now)
            self._data_cond.notify_all()
            return lsn

    # ---- introspection ----
    def tail_lsn(self, logid: int) -> int:
        with self._lock:
            log = self._get(logid)
            return log.lsns[-1] if log.lsns else LSN_INVALID

    def trim(self, logid: int, up_to_lsn: int) -> None:
        with self._lock:
            log = self._get(logid)
            cut = bisect.bisect_right(log.lsns, up_to_lsn)
            for lsn in log.lsns[:cut]:
                del log.batches[lsn]
            del log.lsns[:cut]
            del log.times[:cut]
            log.trim_lsn = max(log.trim_lsn, up_to_lsn)

    def trim_point(self, logid: int) -> int:
        return self._get(logid).trim_lsn

    def find_time(self, logid: int, ts_ms: int) -> int:
        with self._lock:
            log = self._get(logid)
            i = bisect.bisect_left(log.times, ts_ms)
            if i == len(log.lsns):
                return (log.lsns[-1] + 1) if log.lsns else log.next_lsn
            return log.lsns[i]

    def is_log_empty(self, logid: int) -> bool:
        return self.tail_lsn(logid) == LSN_INVALID

    # ---- reading ----
    def new_reader(self, max_logs: int = 1) -> "MemLogReader":
        return MemLogReader(self)

    # ---- metadata KV ----
    def meta_put(self, key: str, value: bytes) -> None:
        with self._lock:
            self._meta[key] = bytes(value)

    def meta_get(self, key: str) -> bytes | None:
        with self._lock:
            return self._meta.get(key)

    def meta_delete(self, key: str) -> None:
        with self._lock:
            self._meta.pop(key, None)

    def meta_list(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(k for k in self._meta if k.startswith(prefix))

    def meta_cas(self, key: str, expected: bytes | None, value: bytes) -> bool:
        with self._lock:
            if self._meta.get(key) != expected:
                return False
            self._meta[key] = bytes(value)
            return True


class MemLogReader(LogReader):
    """Reader over MemLogStore logs with blocking reads + gap surfacing."""

    def __init__(self, store: MemLogStore):
        self._store = store
        # logid -> [next_lsn_to_read, until_lsn]
        self._cursors: dict[int, list[int]] = {}
        self._timeout_ms = -1

    def start_reading(self, logid: int, from_lsn: int = LSN_MIN,
                      until_lsn: int = LSN_MAX) -> None:
        self._store._get(logid)  # raise if missing
        self._cursors[logid] = [max(from_lsn, LSN_MIN), until_lsn]

    def stop_reading(self, logid: int) -> None:
        self._cursors.pop(logid, None)

    def is_reading(self, logid: int) -> bool:
        return logid in self._cursors

    def set_timeout(self, timeout_ms: int) -> None:
        self._timeout_ms = timeout_ms

    def _poll_once(self, max_records: int) -> list[ReadResult]:
        out: list[ReadResult] = []
        with self._store._lock:
            for logid, cursor in self._cursors.items():
                nxt, until = cursor
                if nxt > until:
                    continue
                try:
                    log = self._store._get(logid)
                except LogNotFound:
                    continue
                # Surface a trim gap once if the cursor fell below trim point.
                if log.trim_lsn >= nxt:
                    hi = min(log.trim_lsn, until)
                    out.append(GapRecord(logid, GapType.TRIM, nxt, hi))
                    cursor[0] = nxt = hi + 1
                    if len(out) >= max_records:
                        break
                i = bisect.bisect_left(log.lsns, nxt)
                while i < len(log.lsns) and len(out) < max_records:
                    lsn = log.lsns[i]
                    if lsn > until:
                        break
                    out.append(log.batches[lsn])
                    cursor[0] = lsn + 1
                    i += 1
                if len(out) >= max_records:
                    break
        return out

    def read(self, max_records: int) -> list[ReadResult]:
        if FAULTS.active:  # chaos probe; one branch when disarmed
            FAULTS.point("store.read")
        deadline = None
        if self._timeout_ms >= 0:
            deadline = time.monotonic() + self._timeout_ms / 1000.0
        while True:
            out = self._poll_once(max_records)
            if out:
                return out
            with self._store._data_cond:
                # Re-check under the lock to avoid a lost wakeup between
                # _poll_once and wait().
                out = self._poll_once(max_records)
                if out:
                    return out
                if deadline is None:
                    self._store._data_cond.wait()
                else:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return []
                    self._store._data_cond.wait(remaining)
