"""Build the native store shared library (g++, no external deps beyond
zlib). The .so is cached next to the source and rebuilt when the source
is newer — a dev-friendly analogue of the reference's cbits build
(hstream-store.cabal cxx-sources)."""

from __future__ import annotations

import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "cpp", "nstore.cpp")
SO = os.path.join(_DIR, "cpp", "libnstore.so")
_lock = threading.Lock()


def build(force: bool = False) -> str:
    """Compile cpp/nstore.cpp -> cpp/libnstore.so if stale; returns the
    .so path."""
    with _lock:
        if (not force and os.path.exists(SO)
                and os.path.getmtime(SO) >= os.path.getmtime(SRC)):
            return SO
        tmp = SO + ".tmp"
        cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared", "-pthread",
               SRC, "-o", tmp, "-lz"]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native store build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp, SO)
        return SO
