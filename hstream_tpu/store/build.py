"""Build the native store shared library (g++, no external deps beyond
zlib). The .so is cached next to the source and rebuilt when the source
is newer — a dev-friendly analogue of the reference's cbits build
(hstream-store.cabal cxx-sources)."""

from __future__ import annotations

import os

from hstream_tpu.common.nativebuild import build_so

_DIR = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_DIR, "cpp", "nstore.cpp")
SO = os.path.join(_DIR, "cpp", "libnstore.so")


def build(force: bool = False) -> str:
    """Compile cpp/nstore.cpp -> cpp/libnstore.so if stale; returns the
    .so path."""
    return build_so(SRC, SO, libs=("z",), force=force)
