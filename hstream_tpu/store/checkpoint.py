"""Checkpoint stores + checkpointed readers.

Reference capability (cbits/logdevice/hs_checkpoint.cpp, Store/Stream.hs:299-357):
three checkpoint-store backends (file / RSM-log / ZK) mapping
(customer_id, logid) -> LSN, and "checkpointed readers" that bind a reader to
a store so consumption can resume where the last committed checkpoint left
off. We provide memory / file / log backends; the log backend is a tiny
replicated-state-machine over the reserved checkpoint log: each update
appends a JSON delta, state is rebuilt by replay on open, and the log is
compacted with a snapshot + trim once the backlog grows.
"""

from __future__ import annotations

import json
import os
import threading

from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.logger import get_logger
from hstream_tpu.store.api import (
    LSN_MAX,
    LSN_MIN,
    CheckpointStore,
    DataBatch,
    LogReader,
    LogStore,
    ReadResult,
)
from hstream_tpu.store.streams import CHECKPOINT_STORE_LOGID, StreamApi

log = get_logger("checkpoint")


class MemCheckpointStore(CheckpointStore):
    def __init__(self) -> None:
        self._data: dict[str, dict[int, int]] = {}
        self._lock = threading.Lock()

    def get(self, customer_id: str, logid: int) -> int | None:
        with self._lock:
            return self._data.get(customer_id, {}).get(logid)

    def update_multi(self, customer_id: str, ckps: dict[int, int]) -> None:
        with self._lock:
            self._data.setdefault(customer_id, {}).update(ckps)

    def remove(self, customer_id: str) -> None:
        with self._lock:
            self._data.pop(customer_id, None)

    def all_for(self, customer_id: str) -> dict[int, int]:
        with self._lock:
            return dict(self._data.get(customer_id, {}))


class FileCheckpointStore(CheckpointStore):
    """One JSON file per root path; atomic replace on update.

    A truncated or torn file (the atomic replace protects against torn
    *replaces*, not a torn write of a pre-atomic-era file or filesystem
    corruption) must not prevent construction — and therefore server
    boot (ISSUE 8). Recovery degrades to an EMPTY store: readers rewind
    to their fallback start (the trim point), replaying at-least-once
    instead of crashing. The corrupt bytes are preserved next to the
    path (``<path>.corrupt``) and ``load_error`` records what happened
    so the owner can journal a ``checkpoint_corrupt`` event."""

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._data: dict[str, dict[str, int]] = {}
        self.load_error: str | None = None
        if os.path.exists(path):
            try:
                with open(path) as f:
                    data = json.load(f)
                if not isinstance(data, dict):
                    raise ValueError(
                        f"checkpoint root is {type(data).__name__}, "
                        f"not an object")
                self._data = data
            except (ValueError, OSError) as e:
                self.load_error = f"{type(e).__name__}: {e}"
                log.error(
                    "checkpoint file %s is corrupt (%s); recovering "
                    "empty — readers rewind to their trim points",
                    path, self.load_error)
                try:
                    os.replace(path, path + ".corrupt")
                except OSError:
                    pass

    def _flush(self) -> None:
        tmp = self._path + ".tmp"
        data = json.dumps(self._data).encode()
        # chaos probe: a torn flush truncates the JSON mid-document
        data = FAULTS.mutate("checkpoint.flush", data)
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path)

    def get(self, customer_id: str, logid: int) -> int | None:
        with self._lock:
            return self._data.get(customer_id, {}).get(str(logid))

    def update_multi(self, customer_id: str, ckps: dict[int, int]) -> None:
        with self._lock:
            cur = self._data.setdefault(customer_id, {})
            for logid, lsn in ckps.items():
                cur[str(logid)] = lsn
            self._flush()

    def remove(self, customer_id: str) -> None:
        with self._lock:
            if self._data.pop(customer_id, None) is not None:
                self._flush()

    def all_for(self, customer_id: str) -> dict[int, int]:
        with self._lock:
            return {int(k): v for k, v in self._data.get(customer_id, {}).items()}


class LogCheckpointStore(CheckpointStore):
    """RSM checkpoint store over the reserved checkpoint log (logid bit 56).

    Each update appends {"c": customer, "k": {logid: lsn}}; remove appends
    {"c": customer, "rm": true}. State = replay of the log. After
    `compact_every` deltas a full snapshot is appended and the log trimmed
    behind it.
    """

    def __init__(self, store: LogStore, *, compact_every: int = 1024):
        self._store = store
        self._logid = CHECKPOINT_STORE_LOGID
        self._lock = threading.Lock()
        self._data: dict[str, dict[int, int]] = {}
        self._deltas = 0
        self._compact_every = compact_every
        # entries the boot replay could not decode/apply (corrupt or
        # torn deltas): skipped loudly instead of failing boot; the
        # ServerContext journals a checkpoint_corrupt event when > 0.
        # A skipped delta can only LOWER a customer's checkpoint, so
        # its reader replays more — at-least-once, never a skip.
        self.replay_skipped = 0
        StreamApi(store).ensure_checkpoint_log()
        self._replay()

    def _replay(self) -> None:
        reader = self._store.new_reader()
        reader.set_timeout(0)
        reader.start_reading(self._logid, LSN_MIN, LSN_MAX)
        while True:
            results = reader.read(256)
            if not results:
                break
            for r in results:
                if not isinstance(r, DataBatch):
                    continue
                for payload in r.payloads:
                    try:
                        self._apply(json.loads(payload))
                    except (ValueError, KeyError, TypeError,
                            AttributeError) as e:
                        self.replay_skipped += 1
                        log.error(
                            "skipping corrupt checkpoint entry at "
                            "lsn %d: %s", r.lsn, e)
        reader.stop_reading(self._logid)

    def _apply(self, entry: dict) -> None:
        if "snap" in entry:
            self._data = {c: {int(k): v for k, v in m.items()}
                          for c, m in entry["snap"].items()}
            return
        customer = entry["c"]
        if entry.get("rm"):
            self._data.pop(customer, None)
        else:
            cur = self._data.setdefault(customer, {})
            for k, v in entry["k"].items():
                cur[int(k)] = v

    def _append(self, entry: dict) -> None:
        data = json.dumps(entry).encode()
        # chaos probe: torn delta write / injected append failure
        data = FAULTS.mutate("checkpoint.flush", data)
        self._store.append(self._logid, data)
        self._deltas += 1
        if self._deltas >= self._compact_every:
            snap = {"snap": {c: {str(k): v for k, v in m.items()}
                             for c, m in self._data.items()}}
            lsn = self._store.append(self._logid, json.dumps(snap).encode())
            self._store.trim(self._logid, lsn - 1)
            self._deltas = 0

    def get(self, customer_id: str, logid: int) -> int | None:
        with self._lock:
            return self._data.get(customer_id, {}).get(logid)

    def update_multi(self, customer_id: str, ckps: dict[int, int]) -> None:
        with self._lock:
            self._data.setdefault(customer_id, {}).update(ckps)
            self._append({"c": customer_id,
                          "k": {str(k): v for k, v in ckps.items()}})

    def remove(self, customer_id: str) -> None:
        with self._lock:
            if self._data.pop(customer_id, None) is not None:
                self._append({"c": customer_id, "rm": True})

    def all_for(self, customer_id: str) -> dict[int, int]:
        with self._lock:
            return dict(self._data.get(customer_id, {}))


class CheckpointedReader:
    """A LogReader bound to a CheckpointStore under a customer id.

    start_reading_from_checkpoint resumes at checkpoint+1 (or the given
    start when none committed); write_checkpoints commits progress
    (reference: newLDRsmCkpReader + writeCheckpoints, Stream.hs:299-357).
    """

    def __init__(self, name: str, reader: LogReader, ckp_store: CheckpointStore):
        self.name = name
        self.reader = reader
        self.ckp_store = ckp_store

    def start_reading_from_checkpoint(self, logid: int,
                                      fallback_from: int = LSN_MIN,
                                      until_lsn: int = LSN_MAX) -> int:
        ckp = self.ckp_store.get(self.name, logid)
        start = fallback_from if ckp is None else ckp + 1
        self.reader.start_reading(logid, start, until_lsn)
        return start

    def start_reading(self, logid: int, from_lsn: int = LSN_MIN,
                      until_lsn: int = LSN_MAX) -> None:
        self.reader.start_reading(logid, from_lsn, until_lsn)

    def stop_reading(self, logid: int) -> None:
        self.reader.stop_reading(logid)

    def set_timeout(self, timeout_ms: int) -> None:
        self.reader.set_timeout(timeout_ms)

    def read(self, max_records: int) -> list[ReadResult]:
        return self.reader.read(max_records)

    def write_checkpoints(self, ckps: dict[int, int]) -> None:
        self.ckp_store.update_multi(self.name, ckps)

    def remove_checkpoints(self) -> None:
        self.ckp_store.remove(self.name)
