"""CAS-versioned config store.

Reference: LogDevice's VersionedConfigStore — a compare-and-swap
key/value store for cluster configuration where every value carries a
monotonically increasing version and writers must name the base version
they read (cbits/logdevice/hs_versioned_config_store.cpp:1-173). Built
here on the log store's meta-KV CAS primitive, so values are durable on
the native backend (meta WAL) and versions survive reopen.

Value encoding: u64-LE version || flags u8 (1 = tombstone) || payload.
Deletes write a CAS'd tombstone (never an unconditional remove), so a
concurrent writer's new version cannot be deleted unobserved.
"""

from __future__ import annotations

import struct

from hstream_tpu.common.errors import StoreError
from hstream_tpu.store.api import LogStore


class VersionMismatch(StoreError):
    """The caller's base_version no longer matches the stored version."""

    def __init__(self, key: str, expected, actual):
        super().__init__(
            f"version mismatch on {key!r}: base {expected}, "
            f"stored {actual}")
        self.expected = expected
        self.actual = actual


class VersionedConfigStore:
    """Versioned config KV over a LogStore's meta KV."""

    PREFIX = "vcs/"

    def __init__(self, store: LogStore):
        self._store = store

    def _k(self, key: str) -> str:
        return self.PREFIX + key

    @staticmethod
    def _encode(version: int, value: bytes, *,
                tombstone: bool = False) -> bytes:
        return struct.pack("<QB", version, 1 if tombstone else 0) + value

    @staticmethod
    def _decode(raw: bytes) -> tuple[int, bool, bytes]:
        version, flags = struct.unpack_from("<QB", raw)
        return version, bool(flags & 1), raw[9:]

    def get(self, key: str) -> tuple[int, bytes] | None:
        """(version, value) or None when the key does not exist (or was
        deleted — tombstones read as absent but keep the version chain
        so a re-create still needs no stale base)."""
        raw = self._store.meta_get(self._k(key))
        if raw is None:
            return None
        version, tomb, value = self._decode(raw)
        return None if tomb else (version, value)

    def put(self, key: str, value: bytes, *,
            base_version: int | None = None) -> int:
        """Write conditioned on the version the caller read:
        base_version=None creates (fails if the key exists), otherwise
        the stored version must equal base_version. Returns the new
        version; raises VersionMismatch on a lost race."""
        raw = self._store.meta_get(self._k(key))
        live_version = None
        if raw is not None:
            v, tomb, _ = self._decode(raw)
            live_version = None if tomb else v
        if base_version is None:
            if live_version is not None:
                raise VersionMismatch(key, None, live_version)
            next_v = (self._decode(raw)[0] + 1) if raw is not None else 1
            new = self._encode(next_v, value)
            if not self._store.meta_cas(self._k(key), raw, new):
                cur = self.get(key)
                raise VersionMismatch(key, None,
                                      cur[0] if cur else None)
            return next_v
        if live_version is None:
            raise VersionMismatch(key, base_version, None)
        if live_version != base_version:
            raise VersionMismatch(key, base_version, live_version)
        new = self._encode(live_version + 1, value)
        if not self._store.meta_cas(self._k(key), raw, new):
            cur = self.get(key)
            raise VersionMismatch(key, base_version,
                                  cur[0] if cur else None)
        return live_version + 1

    def delete(self, key: str, base_version: int) -> None:
        """CAS the key to a tombstone — a concurrent writer's newer
        version can never be deleted unobserved."""
        raw = self._store.meta_get(self._k(key))
        if raw is None:
            raise VersionMismatch(key, base_version, None)
        version, tomb, _ = self._decode(raw)
        if tomb:
            raise VersionMismatch(key, base_version, None)
        if version != base_version:
            raise VersionMismatch(key, base_version, version)
        new = self._encode(version + 1, b"", tombstone=True)
        if not self._store.meta_cas(self._k(key), raw, new):
            cur = self.get(key)
            raise VersionMismatch(key, base_version,
                                  cur[0] if cur else None)

    def keys(self) -> list[str]:
        """Live (non-tombstoned) keys."""
        out = []
        for k in self._store.meta_list(self.PREFIX):
            short = k[len(self.PREFIX):]
            if self.get(short) is not None:
                out.append(short)
        return out
