"""Producer dedup windows: exactly-once appends across retries AND
across leader failover.

A producer stamps each Append/INSERT with a monotone ``(producer_id,
seq)``. The window for a producer is a bounded map of its most recent
seqs to the ``(lsn, n_records)`` the original append landed at, plus a
high watermark, persisted in store meta under ``dedup/<producer_id>``.

The replication layer maintains the window *deterministically from the
op-log*: the producer stamp rides the replicated ``LogEntry`` itself
(proto ``producer_id``/``producer_seq``) and every replica updates the
window while applying the entry — so the window needs no separate
replication message and, crucially, a promoted follower already holds
exactly the dedup state its applied prefix implies. A retry that
straddles the promotion is answered from the new leader's window with
the ORIGINAL record ids; it cannot land twice on any replica.

Window semantics for an incoming ``seq``:

  * in the window          -> duplicate; return the recorded (lsn, n)
  * above the watermark    -> new; append, then ``record`` it
  * at/below the watermark
    but evicted            -> ``DuplicateAppend`` (ALREADY_EXISTS): the
                              retry is older than the window can vouch
                              for — refusing loudly beats silently
                              appending a possible duplicate

Single-node stores reuse the same functions straight from the Append
handler (guarded by a context-level lock); durability then follows the
store's own meta durability.
"""

from __future__ import annotations

import json

from hstream_tpu.common.errors import DuplicateAppend

DEDUP_PREFIX = "dedup/"
# seqs remembered per producer; older retries get DuplicateAppend
DEDUP_WINDOW = 128


def _meta_key(producer_id: str) -> str:
    return DEDUP_PREFIX + producer_id


def load_window(store, producer_id: str) -> dict:
    """{"hw": int, "seqs": {str(seq): [lsn, n_records]}} (empty when
    the producer has never appended, or the blob is unreadable — a
    corrupt window only widens the ALREADY_EXISTS refusal surface,
    never duplicates). The empty watermark is -1, NOT 0: seq 0 is a
    legal first stamp (and the proto3 default when a client sets only
    producer_id), and `0 <= hw` on a never-seen producer would refuse
    its very first append as an evicted duplicate."""
    raw = store.meta_get(_meta_key(producer_id))
    if raw is None:
        return {"hw": -1, "seqs": {}}
    try:
        w = json.loads(raw)
        if not isinstance(w.get("seqs"), dict):
            raise ValueError("bad seqs")
        w["hw"] = int(w.get("hw", -1))
        return w
    except (ValueError, TypeError, AttributeError):
        return {"hw": -1, "seqs": {}}


def lookup(store, producer_id: str, seq: int):
    """None when `seq` is new (append it, then ``record``); the
    original ``(lsn, n_records)`` when it is a remembered duplicate.
    Raises DuplicateAppend for a seq at/below the watermark that the
    bounded window has already evicted."""
    w = load_window(store, producer_id)
    hit = w["seqs"].get(str(int(seq)))
    if hit is not None:
        return int(hit[0]), int(hit[1])
    if int(seq) <= w["hw"]:
        raise DuplicateAppend(
            f"producer {producer_id!r} seq {seq} is at/below the dedup "
            f"watermark {w['hw']} but outside the {DEDUP_WINDOW}-entry "
            f"window; the append may already be stored")
    return None


def record(store, producer_id: str, seq: int, lsn: int,
           n_records: int) -> None:
    """Remember (seq -> lsn, n) for the producer, evicting the oldest
    seqs past DEDUP_WINDOW. Idempotent — replay after a crash in the
    apply/log window rewrites the same entry."""
    w = load_window(store, producer_id)
    w["seqs"][str(int(seq))] = [int(lsn), int(n_records)]
    w["hw"] = max(w["hw"], int(seq))
    if len(w["seqs"]) > DEDUP_WINDOW:
        for old in sorted(w["seqs"], key=int)[:len(w["seqs"])
                                              - DEDUP_WINDOW]:
            del w["seqs"][old]
    store.meta_put(_meta_key(producer_id),
                   json.dumps(w, sort_keys=True).encode())


def window_size(store) -> int:
    """Total remembered seqs across producers (the dedup_window_size
    gauge; scrape cost is bounded by the number of producers)."""
    total = 0
    for key in store.meta_list(DEDUP_PREFIX):
        raw = store.meta_get(key)
        if raw is None:
            continue
        try:
            total += len(json.loads(raw).get("seqs", {}))
        except (ValueError, TypeError, AttributeError):
            continue
    return total


def guarded_append(store, lock, logid: int, payloads, compression,
                   producer_id: str, producer_seq: int, *,
                   append_time_ms=None):
    """Dedup-checked append for a NON-replicated store: lookup and
    append+record are atomic under `lock` (the replicated store does
    the same inside its own critical section so the window update
    rides the op-log entry). Returns (lsn, n_records, was_duplicate).
    """
    with lock:
        hit = lookup(store, producer_id, producer_seq)
        if hit is not None:
            return hit[0], hit[1], True
        lsn = store.append_batch(logid, payloads, compression,
                                 append_time_ms=append_time_ms)
        record(store, producer_id, producer_seq, lsn, len(payloads))
        return lsn, len(payloads), False
