"""Stream namespace: stream names <-> logids over the store's metadata KV.

Mirrors the reference's store façade (hstream-store/HStream/Store/Stream.hs):
  * three stream types with distinct path namespaces — stream / view / temp
    (Stream.hs:129-141, 196-199)
  * createStream mints a fresh random logid under the path; name->logid
    lookups are cached (Stream.hs:189-259)
  * the checkpoint-store log lives at a reserved logid with bit 56 set
    (Stream.hs:285-295)
"""

from __future__ import annotations

import enum
import json
import random
import threading

from hstream_tpu.common.errors import StreamExists, StreamNotFound
from hstream_tpu.store.api import LogAttrs, LogStore

CHECKPOINT_STORE_LOGID = 1 << 56  # reserved, outside the random logid range


class StreamType(enum.Enum):
    STREAM = "stream"
    VIEW = "view"
    TEMP = "temp"


_PREFIX = {
    StreamType.STREAM: "/hstream/stream/",
    StreamType.VIEW: "/hstream/view/",
    StreamType.TEMP: "/tmp/hstream/",
}


class StreamApi:
    """Name-level stream operations on top of a LogStore."""

    def __init__(self, store: LogStore):
        self.store = store
        self._logid_cache: dict[str, int] = {}
        self._lock = threading.Lock()

    @staticmethod
    def _key(name: str, stype: StreamType) -> str:
        return _PREFIX[stype] + name

    # ---- lifecycle ----
    def create_stream(self, name: str, *, replication_factor: int = 1,
                      backlog_seconds: int = 0,
                      stream_type: StreamType = StreamType.STREAM) -> int:
        key = self._key(name, stream_type)
        with self._lock:
            if self.store.meta_get(key) is not None:
                raise StreamExists(f"stream {name}")
            logid = random.randrange(1, 1 << 48)
            while self.store.log_exists(logid):
                logid = random.randrange(1, 1 << 48)
            attrs = LogAttrs(replication_factor=replication_factor,
                             backlog_seconds=backlog_seconds)
            self.store.create_log(logid, attrs)
            meta = {"logid": logid, "replication_factor": replication_factor,
                    "backlog_seconds": backlog_seconds}
            self.store.meta_put(key, json.dumps(meta).encode())
            self._logid_cache[key] = logid
            return logid

    def delete_stream(self, name: str,
                      stream_type: StreamType = StreamType.STREAM) -> None:
        key = self._key(name, stream_type)
        with self._lock:
            logid = self._lookup(key)
            self.store.remove_log(logid)
            self.store.meta_delete(key)
            self._logid_cache.pop(key, None)

    def stream_exists(self, name: str,
                      stream_type: StreamType = StreamType.STREAM) -> bool:
        return self.store.meta_get(self._key(name, stream_type)) is not None

    def find_streams(self, stream_type: StreamType = StreamType.STREAM) -> list[str]:
        prefix = _PREFIX[stream_type]
        return [k[len(prefix):] for k in self.store.meta_list(prefix)]

    def stream_meta(self, name: str,
                    stream_type: StreamType = StreamType.STREAM) -> dict:
        raw = self.store.meta_get(self._key(name, stream_type))
        if raw is None:
            raise StreamNotFound(f"stream {name}")
        return json.loads(raw)

    # ---- logid resolution (cached, like Stream.hs:361-369) ----
    def _lookup(self, key: str) -> int:
        logid = self._logid_cache.get(key)
        if logid is not None:
            return logid
        raw = self.store.meta_get(key)
        if raw is None:
            raise StreamNotFound(key)
        logid = json.loads(raw)["logid"]
        self._logid_cache[key] = logid
        return logid

    def get_logid(self, name: str,
                  stream_type: StreamType = StreamType.STREAM) -> int:
        return self._lookup(self._key(name, stream_type))

    # ---- data plane conveniences ----
    def append(self, name: str, payload: bytes, *,
               stream_type: StreamType = StreamType.STREAM) -> int:
        return self.store.append(self.get_logid(name, stream_type), payload)

    def append_batch(self, name: str, payloads, *,
                     stream_type: StreamType = StreamType.STREAM) -> int:
        return self.store.append_batch(self.get_logid(name, stream_type), payloads)

    def ensure_checkpoint_log(self) -> int:
        """Create the reserved checkpoint-store log if absent; returns logid."""
        if not self.store.log_exists(CHECKPOINT_STORE_LOGID):
            try:
                self.store.create_log(CHECKPOINT_STORE_LOGID, LogAttrs())
            except Exception:
                pass  # raced with another creator
        return CHECKPOINT_STORE_LOGID
