// Embedded durable log store — the native bottom layer of the framework.
//
// Capability parity with the reference's LogDevice-backed store layer
// (/root/reference/hstream-store/cbits/hs_logdevice.cpp,
//  cbits/logdevice/hs_writer.cpp, hs_reader.cpp; C surface in
//  include/hs_logdevice.h): integer logids, monotonically increasing
// LSNs, batch appends under one LSN with optional compression, batched
// reads that surface trim gaps exactly once, trim/findTime/isLogEmpty,
// and a small metadata KV (the reference keeps that in LogsConfig +
// VersionedConfigStore — hs_logconfigtypes.cpp,
// hs_versioned_config_store.cpp).
//
// Design (single-node embedded; replication rides above this layer):
//   root/
//     meta.wal            append-only KV oplog, compacted when large
//     logs/<logid>/
//       attrs.json        opaque attrs blob (Python-encoded)
//       trim              decimal trim LSN (atomic rewrite)
//       seg.<n>           data segments, rotated at SEG_BYTES; whole
//                         segments below the trim point are deleted
//
// Batch frame (little-endian):
//   u32 magic 'NSBK' | u32 flags(compression) | u64 lsn | i64 time_ms |
//   u32 nrecs | u32 raw_len | u32 stored_len | u32 crc32(stored) |
//   u32 lens[nrecs] | u8 stored[stored_len]
// A torn tail (crash mid-write) fails magic/crc validation on open and
// the segment is truncated at the last good frame.
//
// Durability: group commit. Appends are written + indexed + visible
// immediately; a flusher thread fsyncs dirty segments every
// sync_interval_ms (default 2) and sync appends wait for their fsync
// ticket — many appender threads amortize one fsync, mirroring the
// reference's completion-callback write path (hs_writer.cpp:36-45).
// The async path (ns_append_async / ns_poll_completions) completes
// tokens only after fsync: the C++ completion queue the Haskell FFI's
// hs_try_putmvar pattern becomes for Python asyncio.

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <filesystem>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace fs = std::filesystem;

namespace {

constexpr uint32_t MAGIC = 0x4E53424B;  // "NSBK"
constexpr int64_t LSN_MIN = 1;
constexpr uint64_t SEG_BYTES_DEFAULT = 64ull << 20;

enum Comp : uint32_t { COMP_NONE = 0, COMP_ZLIB = 1 };

void set_err(char* err, const std::string& msg) {
  if (err) {
    std::snprintf(err, 256, "%s", msg.c_str());
  }
}

struct IndexEntry {
  int64_t lsn;
  int64_t time_ms;
  uint32_t seg;
  uint64_t offset;  // frame start within segment
};

struct Segment {
  uint32_t n = 0;
  int fd = -1;
  uint64_t size = 0;
  bool dirty = false;
};

struct Log {
  std::string attrs_json = "{}";
  std::vector<IndexEntry> index;  // sorted by lsn (append order)
  int64_t next_lsn = LSN_MIN;
  int64_t trim_lsn = 0;
  std::vector<Segment> segs;      // open segments (all of them; fds lazy)
  fs::path dir;
};

struct Completion {
  uint64_t token;
  int64_t lsn;
};

struct PendingAsync {
  uint64_t logid;
  uint64_t token;
  std::vector<std::string> payloads;
  uint32_t compression;
};

struct Store;

struct Reader {
  Store* store;
  // logid -> {next, until}
  std::map<uint64_t, std::pair<int64_t, int64_t>> cursors;
  int64_t timeout_ms = -1;
};

struct Store {
  fs::path root;
  std::mutex mu;
  std::condition_variable data_cv;    // readers wait for appends
  std::condition_variable flush_cv;   // sync appends wait for fsync
  std::condition_variable compl_cv;   // completion-queue consumers
  std::unordered_map<uint64_t, Log> logs;
  std::map<std::string, std::string> meta;
  int meta_fd = -1;
  uint64_t meta_wal_bytes = 0;
  uint64_t seg_bytes = SEG_BYTES_DEFAULT;

  // group commit
  std::thread flusher;
  std::thread async_worker;
  std::atomic<bool> stopping{false};
  int64_t sync_interval_ms = 2;
  uint64_t write_seq = 0;    // bumped per append
  uint64_t flushed_seq = 0;  // appends with seq <= this are fsynced
  std::deque<PendingAsync> async_q;
  std::condition_variable async_cv;
  std::deque<Completion> completions;

  ~Store() { shutdown(); }

  void shutdown() {
    {
      std::lock_guard<std::mutex> g(mu);
      if (stopping.exchange(true)) return;
    }
    async_cv.notify_all();
    flush_cv.notify_all();
    if (flusher.joinable()) flusher.join();
    if (async_worker.joinable()) async_worker.join();
    std::lock_guard<std::mutex> g(mu);
    flush_locked();
    for (auto& [id, log] : logs)
      for (auto& s : log.segs)
        if (s.fd >= 0) ::close(s.fd);
    if (meta_fd >= 0) ::close(meta_fd);
    meta_fd = -1;
  }

  // ---- helpers (all called with mu held unless noted) ----

  Log* get(uint64_t logid) {
    auto it = logs.find(logid);
    return it == logs.end() ? nullptr : &it->second;
  }

  Segment* active_seg(Log& log) {
    if (log.segs.empty()) {
      add_segment(log, 0);
    }
    return &log.segs.back();
  }

  void add_segment(Log& log, uint32_t n) {
    Segment s;
    s.n = n;
    fs::path p = log.dir / ("seg." + std::to_string(n));
    s.fd = ::open(p.c_str(), O_RDWR | O_CREAT | O_APPEND, 0644);
    s.size = s.fd >= 0 ? (uint64_t)::lseek(s.fd, 0, SEEK_END) : 0;
    log.segs.push_back(s);
  }

  void flush_locked() {
    for (auto& [id, log] : logs)
      for (auto& s : log.segs)
        if (s.dirty && s.fd >= 0) {
          ::fsync(s.fd);
          s.dirty = false;
        }
    flushed_seq = write_seq;
  }

  void flusher_main() {
    std::unique_lock<std::mutex> lk(mu);
    while (!stopping.load()) {
      flush_cv.wait_for(lk, std::chrono::milliseconds(sync_interval_ms));
      if (flushed_seq != write_seq) {
        flush_locked();
        flush_cv.notify_all();
        compl_cv.notify_all();
      }
    }
  }

  void async_main() {
    std::unique_lock<std::mutex> lk(mu);
    while (true) {
      async_cv.wait(lk, [&] { return stopping.load() || !async_q.empty(); });
      if (stopping.load() && async_q.empty()) return;
      PendingAsync job = std::move(async_q.front());
      async_q.pop_front();
      std::vector<const uint8_t*> ptrs;
      std::vector<uint32_t> lens;
      for (auto& p : job.payloads) {
        ptrs.push_back(reinterpret_cast<const uint8_t*>(p.data()));
        lens.push_back((uint32_t)p.size());
      }
      char err[256];
      int64_t lsn = append_locked(job.logid, ptrs, lens, job.compression,
                                  err);
      uint64_t my_seq = write_seq;
      // complete only after the frame is fsynced (group commit)
      while (!stopping.load() && lsn > 0 && flushed_seq < my_seq)
        flush_cv.wait(lk);
      completions.push_back({job.token, lsn});
      compl_cv.notify_all();
    }
  }

  int64_t append_locked(uint64_t logid,
                        const std::vector<const uint8_t*>& ptrs,
                        const std::vector<uint32_t>& lens,
                        uint32_t compression, char* err,
                        int64_t force_time_ms = 0) {
    Log* log = get(logid);
    if (!log) {
      set_err(err, "log not found");
      return -1;
    }
    uint32_t nrecs = (uint32_t)ptrs.size();
    if (nrecs == 0) {
      set_err(err, "empty batch");
      return -1;
    }
    uint64_t raw_len = 0;
    for (auto l : lens) raw_len += l;
    std::string raw;
    raw.reserve(raw_len);
    for (uint32_t i = 0; i < nrecs; i++)
      raw.append(reinterpret_cast<const char*>(ptrs[i]), lens[i]);

    std::string stored;
    uint32_t flags = COMP_NONE;
    if (compression == COMP_ZLIB && raw_len > 0) {
      uLongf bound = compressBound(raw.size());
      stored.resize(bound);
      if (compress2(reinterpret_cast<Bytef*>(&stored[0]), &bound,
                    reinterpret_cast<const Bytef*>(raw.data()), raw.size(),
                    Z_BEST_SPEED) == Z_OK && bound < raw.size()) {
        stored.resize(bound);
        flags = COMP_ZLIB;
      } else {
        stored = raw;
      }
    } else {
      stored = raw;
    }

    int64_t now_ms = force_time_ms;
    if (now_ms == 0)  // 0 = stamp locally; replication passes the
                      // leader's stamp so replicas agree on find_time
      now_ms = (int64_t)std::chrono::duration_cast<
          std::chrono::milliseconds>(std::chrono::system_clock::now()
                                         .time_since_epoch()).count();
    int64_t lsn = log->next_lsn++;
    uint32_t crc = crc32(0, reinterpret_cast<const Bytef*>(stored.data()),
                         stored.size());

    std::string frame;
    frame.reserve(40 + 4 * nrecs + stored.size());
    auto put32 = [&](uint32_t v) { frame.append((char*)&v, 4); };
    auto put64 = [&](uint64_t v) { frame.append((char*)&v, 8); };
    put32(MAGIC);
    put32(flags);
    put64((uint64_t)lsn);
    put64((uint64_t)now_ms);
    put32(nrecs);
    put32((uint32_t)raw_len);
    put32((uint32_t)stored.size());
    put32(crc);
    for (auto l : lens) put32(l);
    frame.append(stored);

    Segment* seg = active_seg(*log);
    if (seg->fd < 0) {
      set_err(err, "segment open failed");
      log->next_lsn--;
      return -1;
    }
    if (seg->size >= seg_bytes) {
      add_segment(*log, seg->n + 1);
      seg = &log->segs.back();
      if (seg->fd < 0) {
        set_err(err, "segment rotate failed");
        log->next_lsn--;
        return -1;
      }
    }
    uint64_t off = seg->size;
    ssize_t w = ::write(seg->fd, frame.data(), frame.size());
    if (w != (ssize_t)frame.size()) {
      // undo partial write so the tail stays frame-aligned
      if (w > 0) {
        if (::ftruncate(seg->fd, (off_t)off) != 0) {
          // can't recover alignment; next open() will truncate the torn
          // frame via crc validation
        }
      }
      set_err(err, "short write");
      log->next_lsn--;
      return -1;
    }
    seg->size += frame.size();
    seg->dirty = true;
    log->index.push_back({lsn, now_ms, seg->n, off});
    write_seq++;
    data_cv.notify_all();
    return lsn;
  }

  // wait (mu held) until the current write_seq is fsynced
  void wait_durable(std::unique_lock<std::mutex>& lk) {
    uint64_t my_seq = write_seq;
    flush_cv.notify_all();  // nudge the flusher
    while (!stopping.load() && flushed_seq < my_seq) flush_cv.wait(lk);
  }

  bool read_frame(Log& log, const IndexEntry& e, std::string* stored,
                  std::vector<uint32_t>* lens, int64_t* time_ms,
                  uint32_t* flags, uint32_t* raw_len) {
    Segment* seg = nullptr;
    for (auto& s : log.segs)
      if (s.n == e.seg) seg = &s;
    if (!seg || seg->fd < 0) return false;
    uint8_t hdr[40];
    if (::pread(seg->fd, hdr, 40, (off_t)e.offset) != 40) return false;
    uint32_t magic, nrecs, stored_len, crc;
    std::memcpy(&magic, hdr, 4);
    std::memcpy(flags, hdr + 4, 4);
    std::memcpy(time_ms, hdr + 16, 8);
    std::memcpy(&nrecs, hdr + 24, 4);
    std::memcpy(raw_len, hdr + 28, 4);
    std::memcpy(&stored_len, hdr + 32, 4);
    std::memcpy(&crc, hdr + 36, 4);
    if (magic != MAGIC) return false;
    lens->resize(nrecs);
    if (nrecs && ::pread(seg->fd, lens->data(), 4ull * nrecs,
                         (off_t)(e.offset + 40)) != (ssize_t)(4ull * nrecs))
      return false;
    stored->resize(stored_len);
    if (stored_len &&
        ::pread(seg->fd, &(*stored)[0], stored_len,
                (off_t)(e.offset + 40 + 4ull * nrecs)) != (ssize_t)stored_len)
      return false;
    return crc32(0, reinterpret_cast<const Bytef*>(stored->data()),
                 stored->size()) == crc;
  }

  // ---- meta WAL ----

  void meta_append(uint8_t op, const std::string& key,
                   const std::string& val) {
    if (meta_fd < 0) return;
    std::string rec;
    uint32_t klen = (uint32_t)key.size(), vlen = (uint32_t)val.size();
    rec.push_back((char)op);
    rec.append((char*)&klen, 4);
    rec.append((char*)&vlen, 4);
    rec.append(key);
    rec.append(val);
    if (::write(meta_fd, rec.data(), rec.size()) == (ssize_t)rec.size()) {
      ::fsync(meta_fd);
      meta_wal_bytes += rec.size();
    }
    if (meta_wal_bytes > (4u << 20)) meta_compact();
  }

  void meta_compact() {
    fs::path tmp = root / "meta.wal.tmp";
    int fd = ::open(tmp.c_str(), O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return;
    uint64_t total = 0;
    for (auto& [k, v] : meta) {
      std::string rec;
      uint32_t klen = (uint32_t)k.size(), vlen = (uint32_t)v.size();
      rec.push_back((char)1);
      rec.append((char*)&klen, 4);
      rec.append((char*)&vlen, 4);
      rec.append(k);
      rec.append(v);
      if (::write(fd, rec.data(), rec.size()) != (ssize_t)rec.size()) {
        ::close(fd);
        return;
      }
      total += rec.size();
    }
    ::fsync(fd);
    ::close(fd);
    fs::rename(tmp, root / "meta.wal");
    if (meta_fd >= 0) ::close(meta_fd);
    meta_fd = ::open((root / "meta.wal").c_str(),
                     O_WRONLY | O_APPEND, 0644);
    meta_wal_bytes = total;
  }

  void meta_load() {
    fs::path p = root / "meta.wal";
    FILE* f = std::fopen(p.c_str(), "rb");
    if (f) {
      while (true) {
        uint8_t op;
        uint32_t klen, vlen;
        if (std::fread(&op, 1, 1, f) != 1) break;
        if (std::fread(&klen, 4, 1, f) != 1) break;
        if (std::fread(&vlen, 4, 1, f) != 1) break;
        if (klen > (64u << 20) || vlen > (64u << 20)) break;  // corrupt
        std::string k(klen, '\0'), v(vlen, '\0');
        if (klen && std::fread(&k[0], 1, klen, f) != klen) break;
        if (vlen && std::fread(&v[0], 1, vlen, f) != vlen) break;
        meta_wal_bytes += 9 + klen + vlen;
        if (op == 1)
          meta[k] = v;
        else
          meta.erase(k);
      }
      std::fclose(f);
    }
    meta_fd = ::open(p.c_str(), O_WRONLY | O_CREAT | O_APPEND, 0644);
  }

  // ---- open/recovery ----

  void load_log(uint64_t logid, const fs::path& dir) {
    Log log;
    log.dir = dir;
    {
      FILE* f = std::fopen((dir / "attrs.json").c_str(), "rb");
      if (f) {
        char buf[8192];
        size_t n = std::fread(buf, 1, sizeof(buf), f);
        log.attrs_json.assign(buf, n);
        std::fclose(f);
      }
    }
    {
      FILE* f = std::fopen((dir / "trim").c_str(), "rb");
      if (f) {
        long long t = 0;
        if (std::fscanf(f, "%lld", &t) == 1) log.trim_lsn = t;
        std::fclose(f);
      }
    }
    // discover segments in order
    std::vector<uint32_t> seg_ns;
    for (auto& de : fs::directory_iterator(dir)) {
      std::string name = de.path().filename().string();
      if (name.rfind("seg.", 0) == 0)
        seg_ns.push_back((uint32_t)std::stoul(name.substr(4)));
    }
    std::sort(seg_ns.begin(), seg_ns.end());
    for (uint32_t n : seg_ns) {
      add_segment(log, n);
      Segment& seg = log.segs.back();
      if (seg.fd < 0) continue;
      // scan + validate frames; truncate at first bad frame
      uint64_t off = 0;
      uint64_t fsize = seg.size;
      while (off + 40 <= fsize) {
        uint8_t hdr[40];
        if (::pread(seg.fd, hdr, 40, (off_t)off) != 40) break;
        uint32_t magic, nrecs, stored_len, crc;
        uint64_t lsn;
        int64_t tm;
        std::memcpy(&magic, hdr, 4);
        std::memcpy(&lsn, hdr + 8, 8);
        std::memcpy(&tm, hdr + 16, 8);
        std::memcpy(&nrecs, hdr + 24, 4);
        std::memcpy(&stored_len, hdr + 32, 4);
        std::memcpy(&crc, hdr + 36, 4);
        if (magic != MAGIC || nrecs > (16u << 20)) break;
        uint64_t frame_len = 40 + 4ull * nrecs + stored_len;
        if (off + frame_len > fsize) break;  // torn tail
        std::string stored(stored_len, '\0');
        if (stored_len &&
            ::pread(seg.fd, &stored[0], stored_len,
                    (off_t)(off + 40 + 4ull * nrecs)) != (ssize_t)stored_len)
          break;
        if (crc32(0, reinterpret_cast<const Bytef*>(stored.data()),
                  stored.size()) != crc)
          break;
        log.index.push_back({(int64_t)lsn, tm, seg.n, off});
        log.next_lsn = std::max(log.next_lsn, (int64_t)lsn + 1);
        off += frame_len;
      }
      if (off < fsize) {
        // torn tail from a crash: truncate to the last good frame
        if (::ftruncate(seg.fd, (off_t)off) == 0) seg.size = off;
        // reposition append offset (O_APPEND handles it)
      }
    }
    log.next_lsn = std::max(log.next_lsn, log.trim_lsn + 1);
    // drop index entries at/below the persisted trim point (their frames
    // may still be in not-yet-reclaimed segments)
    if (log.trim_lsn > 0) {
      auto it = std::upper_bound(
          log.index.begin(), log.index.end(), log.trim_lsn,
          [](int64_t v, const IndexEntry& e) { return v < e.lsn; });
      log.index.erase(log.index.begin(), it);
    }
    logs.emplace(logid, std::move(log));
  }

  void persist_trim(Log& log) {
    fs::path tmp = log.dir / "trim.tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return;
    std::fprintf(f, "%lld", (long long)log.trim_lsn);
    std::fflush(f);
    ::fsync(fileno(f));
    std::fclose(f);
    fs::rename(tmp, log.dir / "trim");
  }
};

// serialize one read result into out; returns bytes needed (written if fits)
size_t emit_batch(uint8_t* out, size_t cap, size_t off, uint64_t logid,
                  int64_t lsn, int64_t time_ms,
                  const std::vector<uint32_t>& lens,
                  const std::string& raw) {
  size_t need = 1 + 8 + 8 + 8 + 4 + 4ull * lens.size() + raw.size();
  if (off + need <= cap) {
    uint8_t* p = out + off;
    *p++ = 0;
    std::memcpy(p, &logid, 8); p += 8;
    std::memcpy(p, &lsn, 8); p += 8;
    std::memcpy(p, &time_ms, 8); p += 8;
    uint32_t n = (uint32_t)lens.size();
    std::memcpy(p, &n, 4); p += 4;
    std::memcpy(p, lens.data(), 4ull * n); p += 4ull * n;
    std::memcpy(p, raw.data(), raw.size());
  }
  return need;
}

size_t emit_gap(uint8_t* out, size_t cap, size_t off, uint64_t logid,
                uint8_t gap_type, int64_t lo, int64_t hi) {
  size_t need = 1 + 8 + 1 + 8 + 8;
  if (off + need <= cap) {
    uint8_t* p = out + off;
    *p++ = 1;
    std::memcpy(p, &logid, 8); p += 8;
    *p++ = gap_type;
    std::memcpy(p, &lo, 8); p += 8;
    std::memcpy(p, &hi, 8);
  }
  return need;
}

}  // namespace

extern "C" {

void* ns_open(const char* root, char* err) {
  auto* st = new Store();
  st->root = root;
  std::error_code ec;
  fs::create_directories(st->root / "logs", ec);
  if (ec) {
    set_err(err, "create_directories: " + ec.message());
    delete st;
    return nullptr;
  }
  st->meta_load();
  if (st->meta_fd < 0) {
    set_err(err, "meta.wal open failed");
    delete st;
    return nullptr;
  }
  for (auto& de : fs::directory_iterator(st->root / "logs")) {
    if (!de.is_directory()) continue;
    try {
      uint64_t logid = std::stoull(de.path().filename().string());
      st->load_log(logid, de.path());
    } catch (...) {
      // non-numeric dir: ignore
    }
  }
  st->flusher = std::thread([st] { st->flusher_main(); });
  st->async_worker = std::thread([st] { st->async_main(); });
  return st;
}

void ns_close(void* h) {
  auto* st = static_cast<Store*>(h);
  st->shutdown();
  delete st;
}

void ns_set_sync_interval(void* h, int64_t ms) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  st->sync_interval_ms = ms < 0 ? 0 : ms;
}

void ns_set_seg_bytes(void* h, uint64_t n) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  st->seg_bytes = n < (1u << 16) ? (1u << 16) : n;
}

int ns_create_log(void* h, uint64_t logid, const char* attrs_json,
                  char* err) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  if (st->get(logid)) {
    set_err(err, "log exists");
    return -1;
  }
  fs::path dir = st->root / "logs" / std::to_string(logid);
  std::error_code ec;
  fs::create_directories(dir, ec);
  if (ec) {
    set_err(err, ec.message());
    return -1;
  }
  FILE* f = std::fopen((dir / "attrs.json").c_str(), "wb");
  if (f) {
    std::fputs(attrs_json ? attrs_json : "{}", f);
    std::fclose(f);
  }
  Log log;
  log.dir = dir;
  log.attrs_json = attrs_json ? attrs_json : "{}";
  st->logs.emplace(logid, std::move(log));
  return 0;
}

int ns_remove_log(void* h, uint64_t logid, char* err) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) {
    set_err(err, "log not found");
    return -1;
  }
  for (auto& s : log->segs)
    if (s.fd >= 0) ::close(s.fd);
  std::error_code ec;
  fs::remove_all(log->dir, ec);
  st->logs.erase(logid);
  return 0;
}

int ns_log_exists(void* h, uint64_t logid) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  return st->get(logid) ? 1 : 0;
}

int64_t ns_list_logs(void* h, uint64_t* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  int64_t n = 0;
  for (auto& [id, log] : st->logs) {
    if (n < cap) out[n] = id;
    n++;
  }
  return n;
}

int64_t ns_log_attrs(void* h, uint64_t logid, char* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) return -1;
  int64_t need = (int64_t)log->attrs_json.size();
  if (need <= cap) std::memcpy(out, log->attrs_json.data(), need);
  return need;
}

int64_t ns_append_batch(void* h, uint64_t logid, const uint8_t* buf,
                        const uint32_t* lens, uint32_t nrecs,
                        int compression, int durable, char* err,
                        int64_t time_ms) {
  auto* st = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(st->mu);
  std::vector<const uint8_t*> ptrs(nrecs);
  std::vector<uint32_t> lvec(lens, lens + nrecs);
  uint64_t off = 0;
  for (uint32_t i = 0; i < nrecs; i++) {
    ptrs[i] = buf + off;
    off += lens[i];
  }
  int64_t lsn = st->append_locked(logid, ptrs, lvec,
                                  (uint32_t)compression, err, time_ms);
  if (lsn > 0 && durable) st->wait_durable(lk);
  return lsn;
}

int ns_append_async(void* h, uint64_t logid, const uint8_t* buf,
                    const uint32_t* lens, uint32_t nrecs, int compression,
                    uint64_t token) {
  auto* st = static_cast<Store*>(h);
  PendingAsync job;
  job.logid = logid;
  job.token = token;
  job.compression = (uint32_t)compression;
  uint64_t off = 0;
  for (uint32_t i = 0; i < nrecs; i++) {
    job.payloads.emplace_back(reinterpret_cast<const char*>(buf + off),
                              lens[i]);
    off += lens[i];
  }
  {
    std::lock_guard<std::mutex> g(st->mu);
    if (st->stopping.load()) return -1;
    st->async_q.push_back(std::move(job));
  }
  st->async_cv.notify_one();
  return 0;
}

int64_t ns_poll_completions(void* h, uint64_t* tokens, int64_t* lsns,
                            int64_t maxn, int64_t timeout_ms) {
  auto* st = static_cast<Store*>(h);
  std::unique_lock<std::mutex> lk(st->mu);
  if (st->completions.empty() && timeout_ms != 0) {
    auto pred = [&] {
      return st->stopping.load() || !st->completions.empty();
    };
    if (timeout_ms < 0)
      st->compl_cv.wait(lk, pred);
    else
      st->compl_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                            pred);
  }
  int64_t n = 0;
  while (n < maxn && !st->completions.empty()) {
    tokens[n] = st->completions.front().token;
    lsns[n] = st->completions.front().lsn;
    st->completions.pop_front();
    n++;
  }
  return n;
}

int64_t ns_tail_lsn(void* h, uint64_t logid) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) return -1;
  return log->index.empty() ? 0 : log->index.back().lsn;
}

int ns_trim(void* h, uint64_t logid, int64_t upto, char* err) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) {
    set_err(err, "log not found");
    return -1;
  }
  auto it = std::upper_bound(
      log->index.begin(), log->index.end(), upto,
      [](int64_t v, const IndexEntry& e) { return v < e.lsn; });
  log->index.erase(log->index.begin(), it);
  if (upto > log->trim_lsn) {
    log->trim_lsn = upto;
    st->persist_trim(*log);
  }
  log->next_lsn = std::max(log->next_lsn, log->trim_lsn + 1);
  // delete whole segments now strictly below the live index
  uint32_t live_min = log->index.empty()
                          ? (log->segs.empty() ? 0 : log->segs.back().n)
                          : log->index.front().seg;
  while (!log->segs.empty() && log->segs.front().n < live_min) {
    Segment& s = log->segs.front();
    if (s.fd >= 0) ::close(s.fd);
    std::error_code ec;
    fs::remove(log->dir / ("seg." + std::to_string(s.n)), ec);
    log->segs.erase(log->segs.begin());
  }
  return 0;
}

int64_t ns_trim_point(void* h, uint64_t logid) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  return log ? log->trim_lsn : -1;
}

int64_t ns_find_time(void* h, uint64_t logid, int64_t ts_ms) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) return -1;
  auto it = std::lower_bound(
      log->index.begin(), log->index.end(), ts_ms,
      [](const IndexEntry& e, int64_t v) { return e.time_ms < v; });
  if (it == log->index.end())
    return log->index.empty() ? log->next_lsn
                              : log->index.back().lsn + 1;
  return it->lsn;
}

int ns_is_log_empty(void* h, uint64_t logid) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  Log* log = st->get(logid);
  if (!log) return -1;
  return log->index.empty() ? 1 : 0;
}

// ---- meta KV ----

int ns_meta_put(void* h, const char* key, const uint8_t* val, int64_t len) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  std::string v(reinterpret_cast<const char*>(val), (size_t)len);
  st->meta[key] = v;
  st->meta_append(1, key, v);
  return 0;
}

int64_t ns_meta_get(void* h, const char* key, uint8_t* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  auto it = st->meta.find(key);
  if (it == st->meta.end()) return -1;
  int64_t need = (int64_t)it->second.size();
  if (need <= cap) std::memcpy(out, it->second.data(), need);
  return need;
}

int ns_meta_delete(void* h, const char* key) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  st->meta.erase(key);
  st->meta_append(0, key, "");
  return 0;
}

int64_t ns_meta_list(void* h, const char* prefix, char* out, int64_t cap) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  std::string joined;
  std::string pfx = prefix;
  for (auto it = st->meta.lower_bound(pfx); it != st->meta.end(); ++it) {
    if (it->first.compare(0, pfx.size(), pfx) != 0) break;
    if (!joined.empty()) joined.push_back('\n');
    joined.append(it->first);
  }
  int64_t need = (int64_t)joined.size();
  if (need <= cap) std::memcpy(out, joined.data(), need);
  return need;
}

int ns_meta_cas(void* h, const char* key, const uint8_t* exp,
                int64_t explen, const uint8_t* val, int64_t vlen) {
  auto* st = static_cast<Store*>(h);
  std::lock_guard<std::mutex> g(st->mu);
  auto it = st->meta.find(key);
  if (explen < 0) {
    if (it != st->meta.end()) return 0;
  } else {
    std::string e(reinterpret_cast<const char*>(exp), (size_t)explen);
    if (it == st->meta.end() || it->second != e) return 0;
  }
  std::string v(reinterpret_cast<const char*>(val), (size_t)vlen);
  st->meta[key] = v;
  st->meta_append(1, key, v);
  return 1;
}

// ---- reader ----

void* ns_reader_new(void* h) {
  auto* r = new Reader();
  r->store = static_cast<Store*>(h);
  return r;
}

void ns_reader_free(void* rh) { delete static_cast<Reader*>(rh); }

int ns_reader_start(void* rh, uint64_t logid, int64_t from, int64_t until) {
  auto* r = static_cast<Reader*>(rh);
  std::lock_guard<std::mutex> g(r->store->mu);
  if (!r->store->get(logid)) return -1;
  r->cursors[logid] = {std::max(from, LSN_MIN), until};
  return 0;
}

int ns_reader_stop(void* rh, uint64_t logid) {
  auto* r = static_cast<Reader*>(rh);
  std::lock_guard<std::mutex> g(r->store->mu);
  r->cursors.erase(logid);
  return 0;
}

int ns_reader_is_reading(void* rh, uint64_t logid) {
  auto* r = static_cast<Reader*>(rh);
  std::lock_guard<std::mutex> g(r->store->mu);
  return r->cursors.count(logid) ? 1 : 0;
}

void ns_reader_set_timeout(void* rh, int64_t ms) {
  auto* r = static_cast<Reader*>(rh);
  std::lock_guard<std::mutex> g(r->store->mu);
  r->timeout_ms = ms;
}

// Serialized results into out (see emit_batch/emit_gap). Returns bytes
// written; 0 = timeout with nothing available; -need if the FIRST item
// alone exceeds cap (caller grows the buffer and retries).
int64_t ns_reader_read(void* rh, int64_t max_records, uint8_t* out,
                       int64_t cap) {
  auto* r = static_cast<Reader*>(rh);
  Store* st = r->store;
  std::unique_lock<std::mutex> lk(st->mu);

  auto poll = [&](size_t* produced) -> size_t {
    size_t off = 0;
    *produced = 0;
    for (auto& [logid, cur] : r->cursors) {
      auto& [nxt, until] = cur;
      if (nxt > until) continue;
      Log* log = st->get(logid);
      if (!log) continue;
      if (log->trim_lsn >= nxt) {
        int64_t hi = std::min(log->trim_lsn, until);
        size_t need = emit_gap(out, cap, off, logid, 0, nxt, hi);
        if (off + need > (size_t)cap)
          return *produced == 0 ? (size_t)-1 : off;
        off += need;
        nxt = hi + 1;
        (*produced)++;
        if ((int64_t)*produced >= max_records) return off;
      }
      auto it = std::lower_bound(
          log->index.begin(), log->index.end(), nxt,
          [](const IndexEntry& e, int64_t v) { return e.lsn < v; });
      for (; it != log->index.end(); ++it) {
        if (it->lsn > until || (int64_t)*produced >= max_records) break;
        std::string stored;
        std::vector<uint32_t> lens;
        int64_t tm;
        uint32_t flags, raw_len;
        if (!st->read_frame(*log, *it, &stored, &lens, &tm, &flags,
                            &raw_len))
          break;
        std::string raw;
        if (flags == COMP_ZLIB) {
          raw.resize(raw_len);
          uLongf dlen = raw_len;
          if (uncompress(reinterpret_cast<Bytef*>(&raw[0]), &dlen,
                         reinterpret_cast<const Bytef*>(stored.data()),
                         stored.size()) != Z_OK)
            break;
        } else {
          raw = std::move(stored);
        }
        size_t need = emit_batch(out, cap, off, logid, it->lsn, tm, lens,
                                 raw);
        if (off + need > (size_t)cap)
          return *produced == 0 ? (size_t)-1 : off;
        off += need;
        nxt = it->lsn + 1;
        (*produced)++;
      }
      if ((int64_t)*produced >= max_records) break;
    }
    return off;
  };

  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(
                      r->timeout_ms < 0 ? 0 : r->timeout_ms);
  while (true) {
    size_t produced = 0;
    size_t off = poll(&produced);
    if (off == (size_t)-1) {
      // first item doesn't fit: report required size for ONE item pass
      // (conservative: ask for 2x cap)
      return -(cap * 2);
    }
    if (produced > 0) return (int64_t)off;
    if (r->timeout_ms == 0) return 0;
    if (r->timeout_ms < 0) {
      st->data_cv.wait(lk);
    } else {
      if (st->data_cv.wait_until(lk, deadline) == std::cv_status::timeout) {
        size_t p2 = 0;
        size_t o2 = poll(&p2);
        return o2 == (size_t)-1 ? -(cap * 2) : (int64_t)o2;
      }
    }
  }
}

}  // extern "C"
