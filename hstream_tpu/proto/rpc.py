"""gRPC service glue generated dynamically from the proto descriptor.

The image has `protoc` but not the grpc python plugin, so instead of
checked-in *_pb2_grpc.py stubs the servicer registration and client stub
are built from `api_pb2.DESCRIPTOR` at import time — same wire format,
same `/hstream.tpu.HStreamApi/<Method>` paths a generated stub would use
(reference service surface: HStreamApi.proto:13-84, 35 RPCs).
"""

from __future__ import annotations

import grpc
from google.protobuf import message_factory

from hstream_tpu.proto import api_pb2

SERVICE_NAME = "hstream.tpu.HStreamApi"

_SERVICE = api_pb2.DESCRIPTOR.services_by_name["HStreamApi"]


def _serializer(cls):
    return lambda msg: msg.SerializeToString()


def method_names() -> list[str]:
    return [m.name for m in _SERVICE.methods]


def add_service_to_server(service_desc, servicer, server) -> None:
    """Register `servicer` (one method per RPC name) for any service
    descriptor on a grpc.Server."""
    full_name = service_desc.full_name
    handlers = {}
    for m in service_desc.methods:
        in_cls = message_factory.GetMessageClass(m.input_type)
        out_cls = message_factory.GetMessageClass(m.output_type)
        behavior = getattr(servicer, m.name)
        deser = in_cls.FromString
        ser = _serializer(out_cls)
        if m.client_streaming and m.server_streaming:
            h = grpc.stream_stream_rpc_method_handler(behavior, deser, ser)
        elif m.server_streaming:
            h = grpc.unary_stream_rpc_method_handler(behavior, deser, ser)
        elif m.client_streaming:
            h = grpc.stream_unary_rpc_method_handler(behavior, deser, ser)
        else:
            h = grpc.unary_unary_rpc_method_handler(behavior, deser, ser)
        handlers[m.name] = h
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(full_name, handlers),))


class ServiceStub:
    """Client stub for any service descriptor (same wire paths a
    generated stub would use)."""

    def __init__(self, service_desc, channel: grpc.Channel):
        for m in service_desc.methods:
            in_cls = message_factory.GetMessageClass(m.input_type)
            out_cls = message_factory.GetMessageClass(m.output_type)
            path = f"/{service_desc.full_name}/{m.name}"
            ser = _serializer(in_cls)
            deser = out_cls.FromString
            if m.client_streaming and m.server_streaming:
                fn = channel.stream_stream(path, request_serializer=ser,
                                           response_deserializer=deser)
            elif m.server_streaming:
                fn = channel.unary_stream(path, request_serializer=ser,
                                          response_deserializer=deser)
            elif m.client_streaming:
                fn = channel.stream_unary(path, request_serializer=ser,
                                          response_deserializer=deser)
            else:
                fn = channel.unary_unary(path, request_serializer=ser,
                                         response_deserializer=deser)
            setattr(self, m.name, fn)


REPLICA_SERVICE = api_pb2.DESCRIPTOR.services_by_name["StoreReplica"]


def add_store_replica_to_server(servicer, server) -> None:
    add_service_to_server(REPLICA_SERVICE, servicer, server)


class StoreReplicaStub(ServiceStub):
    def __init__(self, channel: grpc.Channel):
        super().__init__(REPLICA_SERVICE, channel)


def add_hstream_api_to_server(servicer, server) -> None:
    """Register `servicer` (an object with one method per RPC name) on a
    grpc.Server."""
    add_service_to_server(_SERVICE, servicer, server)


class HStreamApiStub(ServiceStub):
    """Client stub: one callable per RPC, built from the descriptor."""

    def __init__(self, channel: grpc.Channel):
        super().__init__(_SERVICE, channel)
