"""Generated protobuf messages for the HStreamApi surface.

`api_pb2` is generated from `api.proto` by `protoc --python_out`; the
generated file is checked in so tests do not require protoc. Regenerate
with:  protoc --python_out=hstream_tpu/proto --proto_path=hstream_tpu/proto api.proto
"""

from hstream_tpu.proto import api_pb2

__all__ = ["api_pb2"]
