"""Semantic validation of the raw AST.

The reference splits this into Validate.hs (~750 LoC of pre-refine
checks: aggregate placement, alias uniqueness, join-condition shape,
interval sanity, arity — Validate.hs:32-60) and AST.hs's `Refine`
typeclass. Here the parser already produces typed nodes, so refine =
validate + light normalization and returns the same AST. The
stream-schema check (unknown columns vs sampled records) lives in the
server at query creation (handlers._check_columns_against_stream),
since only the server can see the data.
"""

from __future__ import annotations

from hstream_tpu.common.errors import SQLValidateError
from hstream_tpu.engine.expr import BinOp, Col, Expr, UnOp
from hstream_tpu.sql import ast
from hstream_tpu.sql.parser import parse

# aggregates that require an argument (COUNT(*) is the only nullary)
_NEEDS_ARG = {
    ast.SetFuncKind.COUNT, ast.SetFuncKind.SUM, ast.SetFuncKind.AVG,
    ast.SetFuncKind.MIN, ast.SetFuncKind.MAX,
    ast.SetFuncKind.APPROX_COUNT_DISTINCT,
    ast.SetFuncKind.APPROX_QUANTILE, ast.SetFuncKind.TOPK,
    ast.SetFuncKind.TOPKDISTINCT,
}


def _set_funcs(e: Expr) -> list[ast.SetFunc]:
    if isinstance(e, ast.SetFunc):
        inner = _set_funcs(e.arg) if e.arg is not None else []
        return [e] + inner
    if isinstance(e, BinOp):
        return _set_funcs(e.left) + _set_funcs(e.right)
    if isinstance(e, UnOp):
        return _set_funcs(e.operand)
    return []


def columns_outside_aggs(e: Expr) -> set[str]:
    """Bare (non-aggregated) column names referenced by an expression.
    Same traversal as engine.expr.columns_of, which treats SetFunc as a
    leaf (it matches none of Col/BinOp/UnOp)."""
    from hstream_tpu.engine.expr import columns_of

    return columns_of(e)


def _validate_interval(iv, what: str) -> None:
    if iv is not None and iv.ms <= 0:
        raise SQLValidateError(f"{what} must be a positive interval")


def _validate_window(w: ast.WindowExpr) -> None:
    _validate_interval(w.size, "window size")
    if w.grace is not None and w.grace.ms < 0:
        raise SQLValidateError("GRACE BY must be non-negative")
    if w.kind == ast.WindowKind.HOPPING:
        if w.advance is None:
            raise SQLValidateError("HOPPING window needs an advance")
        _validate_interval(w.advance, "HOPPING advance")
        if w.size.ms % w.advance.ms != 0:
            # an advance larger than the size also fails this (size %
            # advance == size != 0), so oversize advances are covered
            raise SQLValidateError(
                "HOPPING size must be a multiple of advance")


def _validate_aggs(items: list[ast.SelectItem],
                   having: Expr | None) -> None:
    exprs = [i.expr for i in items]
    if having is not None:
        exprs.append(having)
    for e in exprs:
        for sf in _set_funcs(e):
            if sf.arg is not None and _set_funcs(sf.arg):
                raise SQLValidateError("nested aggregate functions")
            if sf.kind in _NEEDS_ARG and sf.arg is None:
                raise SQLValidateError(
                    f"{sf.kind.value} requires an argument")
            if sf.kind == ast.SetFuncKind.COUNT_ALL and sf.arg is not None:
                raise SQLValidateError("COUNT(*) takes no argument")
            if sf.kind == ast.SetFuncKind.APPROX_QUANTILE:
                if not isinstance(sf.arg2, (int, float)) \
                        or isinstance(sf.arg2, bool):
                    raise SQLValidateError(
                        "APPROX_QUANTILE(col, q) needs a numeric "
                        "quantile literal")
                q = float(sf.arg2)
                if not (0.0 <= q <= 1.0):
                    raise SQLValidateError(
                        f"quantile must be in [0, 1], got {q}")
            if sf.kind in (ast.SetFuncKind.TOPK,
                           ast.SetFuncKind.TOPKDISTINCT):
                if not isinstance(sf.arg2, int) \
                        or isinstance(sf.arg2, bool) or sf.arg2 < 1:
                    raise SQLValidateError(
                        "TOPK needs an integer k >= 1")


def _validate_group_consistency(sel: ast.Select) -> None:
    """Non-aggregated select/HAVING columns must be group keys — the
    check whose absence lets aggregates silently run on garbage
    (SELECT city, temp ... GROUP BY city)."""
    if not sel.group_by:
        return
    group_names = {g.name for g in sel.group_by if isinstance(g, Col)}
    for idx, item in enumerate(sel.items or []):
        bare = columns_outside_aggs(item.expr)
        extra = bare - group_names
        if extra:
            raise SQLValidateError(
                f"column(s) {sorted(extra)} in SELECT are neither "
                "aggregated nor in GROUP BY")
    if sel.having is not None:
        extra = columns_outside_aggs(sel.having) - group_names
        # HAVING may also reference select aliases of aggregates
        aliases = {i.alias for i in (sel.items or []) if i.alias}
        extra -= aliases
        if extra:
            raise SQLValidateError(
                f"column(s) {sorted(extra)} in HAVING are neither "
                "aggregated nor in GROUP BY")


def _validate_join(sel: ast.Select) -> None:
    join = sel.join
    if not join.table:
        _validate_interval(join.within, "JOIN WITHIN")
    left_names = {sel.source.name, sel.source.alias} - {None}
    right_names = {join.right.name, join.right.alias} - {None}
    if join.right.name == sel.source.name:
        # joined-row fields are qualified by STREAM name (genJoiner),
        # so both sides of a self-join would collide
        raise SQLValidateError(
            "self-join (same stream on both sides) is not supported")
    if not (left_names.isdisjoint(right_names)):
        raise SQLValidateError(
            "JOIN aliases collide with the other side's name")

    def eqs(e: Expr) -> list[tuple[Expr, Expr]]:
        if isinstance(e, BinOp) and e.op == "AND":
            return eqs(e.left) + eqs(e.right)
        if isinstance(e, BinOp) and e.op == "=":
            return [(e.left, e.right)]
        raise SQLValidateError(
            "JOIN ON must be a conjunction of equality comparisons")

    pairs = eqs(join.on)
    if not pairs:
        raise SQLValidateError("JOIN ON needs at least one equality")
    for a, b in pairs:
        for side in (a, b):
            if isinstance(side, Col) and side.stream is None:
                raise SQLValidateError(
                    "JOIN ON columns must be stream-qualified (s.col)")
        sa = _qualifiers(a)
        sb = _qualifiers(b)
        known = left_names | right_names
        for s in (sa | sb):
            if s not in known:
                raise SQLValidateError(
                    f"unknown stream qualifier {s!r} in JOIN ON")
        if (sa <= left_names) == (sb <= left_names):
            raise SQLValidateError(
                "each JOIN ON equality must relate both sides")


def _qualifiers(e: Expr) -> set[str]:
    if isinstance(e, Col):
        return {e.stream} - {None}
    if isinstance(e, BinOp):
        return _qualifiers(e.left) | _qualifiers(e.right)
    if isinstance(e, UnOp):
        return _qualifiers(e.operand)
    return set()


def _validate_select(sel: ast.Select) -> None:
    # aggregates may not appear in WHERE or GROUP BY (Validate.hs)
    if sel.where is not None and _set_funcs(sel.where):
        raise SQLValidateError("aggregate function not allowed in WHERE")
    for g in sel.group_by:
        if not isinstance(g, Col):
            raise SQLValidateError("GROUP BY supports only column names")
        if _set_funcs(g):
            raise SQLValidateError("aggregate function not allowed in "
                                   "GROUP BY")
    dup = {g.name for g in sel.group_by
           if isinstance(g, Col)
           and sum(1 for h in sel.group_by
                   if isinstance(h, Col) and h.name == g.name) > 1}
    if dup:
        raise SQLValidateError(f"duplicate GROUP BY column(s) {sorted(dup)}")
    items = sel.items or []
    _validate_aggs(items, sel.having)
    # alias uniqueness
    aliases = [i.alias for i in items if i.alias]
    if len(aliases) != len(set(aliases)):
        raise SQLValidateError("duplicate column alias")
    has_agg = any(_set_funcs(i.expr) for i in items)
    if sel.window is not None and not (has_agg or sel.group_by):
        raise SQLValidateError("time window requires GROUP BY / aggregates")
    if has_agg and sel.items is None:
        raise SQLValidateError("SELECT * cannot be combined with aggregates")
    if sel.having is not None and not (has_agg or sel.group_by):
        raise SQLValidateError("HAVING requires GROUP BY / aggregates")
    if sel.group_by and not has_agg:
        raise SQLValidateError(
            "GROUP BY queries need at least one aggregate in SELECT")
    _validate_group_consistency(sel)
    if sel.window is not None:
        _validate_window(sel.window)
    if sel.join is not None:
        _validate_join(sel)


def _validate_insert(stmt: ast.Insert) -> None:
    if stmt.fields is not None:
        if len(stmt.fields) != len(stmt.values):
            raise SQLValidateError(
                f"INSERT has {len(stmt.fields)} column(s) but "
                f"{len(stmt.values)} value(s)")
        if len(set(stmt.fields)) != len(stmt.fields):
            raise SQLValidateError("duplicate INSERT column")


def refine(stmt: ast.Statement) -> ast.Statement:
    """Validate; raises SQLValidateError on semantic errors."""
    if isinstance(stmt, ast.Select):
        _validate_select(stmt)
    elif isinstance(stmt, ast.CreateStream) and stmt.as_select is not None:
        _validate_select(stmt.as_select)
    elif isinstance(stmt, ast.CreateView):
        _validate_select(stmt.select)
        sel = stmt.select
        has_agg = any(_set_funcs(i.expr) for i in (sel.items or []))
        if not has_agg and not sel.group_by:
            raise SQLValidateError(
                "CREATE VIEW requires an aggregation (materialized views "
                "store grouped state)")
    elif isinstance(stmt, ast.Insert):
        _validate_insert(stmt)
    elif isinstance(stmt, ast.Explain):
        refine(stmt.stmt)
    return stmt


def parse_and_refine(sql: str) -> ast.Statement:
    """parse -> validate -> refine (reference Parse.hs:19-30)."""
    return refine(parse(sql))
