"""Semantic validation of the raw AST.

The reference splits this into Validate.hs (pre-refine checks: aggregate
placement, alias uniqueness, join condition shape — Validate.hs:32-60)
and AST.hs's `Refine` typeclass. Here the parser already produces typed
nodes, so refine = validate + light normalization and returns the same
AST.
"""

from __future__ import annotations

from hstream_tpu.common.errors import SQLValidateError
from hstream_tpu.engine.expr import BinOp, Col, Expr, UnOp
from hstream_tpu.sql import ast
from hstream_tpu.sql.parser import parse


def _set_funcs(e: Expr) -> list[ast.SetFunc]:
    if isinstance(e, ast.SetFunc):
        inner = _set_funcs(e.arg) if e.arg is not None else []
        return [e] + inner
    if isinstance(e, BinOp):
        return _set_funcs(e.left) + _set_funcs(e.right)
    if isinstance(e, UnOp):
        return _set_funcs(e.operand)
    return []


def _validate_select(sel: ast.Select) -> None:
    # aggregates may not appear in WHERE (reference Validate.hs)
    if sel.where is not None and _set_funcs(sel.where):
        raise SQLValidateError("aggregate function not allowed in WHERE")
    for g in sel.group_by:
        if not isinstance(g, Col):
            raise SQLValidateError("GROUP BY supports only column names")
        if _set_funcs(g):
            raise SQLValidateError("aggregate function not allowed in "
                                   "GROUP BY")
    # nested aggregates: SUM(COUNT(*)) etc.
    items = sel.items or []
    for item in items:
        for sf in _set_funcs(item.expr):
            if sf.arg is not None and _set_funcs(sf.arg):
                raise SQLValidateError("nested aggregate functions")
    # alias uniqueness
    aliases = [i.alias for i in items if i.alias]
    if len(aliases) != len(set(aliases)):
        raise SQLValidateError("duplicate column alias")
    has_agg = any(_set_funcs(i.expr) for i in items)
    if sel.window is not None and not (has_agg or sel.group_by):
        raise SQLValidateError("time window requires GROUP BY / aggregates")
    if has_agg and sel.items is None:
        raise SQLValidateError("SELECT * cannot be combined with aggregates")
    if sel.having is not None and not (has_agg or sel.group_by):
        raise SQLValidateError("HAVING requires GROUP BY / aggregates")
    if sel.window is not None:
        w = sel.window
        if w.kind == ast.WindowKind.HOPPING:
            if w.advance is None:
                raise SQLValidateError("HOPPING window needs an advance")
            if w.size.ms % w.advance.ms != 0:
                raise SQLValidateError(
                    "HOPPING size must be a multiple of advance")
    if sel.join is not None:
        if not _join_cond_shape_ok(sel.join.on):
            raise SQLValidateError(
                "JOIN condition must be s1.col = s2.col (optionally "
                "AND-ed with filters)")


def _join_cond_shape_ok(on: Expr) -> bool:
    # reference requires an equality on qualified columns at the top
    # (Validate.hs join cond shape); allow col = col possibly under ANDs
    if isinstance(on, BinOp) and on.op == "AND":
        return _join_cond_shape_ok(on.left) or _join_cond_shape_ok(on.right)
    return (isinstance(on, BinOp) and on.op == "="
            and isinstance(on.left, Col) and isinstance(on.right, Col))


def refine(stmt: ast.Statement) -> ast.Statement:
    """Validate; raises SQLValidateError on semantic errors."""
    if isinstance(stmt, ast.Select):
        _validate_select(stmt)
    elif isinstance(stmt, ast.CreateStream) and stmt.as_select is not None:
        _validate_select(stmt.as_select)
    elif isinstance(stmt, ast.CreateView):
        _validate_select(stmt.select)
        sel = stmt.select
        has_agg = any(_set_funcs(i.expr) for i in (sel.items or []))
        if not has_agg and not sel.group_by:
            raise SQLValidateError(
                "CREATE VIEW requires an aggregation (materialized views "
                "store grouped state)")
    elif isinstance(stmt, ast.Explain):
        refine(stmt.stmt)
    return stmt


def parse_and_refine(sql: str) -> ast.Statement:
    """parse -> validate -> refine (reference Parse.hs:19-30)."""
    return refine(parse(sql))
