"""SQL -> plan lowering.

The reference's `streamCodegen` lowers the refined AST into a processor-
DAG builder per plan type (Codegen.hs:109-117, SELECT pipeline
source -> filter -> map/groupBy -> window aggregate -> having -> sink at
Codegen.hs:532-567, with `AggregateComponents` fused across the select
list at Codegen.hs:387-477). Here SELECT lowers to the engine's logical
plan: a FilterNode chain under an AggregateNode whose AggSpecs are the
fused accumulator planes of one lattice; HAVING and post-aggregate
expressions become host-side row operations.
"""

from __future__ import annotations

import json

from hstream_tpu.common.errors import SQLCodegenError
from hstream_tpu.engine.expr import BinOp, Col, Expr, Lit, UnOp
from hstream_tpu.engine.plan import (
    AggKind,
    AggregateNode,
    AggSpec,
    FilterNode,
    ProjectNode,
    SourceNode,
)
from hstream_tpu.engine.types import ColumnType, Schema
from hstream_tpu.engine.window import (
    DEFAULT_GRACE_MS,
    HoppingWindow,
    SessionWindow,
    TumblingWindow,
    WindowSpec,
)
from hstream_tpu.sql import ast, plans
from hstream_tpu.sql.plans import Plan
from hstream_tpu.sql.refine import parse_and_refine

_AGG_KIND = {
    ast.SetFuncKind.COUNT_ALL: AggKind.COUNT_ALL,
    ast.SetFuncKind.COUNT: AggKind.COUNT,
    ast.SetFuncKind.SUM: AggKind.SUM,
    ast.SetFuncKind.AVG: AggKind.AVG,
    ast.SetFuncKind.MIN: AggKind.MIN,
    ast.SetFuncKind.MAX: AggKind.MAX,
    ast.SetFuncKind.APPROX_COUNT_DISTINCT: AggKind.APPROX_COUNT_DISTINCT,
    ast.SetFuncKind.APPROX_QUANTILE: AggKind.APPROX_QUANTILE,
    ast.SetFuncKind.TOPK: AggKind.TOPK,
    ast.SetFuncKind.TOPKDISTINCT: AggKind.TOPK_DISTINCT,
}

_STRINGY_OPS = {"TO_UPPER", "TO_LOWER", "TRIM", "LTRIM", "RTRIM",
                "STRLEN", "REVERSE", "IS_STR"}


def lower_window(w: ast.WindowExpr | None) -> WindowSpec | None:
    if w is None:
        return None
    grace = w.grace.ms if w.grace is not None else DEFAULT_GRACE_MS
    if w.kind == ast.WindowKind.TUMBLING:
        return TumblingWindow(w.size.ms, grace_ms=grace)
    if w.kind == ast.WindowKind.HOPPING:
        return HoppingWindow(w.size.ms, w.advance.ms, grace_ms=grace)
    return SessionWindow(w.size.ms, grace_ms=grace)


class _SchemaInference:
    """Column type inference from expression context (the reference is
    dynamically typed over JSON; a columnar engine needs device dtypes)."""

    def __init__(self) -> None:
        self.types: dict[str, ColumnType] = {}

    def note(self, col: str, t: ColumnType) -> None:
        prev = self.types.get(col)
        if prev is None or (prev == ColumnType.FLOAT
                            and t == ColumnType.STRING):
            self.types[col] = t
        # STRING evidence wins over FLOAT default; first wins otherwise

    def walk(self, e: Expr, want: ColumnType | None = None) -> None:
        if isinstance(e, Col):
            self.note(e.name, want or ColumnType.FLOAT)
        elif isinstance(e, BinOp):
            if e.op in ("=", "<>"):
                if isinstance(e.left, Lit) and isinstance(e.left.value, str):
                    self.walk(e.right, ColumnType.STRING)
                    return
                if isinstance(e.right, Lit) and isinstance(e.right.value,
                                                           str):
                    self.walk(e.left, ColumnType.STRING)
                    return
            self.walk(e.left, None if e.op in ("AND", "OR") else want)
            self.walk(e.right, None if e.op in ("AND", "OR") else want)
        elif isinstance(e, UnOp):
            self.walk(e.operand,
                      ColumnType.STRING if e.op in _STRINGY_OPS else want)
        elif isinstance(e, ast.SetFunc):
            if e.arg is not None:
                self.walk(e.arg, want)


def _default_name(item: ast.SelectItem, idx: int) -> str:
    if item.alias:
        return item.alias
    return item.text or f"col{idx}"


class _AggCollector:
    """Fuses every aggregate call in the select list / HAVING into one
    deduplicated AggSpec list (the reference's fuseAggregateComponents,
    Codegen.hs:387-477), rewriting expressions to reference the aggregate
    output columns."""

    def __init__(self) -> None:
        self.specs: list[AggSpec] = []
        self._by_key: dict[tuple, str] = {}

    def intern(self, sf: ast.SetFunc) -> Col:
        kind = _AGG_KIND.get(sf.kind)
        if kind is None:
            raise SQLCodegenError(f"aggregate {sf.kind.value} not supported")
        key = (kind, sf.arg, sf.arg2)
        name = self._by_key.get(key)
        if name is None:
            name = sf.text or f"agg{len(self.specs)}"
            # keep names unique even if two distinct aggs share SQL text
            existing = {s.out_name for s in self.specs}
            if name in existing:
                name = f"{name}#{len(self.specs)}"
            quantile = k = None
            if kind == AggKind.APPROX_QUANTILE:
                quantile = float(sf.arg2)
            if kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT):
                k = int(sf.arg2)
            self.specs.append(AggSpec(kind=kind, out_name=name,
                                      input=sf.arg, quantile=quantile,
                                      k=k))
            self._by_key[key] = name
        return Col(name)

    def rewrite(self, e: Expr) -> Expr:
        if isinstance(e, ast.SetFunc):
            return self.intern(e)
        if isinstance(e, BinOp):
            return BinOp(e.op, self.rewrite(e.left), self.rewrite(e.right))
        if isinstance(e, UnOp):
            return UnOp(e.op, self.rewrite(e.operand))
        return e


def _flatten_join_refs(sel: ast.Select) -> ast.Select:
    """For JOIN queries, rewrite stream-qualified column refs
    `s.col` -> flat `s.col` names: the joined rows built by
    engine.join.JoinExecutor carry stream-qualified field names exactly
    like the reference's genJoiner (Internal/Codegen.hs:62-67), so
    downstream expressions address them as ordinary flat columns."""
    refs = [sel.source, sel.join.right]
    resolve: dict[str, str] = {}
    for ref in refs:
        resolve[ref.name] = ref.name
        if ref.alias:
            resolve[ref.alias] = ref.name

    def flat(e):
        if isinstance(e, Col):
            if e.stream is None:
                return e
            name = resolve.get(e.stream)
            if name is None:
                raise SQLCodegenError(
                    f"unknown stream qualifier {e.stream!r}")
            return Col(f"{name}.{e.name}")
        if isinstance(e, BinOp):
            return BinOp(e.op, flat(e.left), flat(e.right))
        if isinstance(e, UnOp):
            return UnOp(e.op, flat(e.operand))
        if isinstance(e, ast.SetFunc):
            return ast.SetFunc(e.kind,
                               flat(e.arg) if e.arg is not None else None,
                               e.arg2, e.text)
        return e

    items = None
    if sel.items is not None:
        items = [ast.SelectItem(flat(i.expr), i.alias, i.text)
                 for i in sel.items]
    return ast.Select(
        items=items, source=sel.source, join=sel.join,
        where=flat(sel.where) if sel.where is not None else None,
        group_by=[flat(g) for g in sel.group_by], window=sel.window,
        having=flat(sel.having) if sel.having is not None else None,
        emit_changes=sel.emit_changes)


def lower_select(sel: ast.Select, sql: str = "") -> plans.SelectPlan:
    """SELECT -> engine plan (aggregate or stateless)."""
    if sel.join is not None:
        sel = _flatten_join_refs(sel)
    infer = _SchemaInference()
    if sel.where is not None:
        infer.walk(sel.where)
    for item in (sel.items or []):
        infer.walk(item.expr)

    window = lower_window(sel.window)
    items = sel.items or []
    has_agg = any(isinstance(sf, ast.SetFunc)
                  for i in items for sf in _walk_setfuncs(i.expr))
    grouped = bool(sel.group_by) or window is not None or has_agg

    source = SourceNode(stream=sel.source.name, schema=None)
    node = source
    if sel.where is not None:
        node = FilterNode(node, sel.where)

    if grouped:
        coll = _AggCollector()
        group_names = [g.name for g in sel.group_by
                       if isinstance(g, Col)]
        # One (name, expr) per select item over the aggregate outputs.
        # When every item is a bare aggregate or plain group column with
        # no alias, the executor's natural emission (key cols + agg
        # outputs) already matches — post projections stay empty. Any
        # alias or computed item forces explicit projection of ALL items
        # so the emitted row carries exactly the selected fields.
        projected: list[tuple[str, Expr]] = []
        natural = True
        for idx, item in enumerate(items):
            rewritten = coll.rewrite(item.expr)
            name = _default_name(item, idx)
            bare_agg = (isinstance(item.expr, ast.SetFunc)
                        and item.alias is None)
            plain_group = (isinstance(item.expr, Col)
                           and item.expr.name in group_names
                           and item.alias is None)
            if not (bare_agg or plain_group):
                natural = False
            projected.append((name, rewritten))
        # Explicit projection must still carry the group-key columns:
        # the reference's emitted row always includes the key (the
        # aggregate output is keyed by it — Codegen.hs:479-521), so
        # `SELECT COUNT(*) AS c ... GROUP BY city` emits city too.
        if not natural:
            covered = {e.name for _, e in projected if isinstance(e, Col)}
            key_proj = [(g, Col(g)) for g in group_names
                        if g not in covered]
            projected = key_proj + projected
        having = None
        if sel.having is not None:
            having = coll.rewrite(sel.having)
        if not coll.specs:
            raise SQLCodegenError(
                "GROUP BY queries need at least one aggregate in SELECT")
        node = AggregateNode(
            child=node,
            group_keys=list(sel.group_by),
            window=window,
            aggs=coll.specs,
            having=having,
            post_projections=[] if natural else projected,
        )
    else:
        exprs = [( _default_name(i, n), i.expr) for n, i in enumerate(items)]
        node = ProjectNode(node, exprs) if items else node

    return plans.SelectPlan(
        sql=sql,
        source=sel.source.name,
        node=node,
        schema_req=plans.SchemaRequirement(inferred=dict(infer.types)),
        emit_changes=sel.emit_changes,
        join=sel.join,
        source_alias=sel.source.alias,
    )


def _walk_setfuncs(e: Expr):
    if isinstance(e, ast.SetFunc):
        yield e
        if e.arg is not None:
            yield from _walk_setfuncs(e.arg)
    elif isinstance(e, BinOp):
        yield from _walk_setfuncs(e.left)
        yield from _walk_setfuncs(e.right)
    elif isinstance(e, UnOp):
        yield from _walk_setfuncs(e.operand)


def stream_codegen(sql: str) -> plans.Plan:
    """Text -> plan (the reference's streamCodegen, Codegen.hs:109-110)."""
    stmt = parse_and_refine(sql)
    return _codegen(stmt, sql)


def _codegen(stmt: ast.Statement, sql: str) -> plans.Plan:
    if isinstance(stmt, ast.Select):
        if not stmt.emit_changes:
            # pull query against a materialized view (SelectViewPlan,
            # reference Handler.hs:277-325)
            return plans.SelectViewPlan(sql=sql, view=stmt.source.name,
                                        select=stmt)
        return lower_select(stmt, sql)
    if isinstance(stmt, ast.CreateStream):
        if stmt.as_select is not None:
            return plans.CreateBySelectPlan(
                stream=stmt.name,
                select=lower_select(stmt.as_select, sql),
                options=dict(stmt.options))
        return plans.CreatePlan(stream=stmt.name, options=dict(stmt.options))
    if isinstance(stmt, ast.CreateView):
        return plans.CreateViewPlan(view=stmt.name,
                                    select=lower_select(stmt.select, sql))
    if isinstance(stmt, ast.CreateConnector):
        return plans.CreateSinkConnectorPlan(
            name=stmt.name, options=dict(stmt.options),
            if_not_exist=stmt.if_not_exist)
    if isinstance(stmt, ast.Insert):
        if stmt.fields is not None:
            return plans.InsertPlan(
                stream=stmt.stream,
                payload=dict(zip(stmt.fields, stmt.values)),
                raw_payload=None)
        if stmt.json_payload is not None:
            try:
                obj = json.loads(stmt.json_payload)
            except json.JSONDecodeError as e:
                raise SQLCodegenError(f"bad JSON payload: {e}") from e
            if not isinstance(obj, dict):
                raise SQLCodegenError("INSERT JSON payload must be an object")
            return plans.InsertPlan(stream=stmt.stream, payload=obj,
                                    raw_payload=None)
        return plans.InsertPlan(
            stream=stmt.stream, payload=None,
            raw_payload=stmt.binary_payload.encode("utf-8"))
    if isinstance(stmt, ast.Show):
        return plans.ShowPlan(what=stmt.what)
    if isinstance(stmt, ast.Drop):
        return plans.DropPlan(what=stmt.what, name=stmt.name,
                              if_exists=stmt.if_exists)
    if isinstance(stmt, ast.Terminate):
        return plans.TerminatePlan(query_id=stmt.query_id)
    if isinstance(stmt, ast.Explain):
        inner = _codegen(stmt.stmt, sql)
        return plans.ExplainPlan(inner=inner, text=explain_text(inner))
    raise SQLCodegenError(f"cannot lower {type(stmt).__name__}")


def mesh_exclusion_reason(plan: plans.Plan) -> str | None:
    """Why a plan cannot execute over the device mesh (None = shardable).
    One predicate shared by the task runtime's gate and EXPLAIN, so the
    single-chip fallback is always visible (SURVEY §2.3)."""
    if not isinstance(plan, plans.SelectPlan):
        sel = getattr(plan, "select", None)
        if sel is None:
            return "not a SELECT plan"
        plan = sel
    if plan.join is not None and getattr(plan.join, "table", False):
        return ("stream-TABLE JOIN keeps keyed last-value state on the "
                "host; the probe side runs single-chip")
    # interval (stream-stream) joins shard: key-sharded side stores with
    # the fused probe scatter into the sharded aggregate lattice, and
    # session windows shard their chain-merge arena per key shard — only
    # the downstream aggregate's own exclusions remain
    from hstream_tpu.engine.plan import AggKind, AggregateNode

    node = plan.node
    if not isinstance(node, AggregateNode):
        return "stateless plans have no device state to shard"
    if any(a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT)
           for a in node.aggs):
        return ("TOPK/TOPK_DISTINCT planes have no elementwise shard "
                "merge; the query runs single-chip")
    return None


def explain_text(plan: plans.Plan) -> str:
    """Render the task topology (reference ExecPlan.hs:80-119)."""
    if isinstance(plan, plans.SelectPlan):
        lines = []
        node = plan.node

        def walk(n, depth):
            pad = "  " * depth
            if isinstance(n, AggregateNode):
                w = n.window
                wtxt = (f" window={type(w).__name__}" if w else "")
                lines.append(
                    f"{pad}AGGREGATE keys={[getattr(g, 'name', '?') for g in n.group_keys]}"
                    f" aggs={[a.out_name for a in n.aggs]}{wtxt}"
                    + (" having" if n.having is not None else "")
                    + (f" [state: lattice {len(n.aggs)} planes]"))
                walk(n.child, depth + 1)
            elif isinstance(n, FilterNode):
                lines.append(f"{pad}FILTER")
                walk(n.child, depth + 1)
            elif isinstance(n, ProjectNode):
                lines.append(f"{pad}PROJECT {[name for name, _ in n.exprs]}")
                walk(n.child, depth + 1)
            elif isinstance(n, SourceNode):
                lines.append(f"{pad}SOURCE stream={n.stream}")

        walk(node, 0)
        if plan.join is not None:
            if getattr(plan.join, "table", False):
                lines.insert(0, f"JOIN TABLE({plan.join.right.name}) "
                                "[keyed last-value]")
            else:
                lines.insert(0, f"JOIN {plan.join.right.name} "
                                f"WITHIN {plan.join.within.ms}ms")
        reason = mesh_exclusion_reason(plan)
        if reason is None:
            try:
                import jax
                nd = jax.device_count()
            except Exception:  # noqa: BLE001 — EXPLAIN must render
                nd = 1         # without a device runtime
            lines.append(f"MESH: shardable over {nd} chips "
                         "(data x key) when --mesh is set")
        else:
            lines.append(f"MESH: single-chip — {reason}")
        # co-compile packing eligibility (ISSUE 17c): the typed refusal
        # reason surfaces here so EXPLAIN answers "why didn't this
        # query share a lattice" (lazy import: placer pulls scheduler,
        # which pulls codegen back)
        from hstream_tpu.placer.packing import (
            PackRefusal,
            pack_signature,
            signature_text,
        )

        sig = pack_signature(plan)
        if isinstance(sig, PackRefusal):
            lines.append(f"PACK: unpackable — {sig.code}: {sig.detail}")
        else:
            lines.append("PACK: packable with --pack-queries — "
                         f"{signature_text(sig)}")
        return "\n".join(lines)
    if isinstance(plan, plans.CreateBySelectPlan):
        return (f"CREATE STREAM {plan.stream} AS\n"
                + explain_text(plan.select))
    if isinstance(plan, plans.CreateViewPlan):
        return f"CREATE VIEW {plan.view} AS\n" + explain_text(plan.select)
    return type(plan).__name__


def emitted_group_cols(node: AggregateNode) -> list[str]:
    """Names under which the group-key columns appear in EMITTED rows.

    Without post projections rows carry the plan column names; with them
    (any aliased/computed select item) a key column emits under the name
    of the first projected item that is exactly that column — e.g.
    `SELECT city AS c ... GROUP BY city` emits the key as "c". Consumers
    keying on emitted rows (materialized views) must use these names."""
    out = []
    for g in node.group_keys:
        if not isinstance(g, Col):
            continue
        name = g.name
        for out_name, e in (node.post_projections or []):
            if isinstance(e, Col) and e.name == g.name:
                name = out_name
                break
        out.append(name)
    return out


def make_executor(plan: plans.SelectPlan, sample_rows=None, *,
                  mesh=None, initial_keys: int = 1024,
                  batch_capacity: int = 4096):
    """Instantiate the executor for a lowered SELECT plan.

    `sample_rows` refine schema inference (bind_schema). With `mesh`, the
    aggregation lattice is sharded over it (hstream_tpu.parallel)."""
    if plan.join is not None:
        from hstream_tpu.engine.join import JoinExecutor, TableJoinExecutor

        # schema inference for the inner executor uses the first JOINED
        # batch (caller sample rows are single-stream shaped)
        if getattr(plan.join, "table", False):
            # TABLE joins keep keyed last-value state on the host
            return TableJoinExecutor(plan, initial_keys=initial_keys,
                                     batch_capacity=batch_capacity)
        return JoinExecutor(plan, initial_keys=initial_keys,
                            batch_capacity=batch_capacity, mesh=mesh)
    node = plan.node
    if isinstance(node, AggregateNode):
        schema = bind_schema(plan, sample_rows)
        if isinstance(node.window, SessionWindow):
            from hstream_tpu.engine.session import SessionExecutor

            return SessionExecutor(node, schema,
                                   emit_changes=plan.emit_changes,
                                   mesh=mesh)
        if mesh is not None and any(
                a.kind in (AggKind.TOPK, AggKind.TOPK_DISTINCT)
                for a in node.aggs):
            mesh = None  # TOPK planes have no elementwise shard merge
        if mesh is not None:
            from hstream_tpu.parallel import ShardedQueryExecutor

            return ShardedQueryExecutor(
                node, schema, mesh=mesh, emit_changes=plan.emit_changes,
                initial_keys=initial_keys, batch_capacity=batch_capacity)
        from hstream_tpu.engine.executor import QueryExecutor

        return QueryExecutor(node, schema, emit_changes=plan.emit_changes,
                             initial_keys=initial_keys,
                             batch_capacity=batch_capacity)
    from hstream_tpu.engine.stateless import StatelessExecutor

    return StatelessExecutor(node)


def bind_schema(plan: plans.SelectPlan, sample_rows=None) -> Schema:
    """Concrete device Schema for a lowered plan: inferred types, refined
    by sampling decoded records when provided (numbers -> FLOAT,
    strings -> STRING, bools -> BOOL)."""
    types = dict(plan.schema_req.inferred)
    for row in (sample_rows or []):
        for k, v in row.items():
            if k in types:
                continue
            if isinstance(v, bool):
                types[k] = ColumnType.BOOL
            elif isinstance(v, (int, float)):
                types[k] = ColumnType.FLOAT
            elif isinstance(v, str):
                types[k] = ColumnType.STRING
    # group-key columns referenced by emission must exist in the schema
    # for row decode; give unseen ones STRING
    node = plan.node
    if isinstance(node, AggregateNode):
        for g in node.group_keys:
            if isinstance(g, Col) and g.name not in types:
                types[g.name] = ColumnType.STRING
    return Schema(tuple(types.items()))
