"""SQL tokenizer.

Token kinds: IDENT, RAWCOL (`backticked`), NUMBER, STRING ("double"),
SSTRING ('single' — JSON payload in INSERT), symbols, EOF. Keywords are
recognized case-insensitively at the parser level (the reference's BNFC
grammar demands exact-case keywords; we accept any case and canonicalize).
Comments: // line and /* block */ (SQL.cf `comment` pragmas).
"""

from __future__ import annotations

from dataclasses import dataclass

from hstream_tpu.common.errors import SQLParseError

SYMBOLS = [
    "<>", "<=", ">=", "||", "&&",
    "(", ")", "[", "]", "{", "}", ",", ";", ":", ".", "=", "<", ">",
    "+", "-", "*", "/", "%",
]


@dataclass(frozen=True)
class Token:
    kind: str      # IDENT RAWCOL NUMBER STRING SSTRING SYM EOF
    text: str
    value: object  # parsed value for NUMBER/STRING
    line: int
    col: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(src: str) -> list[Token]:
    toks: list[Token] = []
    i, line, col = 0, 1, 1
    n = len(src)

    def err(msg: str):
        raise SQLParseError(msg, (line, col))

    while i < n:
        c = src[i]
        if c == "\n":
            i += 1
            line += 1
            col = 1
            continue
        if c in " \t\r":
            i += 1
            col += 1
            continue
        if src.startswith("//", i):
            while i < n and src[i] != "\n":
                i += 1
            continue
        if src.startswith("/*", i):
            end = src.find("*/", i + 2)
            if end < 0:
                err("unterminated block comment")
            skipped = src[i:end + 2]
            line += skipped.count("\n")
            col = 1 if "\n" in skipped else col + len(skipped)
            i = end + 2
            continue
        start_line, start_col = line, col
        if c.isdigit() or (c == "." and i + 1 < n and src[i + 1].isdigit()):
            j = i
            is_float = False
            while j < n and (src[j].isdigit() or src[j] == "."):
                if src[j] == ".":
                    if is_float:
                        break
                    is_float = True
                j += 1
            if j < n and src[j] in "eE":
                k = j + 1
                if k < n and src[k] in "+-":
                    k += 1
                if k < n and src[k].isdigit():
                    is_float = True
                    j = k
                    while j < n and src[j].isdigit():
                        j += 1
            text = src[i:j]
            value = float(text) if is_float else int(text)
            toks.append(Token("NUMBER", text, value, start_line, start_col))
            col += j - i
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (src[j].isalnum() or src[j] == "_"):
                j += 1
            text = src[i:j]
            toks.append(Token("IDENT", text, text, start_line, start_col))
            col += j - i
            i = j
            continue
        if c == "`":
            j = src.find("`", i + 1)
            if j < 0:
                err("unterminated `raw column`")
            text = src[i + 1:j]
            toks.append(Token("RAWCOL", text, text, start_line, start_col))
            col += j + 1 - i
            i = j + 1
            continue
        if c in "\"'":
            quote = c
            j = i + 1
            buf = []
            while j < n and src[j] != quote:
                if src[j] == "\\" and j + 1 < n:
                    esc = src[j + 1]
                    buf.append({"n": "\n", "t": "\t", "\\": "\\",
                                quote: quote}.get(esc, esc))
                    j += 2
                else:
                    buf.append(src[j])
                    j += 1
            if j >= n:
                err("unterminated string literal")
            kind = "STRING" if quote == '"' else "SSTRING"
            toks.append(Token(kind, src[i:j + 1], "".join(buf),
                              start_line, start_col))
            col += j + 1 - i
            i = j + 1
            continue
        for sym in SYMBOLS:
            if src.startswith(sym, i):
                toks.append(Token("SYM", sym, sym, start_line, start_col))
                i += len(sym)
                col += len(sym)
                break
        else:
            err(f"unexpected character {c!r}")
    toks.append(Token("EOF", "", None, line, col))
    return toks
