"""Recursive-descent / Pratt parser for the HStream SQL surface.

Grammar parity with the reference's BNFC grammar (hstream-sql/etc/SQL.cf):
statements SELECT / CREATE (STREAM [AS] | VIEW | SINK CONNECTOR) / INSERT
(fields, 'json', "binary") / SHOW / DROP [IF EXISTS] / TERMINATE /
EXPLAIN; SELECT with FROM + [JOIN ... WITHIN(...) ON ...] + WHERE +
GROUP BY [, window] + HAVING + [EMIT CHANGES]; value expressions with
|| && arithmetic, scalar functions, set functions, BETWEEN, NOT;
search conditions with OR/AND/NOT. A select without EMIT CHANGES is a
pull query against a view (SelectView in the reference).
"""

from __future__ import annotations

from typing import Any

from hstream_tpu.common.errors import SQLParseError
from hstream_tpu.engine.expr import BinOp, Col, Expr, Lit, UnOp
from hstream_tpu.sql import ast
from hstream_tpu.sql.lexer import Token, tokenize

# scalar function name -> engine UnOp/BinOp op name
_UNARY_FUNCS = {
    "SIN": "SIN", "SINH": "SINH", "ASIN": "ASIN", "ASINH": "ASINH",
    "COS": "COS", "COSH": "COSH", "ACOS": "ACOS", "ACOSH": "ACOSH",
    "TAN": "TAN", "TANH": "TANH", "ATAN": "ATAN", "ATANH": "ATANH",
    "ABS": "ABS", "CEIL": "CEIL", "FLOOR": "FLOOR", "ROUND": "ROUND",
    "SIGN": "SIGN", "SQRT": "SQRT", "LOG": "LOG", "LOG2": "LOG2",
    "LOG10": "LOG10", "EXP": "EXP",
    "IS_INT": "IS_INT", "IS_FLOAT": "IS_FLOAT", "IS_NUM": "IS_NUM",
    "IS_BOOL": "IS_BOOL", "IS_STR": "IS_STR", "IS_ARRAY": "IS_ARRAY",
    "TO_STR": "TO_STR", "TO_LOWER": "TO_LOWER", "TO_UPPER": "TO_UPPER",
    "TRIM": "TRIM", "LEFT_TRIM": "LTRIM", "RIGHT_TRIM": "RTRIM",
    "REVERSE": "REVERSE", "STRLEN": "STRLEN",
    "ARRAY_DISTINCT": "ARR_DISTINCT", "ARRAY_LENGTH": "ARR_LENGTH",
    "ARRAY_MAX": "ARR_MAX", "ARRAY_MIN": "ARR_MIN", "ARRAY_SORT": "ARR_SORT",
}

_BINARY_FUNCS = {
    "IFNULL": "IFNULL",
    "ARRAY_CONTAIN": "ARR_CONTAINS",
    "ARRAY_JOIN": "ARR_JOIN",
}

_AGG_FUNCS = {
    "COUNT": ast.SetFuncKind.COUNT,
    "AVG": ast.SetFuncKind.AVG,
    "SUM": ast.SetFuncKind.SUM,
    "MAX": ast.SetFuncKind.MAX,
    "MIN": ast.SetFuncKind.MIN,
    "TOPK": ast.SetFuncKind.TOPK,
    "TOPKDISTINCT": ast.SetFuncKind.TOPKDISTINCT,
    "APPROX_COUNT_DISTINCT": ast.SetFuncKind.APPROX_COUNT_DISTINCT,
    "APPROX_QUANTILE": ast.SetFuncKind.APPROX_QUANTILE,
}

_TIME_UNITS = {"SECOND", "MINUTE", "HOUR", "DAY", "WEEK", "MONTH", "YEAR"}


class Parser:
    def __init__(self, src: str):
        self.src = src
        self.toks = tokenize(src)
        self.pos = 0

    # ---- token helpers ----
    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.pos + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.pos]
        if t.kind != "EOF":
            self.pos += 1
        return t

    def err(self, msg: str, tok: Token | None = None):
        tok = tok or self.peek()
        raise SQLParseError(msg, (tok.line, tok.col))

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.upper in kws

    def eat_kw(self, kw: str) -> Token:
        if not self.at_kw(kw):
            self.err(f"expected {kw}")
        return self.next()

    def try_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def at_sym(self, s: str) -> bool:
        t = self.peek()
        return t.kind == "SYM" and t.text == s

    def peek2_sym(self, s: str) -> bool:
        """The token AFTER the current one is the symbol `s` (lookahead
        to disambiguate JOIN TABLE( from a stream named table)."""
        t = self.peek(1)
        return t.kind == "SYM" and t.text == s

    def eat_sym(self, s: str) -> Token:
        if not self.at_sym(s):
            self.err(f"expected {s!r}")
        return self.next()

    def try_sym(self, s: str) -> bool:
        if self.at_sym(s):
            self.next()
            return True
        return False

    def ident(self, what: str = "identifier") -> str:
        t = self.peek()
        if t.kind not in ("IDENT", "RAWCOL"):
            self.err(f"expected {what}")
        return self.next().text

    def text_between(self, start: int, end: int) -> str:
        # compact rendering: no spaces around ( ) , . so aggregate output
        # names read like the SQL source ("SUM(temp)")
        out: list[str] = []
        for t in self.toks[start:end]:
            if out and (t.text in (")", ",", ".", "(")
                        or out[-1].endswith(("(", "."))):
                out[-1] = out[-1] + t.text
            else:
                out.append(t.text)
        return " ".join(out)

    # ---- statements ----
    def parse_stmt(self) -> ast.Statement:
        if self.at_kw("SELECT"):
            return self.parse_select()
        if self.at_kw("CREATE"):
            return self.parse_create()
        if self.at_kw("INSERT"):
            return self.parse_insert()
        if self.at_kw("SHOW"):
            self.next()
            t = self.next()
            what = t.upper
            if what not in ("QUERIES", "STREAMS", "CONNECTORS", "VIEWS"):
                self.err("expected QUERIES, STREAMS, CONNECTORS or VIEWS", t)
            return ast.Show(what)
        if self.at_kw("DROP"):
            self.next()
            t = self.next()
            what = t.upper
            if what not in ("STREAM", "VIEW", "CONNECTOR"):
                self.err("expected STREAM, VIEW or CONNECTOR", t)
            name = self.ident("name")
            if_exists = False
            if self.try_kw("IF"):
                self.eat_kw("EXISTS")
                if_exists = True
            return ast.Drop(what, name, if_exists)
        if self.at_kw("TERMINATE"):
            self.next()
            if self.try_kw("ALL"):
                return ast.Terminate(None)
            self.eat_kw("QUERY")
            t = self.next()
            if t.kind not in ("NUMBER", "IDENT", "SSTRING", "STRING"):
                self.err("expected query id", t)
            return ast.Terminate(str(t.value if t.kind == "NUMBER" else t.text))
        if self.at_kw("EXPLAIN"):
            self.next()
            inner = self.parse_stmt()
            if not isinstance(inner, (ast.Select, ast.CreateStream,
                                      ast.CreateView)):
                self.err("EXPLAIN expects SELECT or CREATE")
            return ast.Explain(inner)
        self.err("expected a statement (SELECT/CREATE/INSERT/SHOW/DROP/"
                 "TERMINATE/EXPLAIN)")

    def parse(self) -> ast.Statement:
        stmt = self.parse_stmt()
        self.try_sym(";")
        if self.peek().kind != "EOF":
            self.err("unexpected trailing input")
        return stmt

    # ---- CREATE ----
    def parse_create(self) -> ast.Statement:
        self.eat_kw("CREATE")
        if self.try_kw("VIEW"):
            name = self.ident("view name")
            self.eat_kw("AS")
            select = self.parse_select()
            return ast.CreateView(name, select)
        if self.try_kw("SINK"):
            self.eat_kw("CONNECTOR")
            name = self.ident("connector name")
            if_not_exist = False
            if self.try_kw("IF"):
                self.eat_kw("NOT")
                self.eat_kw("EXIST")
                if_not_exist = True
            self.eat_kw("WITH")
            opts = self.parse_options()
            return ast.CreateConnector(name, opts, if_not_exist)
        self.eat_kw("STREAM")
        name = self.ident("stream name")
        as_select = None
        options: dict[str, Any] = {}
        if self.try_kw("AS"):
            as_select = self.parse_select()
        if self.try_kw("WITH"):
            options = self.parse_options()
        return ast.CreateStream(name, options, as_select)

    def parse_options(self) -> dict[str, Any]:
        self.eat_sym("(")
        opts: dict[str, Any] = {}
        while not self.at_sym(")"):
            key = self.ident("option name").upper()
            self.eat_sym("=")
            t = self.next()
            if t.kind in ("NUMBER", "STRING", "SSTRING"):
                opts[key] = t.value
            elif t.kind == "IDENT":
                opts[key] = t.text
            else:
                self.err("expected option value", t)
            if not self.try_sym(","):
                break
        self.eat_sym(")")
        return opts

    # ---- INSERT ----
    def parse_insert(self) -> ast.Insert:
        self.eat_kw("INSERT")
        self.eat_kw("INTO")
        stream = self.ident("stream name")
        if self.try_sym("("):
            fields = [self.ident("field")]
            while self.try_sym(","):
                fields.append(self.ident("field"))
            self.eat_sym(")")
            self.eat_kw("VALUES")
            self.eat_sym("(")
            values = [self.parse_literal()]
            while self.try_sym(","):
                values.append(self.parse_literal())
            self.eat_sym(")")
            if len(fields) != len(values):
                self.err("INSERT field/value count mismatch")
            return ast.Insert(stream, fields, values, None, None)
        self.eat_kw("VALUES")
        t = self.next()
        if t.kind == "SSTRING":
            return ast.Insert(stream, None, None, t.value, None)
        if t.kind == "STRING":
            return ast.Insert(stream, None, None, None, t.value)
        self.err("expected (fields) VALUES (...), 'json' or \"binary\"", t)

    def parse_literal(self) -> Any:
        t = self.peek()
        if t.kind == "NUMBER":
            return self.next().value
        if t.kind in ("STRING", "SSTRING"):
            return self.next().value
        if t.kind == "IDENT" and t.upper in ("TRUE", "FALSE"):
            return self.next().upper == "TRUE"
        if t.kind == "IDENT" and t.upper == "NULL":
            self.next()
            return None
        if t.kind == "SYM" and t.text == "-":
            self.next()
            v = self.parse_literal()
            if not isinstance(v, (int, float)):
                self.err("expected number after -")
            return -v
        self.err("expected literal")

    # ---- SELECT ----
    def parse_select(self) -> ast.Select:
        self.eat_kw("SELECT")
        items: list[ast.SelectItem] | None
        if self.try_sym("*"):
            items = None
        else:
            items = [self.parse_select_item()]
            while self.try_sym(","):
                items.append(self.parse_select_item())
        self.eat_kw("FROM")
        source = self.parse_stream_ref()
        join = None
        if self.at_kw("INNER", "LEFT", "OUTER", "JOIN"):
            join = self.parse_join()
        where = None
        if self.try_kw("WHERE"):
            where = self.parse_cond()
        group_by: list[Expr] = []
        window = None
        if self.try_kw("GROUP"):
            self.eat_kw("BY")
            while True:
                if self.at_kw("TUMBLING", "HOPPING", "SESSION"):
                    window = self.parse_window()
                else:
                    group_by.append(self.parse_colname())
                if not self.try_sym(","):
                    break
        having = None
        if self.try_kw("HAVING"):
            having = self.parse_cond()
        emit_changes = False
        if self.try_kw("EMIT"):
            self.eat_kw("CHANGES")
            emit_changes = True
        return ast.Select(items=items, source=source, join=join, where=where,
                          group_by=group_by, window=window, having=having,
                          emit_changes=emit_changes)

    def parse_colname(self) -> Col:
        t = self.next()
        if t.kind not in ("IDENT", "RAWCOL"):
            self.err("expected column name", t)
        name = t.text
        if self.at_sym(".") and self.peek(1).kind in ("IDENT", "RAWCOL"):
            self.next()
            field = self.ident("column")
            return Col(field, stream=name)
        return Col(name)

    def parse_select_item(self) -> ast.SelectItem:
        start = self.pos
        expr = self.parse_expr()
        text = self.text_between(start, self.pos)
        alias = None
        if self.try_kw("AS"):
            alias = self.ident("alias")
        return ast.SelectItem(expr, alias, text)

    def parse_stream_ref(self) -> ast.StreamRef:
        name = self.ident("stream name")
        alias = None
        if self.try_kw("AS"):
            alias = self.ident("alias")
        return ast.StreamRef(name, alias)

    def parse_join(self) -> ast.JoinClause:
        jt = "INNER"
        if self.at_kw("INNER", "LEFT", "OUTER"):
            jt = self.next().upper
        self.eat_kw("JOIN")
        # JOIN TABLE(s): the right side is a keyed last-value TABLE of
        # the stream (reference stream-table join, Stream.hs:302-344);
        # no WITHIN — table lookups are not time-bounded
        if self.at_kw("TABLE") and self.peek2_sym("("):
            self.next()
            self.eat_sym("(")
            right = self.parse_stream_ref()
            self.eat_sym(")")
            alias = None
            if self.try_kw("AS"):
                alias = self.ident("alias")
                right = ast.StreamRef(right.name, alias)
            self.eat_kw("ON")
            on = self.parse_cond()
            return ast.JoinClause(jt, right, None, on, table=True)
        right = self.parse_stream_ref()
        self.eat_kw("WITHIN")
        self.eat_sym("(")
        within = self.parse_interval()
        self.eat_sym(")")
        self.eat_kw("ON")
        on = self.parse_cond()
        return ast.JoinClause(jt, right, within, on)

    def parse_window(self) -> ast.WindowExpr:
        t = self.next()
        kind = ast.WindowKind[t.upper]
        self.eat_sym("(")
        size = self.parse_interval()
        advance = None
        if kind == ast.WindowKind.HOPPING:
            self.eat_sym(",")
            advance = self.parse_interval()
        self.eat_sym(")")
        grace = None
        if self.try_kw("GRACE"):   # extension: GRACE BY INTERVAL n unit
            self.eat_kw("BY")
            grace = self.parse_interval()
        return ast.WindowExpr(kind, size, advance, grace)

    def parse_interval(self) -> ast.Interval:
        self.eat_kw("INTERVAL")
        t = self.next()
        if t.kind != "NUMBER" or not isinstance(t.value, int):
            self.err("expected integer interval amount", t)
        unit_t = self.next()
        if unit_t.upper not in _TIME_UNITS:
            self.err(f"expected time unit, got {unit_t.text}", unit_t)
        return ast.Interval(t.value, unit_t.upper)

    # ---- search conditions (OR/AND/NOT over comparisons) ----
    def parse_cond(self) -> Expr:
        left = self.parse_cond_and()
        while self.at_kw("OR"):
            self.next()
            left = BinOp("OR", left, self.parse_cond_and())
        return left

    def parse_cond_and(self) -> Expr:
        left = self.parse_cond_not()
        while self.at_kw("AND"):
            self.next()
            left = BinOp("AND", left, self.parse_cond_not())
        return left

    def parse_cond_not(self) -> Expr:
        if self.try_kw("NOT"):
            return UnOp("NOT", self.parse_cond_not())
        return self.parse_cond_cmp()

    def parse_cond_cmp(self) -> Expr:
        if self.at_sym("(") and self._paren_is_cond():
            self.eat_sym("(")
            c = self.parse_cond()
            self.eat_sym(")")
            return c
        left = self.parse_expr()
        t = self.peek()
        if t.kind == "SYM" and t.text in ("=", "<>", "<", "<=", ">", ">="):
            op = self.next().text
            right = self.parse_expr()
            return BinOp(op, left, right)
        if self.at_kw("BETWEEN"):
            self.next()
            lo = self.parse_expr()
            self.eat_kw("AND")
            hi = self.parse_expr()
            return BinOp("AND", BinOp(">=", left, lo), BinOp("<=", left, hi))
        return left  # bare boolean expression

    def _paren_is_cond(self) -> bool:
        """Lookahead: does this parenthesized group contain a top-level
        OR/AND/NOT/comparison (a condition) rather than a value expr?"""
        depth = 0
        i = self.pos
        while i < len(self.toks):
            t = self.toks[i]
            if t.kind == "SYM" and t.text == "(":
                depth += 1
            elif t.kind == "SYM" and t.text == ")":
                depth -= 1
                if depth == 0:
                    return False
            elif depth == 1:
                if t.kind == "IDENT" and t.upper in ("OR", "AND", "NOT",
                                                     "BETWEEN"):
                    return True
                if t.kind == "SYM" and t.text in ("=", "<>", "<", "<=",
                                                  ">", ">="):
                    return True
            i += 1
        return False

    # ---- value expressions (Pratt: || < && < +- < */% < unary) ----
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.at_sym("||"):
            self.next()
            left = BinOp("OR", left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_add()
        while self.at_sym("&&"):
            self.next()
            left = BinOp("AND", left, self.parse_add())
        return left

    def parse_add(self) -> Expr:
        left = self.parse_mul()
        while self.at_sym("+") or self.at_sym("-"):
            op = self.next().text
            left = BinOp(op, left, self.parse_mul())
        return left

    def parse_mul(self) -> Expr:
        left = self.parse_unary()
        while self.at_sym("*") or self.at_sym("/") or self.at_sym("%"):
            op = self.next().text
            left = BinOp(op, left, self.parse_unary())
        return left

    def parse_unary(self) -> Expr:
        if self.at_sym("-"):
            self.next()
            return UnOp("NEG", self.parse_unary())
        return self.parse_atom()

    def parse_atom(self) -> Expr:
        t = self.peek()
        if t.kind == "SYM" and t.text == "(":
            self.next()
            e = self.parse_expr()
            self.eat_sym(")")
            return e
        if t.kind == "NUMBER":
            return Lit(self.next().value)
        if t.kind in ("STRING", "SSTRING"):
            return Lit(self.next().value)
        if t.kind == "RAWCOL":
            return Col(self.next().text)
        if t.kind == "SYM" and t.text == "[":
            self.next()
            items = []
            if not self.at_sym("]"):
                items.append(self.parse_literal())
                while self.try_sym(","):
                    items.append(self.parse_literal())
            self.eat_sym("]")
            return Lit(items)
        if t.kind == "IDENT":
            upper = t.upper
            if upper == "NULL":
                self.next()
                return Lit(None)
            if upper in ("TRUE", "FALSE"):
                self.next()
                return Lit(upper == "TRUE")
            if upper == "INTERVAL":
                iv = self.parse_interval()
                return Lit(iv.ms)
            # function call?
            if self.peek(1).kind == "SYM" and self.peek(1).text == "(":
                return self.parse_call()
            # column ref, possibly stream-qualified
            name = self.next().text
            if self.at_sym(".") and self.peek(1).kind in ("IDENT", "RAWCOL"):
                self.next()
                field = self.ident("column")
                return Col(field, stream=name)
            return Col(name)
        self.err("expected expression")

    def parse_call(self) -> Expr:
        name_t = self.next()
        fname = name_t.upper
        start = self.pos - 1
        self.eat_sym("(")
        if fname == "COUNT" and self.try_sym("*"):
            self.eat_sym(")")
            return ast.SetFunc(ast.SetFuncKind.COUNT_ALL, None, None,
                               "COUNT(*)")
        args: list[Expr] = []
        if not self.at_sym(")"):
            args.append(self.parse_expr())
            while self.try_sym(","):
                args.append(self.parse_expr())
        self.eat_sym(")")
        text = self.text_between(start, self.pos)

        if fname in _AGG_FUNCS:
            kind = _AGG_FUNCS[fname]
            if kind in (ast.SetFuncKind.TOPK, ast.SetFuncKind.TOPKDISTINCT,
                        ast.SetFuncKind.APPROX_QUANTILE):
                if len(args) != 2 or not isinstance(args[1], Lit):
                    self.err(f"{fname} expects (expr, literal)", name_t)
                return ast.SetFunc(kind, args[0], args[1].value, text)
            if len(args) != 1:
                self.err(f"{fname} expects 1 argument", name_t)
            return ast.SetFunc(kind, args[0], None, text)
        if fname in _UNARY_FUNCS:
            if len(args) != 1:
                self.err(f"{fname} expects 1 argument", name_t)
            return UnOp(_UNARY_FUNCS[fname], args[0])
        if fname in _BINARY_FUNCS:
            if len(args) != 2:
                self.err(f"{fname} expects 2 arguments", name_t)
            return BinOp(_BINARY_FUNCS[fname], args[0], args[1])
        self.err(f"unknown function {name_t.text}", name_t)


def parse(sql: str) -> ast.Statement:
    return Parser(sql).parse()
