"""The plan ADT produced by SQL codegen.

Mirrors the reference's `HStreamPlan` (hstream-sql Codegen.hs:94-105):
SelectPlan / CreatePlan / CreateBySelectPlan / CreateViewPlan /
CreateSinkConnectorPlan / InsertPlan / DropPlan / ShowPlan /
TerminatePlan / SelectViewPlan / ExplainPlan — lowered here to the
engine's logical plan nodes instead of processor closures.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from hstream_tpu.engine.plan import PlanNode
from hstream_tpu.engine.types import ColumnType
from hstream_tpu.sql import ast


@dataclass(frozen=True)
class SchemaRequirement:
    """Column types the lowered plan needs on device. `inferred` maps a
    column to its type as deduced from expression context (string
    comparisons -> STRING, arithmetic/aggregation -> FLOAT); columns used
    only as group keys stay host-side and are not listed."""

    inferred: dict[str, ColumnType] = field(default_factory=dict)


@dataclass(frozen=True)
class SelectPlan:
    sql: str
    source: str                  # source stream name
    node: PlanNode               # engine logical plan (root)
    schema_req: SchemaRequirement
    emit_changes: bool
    join: ast.JoinClause | None = None
    source_alias: str | None = None   # FROM <source> AS <alias>


@dataclass(frozen=True)
class CreatePlan:
    stream: str
    options: dict[str, Any]


@dataclass(frozen=True)
class CreateBySelectPlan:
    stream: str
    select: SelectPlan
    options: dict[str, Any]


@dataclass(frozen=True)
class CreateViewPlan:
    view: str
    select: SelectPlan


@dataclass(frozen=True)
class CreateSinkConnectorPlan:
    name: str
    options: dict[str, Any]
    if_not_exist: bool


@dataclass(frozen=True)
class InsertPlan:
    stream: str
    payload: dict | None         # decoded JSON object
    raw_payload: bytes | None    # binary insert


@dataclass(frozen=True)
class DropPlan:
    what: str                    # STREAM / VIEW / CONNECTOR
    name: str
    if_exists: bool


@dataclass(frozen=True)
class ShowPlan:
    what: str                    # QUERIES / STREAMS / CONNECTORS / VIEWS


@dataclass(frozen=True)
class TerminatePlan:
    query_id: str | None         # None = TERMINATE ALL


@dataclass(frozen=True)
class SelectViewPlan:
    """Pull query: SELECT ... FROM view [WHERE key = ...] without EMIT
    CHANGES (reference SelectViewPlan, served from materialized state)."""

    sql: str
    view: str
    select: ast.Select


@dataclass(frozen=True)
class ExplainPlan:
    inner: "Plan"
    text: str


Plan = (SelectPlan | CreatePlan | CreateBySelectPlan | CreateViewPlan
        | CreateSinkConnectorPlan | InsertPlan | DropPlan | ShowPlan
        | TerminatePlan | SelectViewPlan | ExplainPlan)
