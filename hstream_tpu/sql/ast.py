"""Raw SQL AST produced by the parser.

Mirrors the shape of the reference's BNFC-generated abstract syntax
(hstream-sql AST before `Refine` — see AST.hs): statements, select
structure, search conditions and value expressions. Scalar/aggregate
expressions reuse the engine's Expr nodes (Col/Lit/BinOp/UnOp) directly,
plus SQL-only wrappers defined here for aggregates and intervals.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any

from hstream_tpu.engine.expr import Expr


# ---- aggregates (set functions) -------------------------------------------

class SetFuncKind(enum.Enum):
    COUNT_ALL = "COUNT(*)"
    COUNT = "COUNT"
    AVG = "AVG"
    SUM = "SUM"
    MAX = "MAX"
    MIN = "MIN"
    TOPK = "TOPK"
    TOPKDISTINCT = "TOPKDISTINCT"
    APPROX_COUNT_DISTINCT = "APPROX_COUNT_DISTINCT"
    APPROX_QUANTILE = "APPROX_QUANTILE"


@dataclass(frozen=True)
class SetFunc(Expr):
    """An aggregate call appearing inside a select-list expression."""

    kind: SetFuncKind
    arg: Expr | None = None       # None for COUNT(*)
    arg2: Any = None              # k for TOPK / quantile for APPROX_QUANTILE
    text: str = ""                # original SQL text, used as default name


# ---- intervals & windows ---------------------------------------------------

_UNIT_MS = {
    "SECOND": 1000,
    "MINUTE": 60_000,
    "HOUR": 3_600_000,
    "DAY": 86_400_000,
    "WEEK": 7 * 86_400_000,
    "MONTH": 30 * 86_400_000,
    "YEAR": 365 * 86_400_000,
}


@dataclass(frozen=True)
class Interval:
    amount: int
    unit: str  # SECOND/MINUTE/...

    @property
    def ms(self) -> int:
        return self.amount * _UNIT_MS[self.unit]


class WindowKind(enum.Enum):
    TUMBLING = "TUMBLING"
    HOPPING = "HOPPING"
    SESSION = "SESSION"


@dataclass(frozen=True)
class WindowExpr:
    kind: WindowKind
    size: Interval
    advance: Interval | None = None   # HOPPING only
    grace: Interval | None = None     # extension: GRACE BY


# ---- select ----------------------------------------------------------------

@dataclass(frozen=True)
class SelectItem:
    expr: Expr               # may contain SetFunc nodes
    alias: str | None
    text: str                # original SQL text


@dataclass(frozen=True)
class StreamRef:
    name: str
    alias: str | None = None


@dataclass(frozen=True)
class JoinClause:
    join_type: str           # INNER / LEFT / OUTER
    right: StreamRef
    within: Interval | None  # None = stream-table join (JOIN TABLE(x))
    on: Expr
    table: bool = False      # right side is a keyed last-value table


@dataclass(frozen=True)
class Select:
    items: list[SelectItem] | None     # None = SELECT *
    source: StreamRef
    join: JoinClause | None
    where: Expr | None
    group_by: list[Expr]
    window: WindowExpr | None
    having: Expr | None
    emit_changes: bool                 # False = SelectView (pull query)


# ---- statements ------------------------------------------------------------

@dataclass(frozen=True)
class CreateStream:
    name: str
    options: dict[str, Any] = field(default_factory=dict)
    as_select: Select | None = None


@dataclass(frozen=True)
class CreateView:
    name: str
    select: Select


@dataclass(frozen=True)
class CreateConnector:
    name: str
    options: dict[str, Any]
    if_not_exist: bool = False


@dataclass(frozen=True)
class Insert:
    stream: str
    fields: list[str] | None      # field-list form
    values: list[Any] | None
    json_payload: str | None      # INSERT ... VALUES '{"a": 1}'
    binary_payload: str | None    # INSERT ... VALUES "raw"


@dataclass(frozen=True)
class Show:
    what: str  # QUERIES STREAMS CONNECTORS VIEWS


@dataclass(frozen=True)
class Drop:
    what: str  # STREAM VIEW CONNECTOR
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class Terminate:
    query_id: str | None  # None = TERMINATE ALL


@dataclass(frozen=True)
class Explain:
    stmt: "Statement"


Statement = (Select | CreateStream | CreateView | CreateConnector | Insert
             | Show | Drop | Terminate | Explain)
