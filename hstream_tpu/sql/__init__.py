"""SQL frontend: lexer -> parser -> validate -> refine -> plan.

Covers the reference's SQL surface (hstream-sql/etc/SQL.cf): SELECT with
EMIT CHANGES, windows TUMBLING/HOPPING/SESSION, INNER/LEFT/OUTER JOIN
WITHIN, CREATE STREAM [AS] / CREATE VIEW / CREATE SINK CONNECTOR,
INSERT (fields / JSON / binary), SHOW / DROP / TERMINATE / EXPLAIN, the
scalar function library, and pull queries against views (SelectView).
Extensions: APPROX_COUNT_DISTINCT and APPROX_QUANTILE aggregates backed
by the engine's sketch kernels.

The pipeline mirrors the reference's parse -> validate -> refine
(Parse.hs:19-30) but is a hand-written Pratt/recursive-descent parser
instead of generated BNFC tables, and codegen lowers to the engine's
logical plan rather than processor closures (Codegen.hs:94-105 plan ADT).
"""

from hstream_tpu.sql.parser import parse
from hstream_tpu.sql.refine import refine, parse_and_refine
from hstream_tpu.sql.codegen import stream_codegen, Plan
from hstream_tpu.sql import plans

__all__ = ["parse", "refine", "parse_and_refine", "stream_codegen", "Plan",
           "plans"]
