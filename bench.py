"""Headline benchmark: BASELINE config 1/3 sustained ingest on one chip.

Query: SELECT COUNT(*), SUM(temp), APPROX_COUNT_DISTINCT(temp)
       FROM sensors GROUP BY device, TUMBLE(10s)
1k keys, window-close emission. Records are staged columnar (the
production ingest contract: native decode feeds columnar batches) and
shipped to the device as ONE packed buffer per micro-batch; the measured
path is the executor's jitted lattice step + host watermark bookkeeping +
window close/extract — the full steady-state engine.

The loop synchronizes once per micro-batch (bounded pipeline depth):
through tunneled dev TPUs, deep async queues serialize pathologically,
and on real hardware per-batch sync costs ~nothing at these batch sizes.

Prints ONE JSON line:
  {"metric": "events_per_sec", "value": N, "unit": "events/s",
   "vs_baseline": N / 10e6, ...extras}
Baseline: 10M events/s north star (BASELINE.md, TPU v5e-1).
"""

from __future__ import annotations

import json
import time

import numpy as np

TARGET = 10_000_000  # events/s, BASELINE.md north star
N_KEYS = 1000
BATCH = 1 << 19            # records per micro-batch
STREAM_MS_PER_BATCH = 200  # stream time per batch -> close every 50 batches
N_UNIQUE = 8               # distinct pre-generated batches, cycled
WARMUP_BATCHES = 60        # spans one window close (compiles extract/reset)
MEASURE_BATCHES = 150      # spans three window closes


def build_executor():
    from hstream_tpu.engine import (
        AggKind,
        AggSpec,
        AggregateNode,
        ColumnType,
        QueryExecutor,
        Schema,
        SourceNode,
        TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("sensors", schema),
        group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[
            AggSpec(AggKind.COUNT_ALL, "cnt"),
            AggSpec(AggKind.SUM, "total", input=Col("temp")),
            AggSpec(AggKind.APPROX_COUNT_DISTINCT, "uniq",
                    input=Col("temp")),
        ],
    )
    ex = QueryExecutor(node, schema, emit_changes=False,
                       initial_keys=1024, batch_capacity=BATCH)
    for k in range(N_KEYS):
        ex.key_id_for((f"d{k}",))
    return ex


class BatchSource:
    """Cycles N_UNIQUE pre-generated (kids, temp) pairs; timestamps are
    regenerated per use so stream time advances monotonically."""

    def __init__(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.kids = [rng.integers(0, N_KEYS, size=BATCH).astype(np.int32)
                     for _ in range(N_UNIQUE)]
        self.temps = [rng.normal(20.0, 5.0, size=BATCH).astype(np.float32)
                      for _ in range(N_UNIQUE)]
        self.ts_template = ((np.arange(BATCH, dtype=np.int64)
                             * STREAM_MS_PER_BATCH) // BATCH)
        self.base = 1_700_000_000_000
        self.i = 0

    def next(self):
        j = self.i % N_UNIQUE
        ts = self.base + self.i * STREAM_MS_PER_BATCH + self.ts_template
        self.i += 1
        return self.kids[j], ts, {"temp": self.temps[j]}


def step_only_eps(ex, src) -> float:
    """Device-resident step throughput (the XLA hot-path number, free of
    host->device transfer artifacts)."""
    import jax

    from hstream_tpu.engine import lattice

    kids, ts, cols = src.next()
    ts_rel = (ts - ex.epoch).astype(np.int32)
    packed = lattice.pack_batch_host(BATCH, BATCH, kids, ts_rel, None,
                                     cols, [None] * len(ex._null_refs),
                                     ex._layout)
    dev = jax.device_put(packed)
    wm = np.int32(0)
    st = ex._step(ex.state, wm, dev)
    jax.block_until_ready(st)
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        st = ex._step(st, wm, dev)
    jax.block_until_ready(st)
    return reps * BATCH / (time.perf_counter() - t0)


def main() -> None:
    import jax

    ex = build_executor()
    src = BatchSource()

    # One tiny device->host fetch up front: tunneled dev TPUs defer real
    # execution until the first fetch and then run synchronously; doing it
    # now means the measured loop reflects true sustained execution on
    # either a tunnel or real hardware.
    np.asarray(jax.jit(lambda: jax.numpy.zeros(1))())

    for _ in range(WARMUP_BATCHES):
        kids, ts, cols = src.next()
        ex.process_columnar(kids, ts, cols)
        jax.block_until_ready(ex.state)

    close_ms: list[float] = []
    t_start = time.perf_counter()
    for _ in range(MEASURE_BATCHES):
        kids, ts, cols = src.next()
        t0 = time.perf_counter()
        emitted = ex.process_columnar(kids, ts, cols)
        jax.block_until_ready(ex.state)
        if emitted:
            # batch included a window close (extract+decode): record its
            # wall time as a conservative close-latency sample
            close_ms.append((time.perf_counter() - t0) * 1e3)
    elapsed = time.perf_counter() - t_start

    events = MEASURE_BATCHES * BATCH
    eps = events / elapsed
    p99_close = (float(np.percentile(close_ms, 99)) if close_ms else None)
    kernel_eps = step_only_eps(ex, src)
    result = {
        "metric": "events_per_sec",
        "value": round(eps),
        "unit": "events/s",
        "vs_baseline": round(eps / TARGET, 4),
        "batch": BATCH,
        "batches": MEASURE_BATCHES,
        "keys": N_KEYS,
        "elapsed_s": round(elapsed, 3),
        "p99_window_close_ms": (round(p99_close, 2)
                                if p99_close is not None else None),
        "n_window_closes": len(close_ms),
        "kernel_events_per_sec": round(kernel_eps),
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
