"""Headline benchmark: BASELINE config 1/3 sustained ingest on one chip.

Query: SELECT COUNT(*), SUM(temp), APPROX_COUNT_DISTINCT(temp)
       FROM sensors GROUP BY device, TUMBLE(10s)
1k keys, window-close emission.

Measured path = the production ingest contract end-to-end:
  columnar staging -> adaptive bit-packed wire codec (engine/transport:
  u16 key + u8 time-delta + dec16 fixed-point payload = 5 B/event) ->
  host->device upload -> jitted decode+scatter lattice step -> host
  watermark bookkeeping -> window close (device extract+reset) -> row
  decode. Encode/upload runs on the IngestPipeline worker thread,
  overlapping the step dispatches (engine/pipeline.py); window-close
  extraction is dispatched inline and decoded at the sink (pull-based,
  engine.executor.drain_closed). The timed region covers every batch
  submitted AND a final forcing fetch, so all device work is inside it.

Temperatures are decimal sensor readings (one decimal place, the codec's
canonical f32 form) — the DECIMAL-style data the dec16 wire path exists
for; the codec verifies bit-exact round-trip per batch and falls back to
raw f32 otherwise (tests/test_transport.py).

p99_window_close_ms is measured in a separate steady-state phase: with
the pipeline drained, ingest a small batch that crosses a window
boundary and time until the closed rows are decoded on host — through
the FUSED close path (one extract+reset dispatch + one D2H fetch per
close cycle, engine.lattice.build_extract_reset_slots; columnar host
decode). On tunneled dev chips this is floored by the link RTT
(reported as rtt_ms).

Prints ONE JSON line:
  {"metric": "events_per_sec", "value": N, "unit": "events/s",
   "vs_baseline": N / 10e6, ...extras}
Baseline: 10M events/s north star (BASELINE.md, TPU v5e-1).
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np

TARGET = 10_000_000  # events/s, BASELINE.md north star
N_KEYS = 1000
BATCH = 1 << 20            # records per micro-batch
STREAM_MS_PER_BATCH = 200  # stream time per batch -> close every 50 batches
N_UNIQUE = 8               # distinct pre-generated batches, cycled
WARMUP_BATCHES = 55        # spans one window close (compiles extract/reset)
MEASURE_BATCHES = 100      # spans two window closes
WARMUP_RUN_BATCHES = 25    # untimed warmup RUN before the timed runs:
                           # settles the link/allocator so the first
                           # timed run is not the cold outlier that made
                           # runs_eps spread ~17% across rounds
PIPELINE_DEPTH = 4
ENCODE_WORKERS = 2         # host-encode worker pool (engine.pipeline)


def build_executor():
    from hstream_tpu.engine import (
        AggKind,
        AggSpec,
        AggregateNode,
        ColumnType,
        QueryExecutor,
        Schema,
        SourceNode,
        TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("sensors", schema),
        group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[
            AggSpec(AggKind.COUNT_ALL, "cnt"),
            AggSpec(AggKind.SUM, "total", input=Col("temp")),
            AggSpec(AggKind.APPROX_COUNT_DISTINCT, "uniq",
                    input=Col("temp")),
        ],
    )
    ex = QueryExecutor(node, schema, emit_changes=False,
                       initial_keys=1024, batch_capacity=BATCH)
    ex.defer_close_decode = True
    for k in range(N_KEYS):
        ex.key_id_for((f"d{k}",))
    return ex


class BatchSource:
    """Cycles N_UNIQUE pre-generated (kids, temp) pairs; timestamps are
    regenerated per use so stream time advances monotonically. Temps are
    decimal sensor readings in the codec-canonical f32 form."""

    def __init__(self, seed: int = 0):
        rng = np.random.default_rng(seed)
        self.kids = [rng.integers(0, N_KEYS, size=BATCH).astype(np.int32)
                     for _ in range(N_UNIQUE)]
        self.temps = [
            (np.rint(rng.normal(20.0, 5.0, size=BATCH) * 10)
             .astype(np.float32) * np.float32(0.1))
            for _ in range(N_UNIQUE)]
        self.ts_template = ((np.arange(BATCH, dtype=np.int64)
                             * STREAM_MS_PER_BATCH) // BATCH)
        self.base = 1_700_000_000_000
        self.i = 0

    def next(self):
        j = self.i % N_UNIQUE
        ts = self.base + self.i * STREAM_MS_PER_BATCH + self.ts_template
        self.i += 1
        return self.kids[j], ts, {"temp": self.temps[j]}

    def now(self) -> int:
        """Current stream time (max ts issued so far)."""
        return self.base + self.i * STREAM_MS_PER_BATCH - 1


def force(ex) -> None:
    """One tiny forcing fetch: guarantees every dispatched device op has
    actually executed (block_until_ready is advisory on tunneled dev
    backends; a data fetch is not)."""
    np.asarray(ex.state["count"][0, 0])


def kernel_only_eps(ex, src) -> float:
    """Device step throughput on resident data (the XLA hot-path number,
    free of host->device transfer)."""
    kids, ts, cols = src.next()
    staged = ex.stage_columnar(kids, ts, cols)
    from hstream_tpu.engine import lattice

    step = lattice.compiled_encoded_step(ex.spec, ex.schema,
                                         ex._filter_expr, staged.combo,
                                         staged.cap)
    wm = np.int32(0)
    st = ex.state
    st = step(st, wm, np.int32(staged.n), staged.bases, staged.words)
    np.asarray(st["count"][0, 0])
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        st = step(st, wm, np.int32(staged.n), staged.bases, staged.words)
    np.asarray(st["count"][0, 0])
    dt = time.perf_counter() - t0
    ex.state = st
    return reps * BATCH / dt


def measure_close_latency(ex, pipe, src, n_samples: int = 32) -> tuple:
    """Steady-state window-close latency: pipeline drained, then a small
    batch crosses the next window boundary; time until rows decoded.

    Returns (total_ms_samples, dispatch_ms_samples): total includes the
    device->host fetch (floored by the link RTT on tunneled dev chips);
    dispatch covers ingest + extract/reset dispatch only — the on-device
    close cost net of the link."""
    samples: list[float] = []
    dispatch: list[float] = []
    w = ex.window
    for sample_i in range(n_samples + 1):  # first sample = compile, dropped
        # advance stream time to just before the next boundary
        kids, ts, cols = src.next()
        pipe.submit(kids, ts, cols)
        pipe.flush()
        ex.drain_closed()
        force(ex)
        now = src.now()
        boundary = (now // w.size_ms + 1) * w.size_ms
        n = 4096
        kids_s = np.arange(n, dtype=np.int32) % N_KEYS
        ts_s = np.full(n, boundary + 1, dtype=np.int64)
        temps = np.full(n, np.float32(21.5))
        t0 = time.perf_counter()
        ex.process_columnar(kids_s, ts_s, {"temp": temps})
        t1 = time.perf_counter()  # extract+reset dispatched (async)
        rows = ex.drain_closed()
        t2 = time.perf_counter()
        if rows and sample_i > 0:
            samples.append((t2 - t0) * 1e3)
            dispatch.append((t1 - t0) * 1e3)
        # re-anchor the source past the boundary so subsequent batches
        # don't run backwards in stream time
        src.i = (boundary + w.size_ms - src.base) // STREAM_MS_PER_BATCH
    return samples, dispatch


def measure_freshness(feed, drain, batches: int) -> dict:
    """End-to-end freshness of the ENGINE path (ISSUE 13): for each
    steady-state batch, wall time from the batch's submission to its
    triggered emissions decoded on host — split into dispatch (the
    feed/step call) and drain (deferred close/changelog fetch+decode).
    Only batches that produced emissions sample; p50/p99 over those.
    The served path's freshness comes from the server's own
    freshness histograms instead (server_path_eps)."""
    total: list[float] = []
    disp: list[float] = []
    dr: list[float] = []
    for b in range(batches):
        t0 = time.perf_counter()
        out = feed(b)
        t1 = time.perf_counter()
        rows = drain()
        t2 = time.perf_counter()
        emitted = (out is not None and len(out)) or \
            (rows is not None and len(rows))
        if emitted:
            total.append((t2 - t0) * 1e3)
            disp.append((t1 - t0) * 1e3)
            dr.append((t2 - t1) * 1e3)
    if not total:
        return {"samples": 0}

    def pct(xs, q):
        return round(float(np.percentile(xs, q)), 3)

    return {
        "samples": len(total),
        "p50": pct(total, 50),
        "p99": pct(total, 99),
        "stages_ms": {
            "dispatch_p50": pct(disp, 50), "dispatch_p99": pct(disp, 99),
            "drain_p50": pct(dr, 50), "drain_p99": pct(dr, 99),
        },
    }


def measure_device_cost(ex, run_batches) -> dict:
    """Device cost plane (ISSUE 18): a short pass with the device-time
    sampler armed at rate 1 (every dispatch fenced + timed), run AFTER
    the timed region so the fences never tax the headline numbers,
    then the exact live HBM bytes per executor plane — the bench
    record of the kernel_device_ms / device_arena_bytes series."""
    from hstream_tpu.stats.devicecost import DEVICE_TIME

    DEVICE_TIME.reset()
    DEVICE_TIME.arm(1)
    try:
        run_batches()
        pct = DEVICE_TIME.percentiles()
    finally:
        DEVICE_TIME.disarm()
        DEVICE_TIME.reset()
    fn = getattr(ex, "device_plane_bytes", None)
    planes = fn() if fn is not None else {}
    return {
        "device_time_ms": {
            fam: {"p50": round(v["p50"], 3), "p99": round(v["p99"], 3),
                  "samples": v["count"]}
            for fam, v in sorted(pct.items())},
        "hbm_bytes": {"total": int(sum(planes.values())),
                      "planes": {k: int(v)
                                 for k, v in sorted(planes.items())}},
    }


@functools.lru_cache(maxsize=1)
def _rtt_step():
    """Memoized ping kernel: the jit used to be built inside
    measure_rtt, retracing on every call (hstream-analyze,
    retrace-uncached-jit)."""
    import jax

    return jax.jit(lambda x: x + 1)


def measure_rtt() -> float:
    import jax.numpy as jnp

    f = _rtt_step()
    d = f(jnp.zeros(8, jnp.int32))
    np.asarray(d[0])
    t0 = time.perf_counter()
    reps = 5
    for _ in range(reps):
        d = f(d)
        np.asarray(d[0])
    return (time.perf_counter() - t0) / reps * 1e3


def bench_config2_hop_multi() -> dict:
    """BASELINE config 2: HOP(60s,10s) AVG/MIN/MAX multi-agg, 1k keys."""
    from hstream_tpu.engine import (
        AggKind, AggSpec, AggregateNode, ColumnType, HoppingWindow,
        QueryExecutor, Schema, SourceNode,
    )
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.engine.pipeline import IngestPipeline

    schema = Schema.of(device=ColumnType.STRING, v=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("device")],
        window=HoppingWindow(60_000, 10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.AVG, "avg", input=Col("v")),
              AggSpec(AggKind.MIN, "lo", input=Col("v")),
              AggSpec(AggKind.MAX, "hi", input=Col("v"))])
    ex = QueryExecutor(node, schema, emit_changes=False,
                       initial_keys=1024, batch_capacity=BATCH)
    ex.defer_close_decode = True
    for k in range(N_KEYS):
        ex.key_id_for((f"d{k}",))
    pipe = IngestPipeline(ex, depth=PIPELINE_DEPTH,
                          workers=ENCODE_WORKERS)
    src = BatchSource(seed=2)
    warm, meas = 12, 40
    for _ in range(warm):
        kids, ts, cols = src.next()
        pipe.submit(kids, ts, {"v": cols["temp"]})
    pipe.flush()
    ex.drain_closed()
    force(ex)
    t0 = time.perf_counter()
    for _ in range(meas):
        kids, ts, cols = src.next()
        pipe.submit(kids, ts, {"v": cols["temp"]})
    pipe.flush()
    rows = len(ex.drain_closed())
    force(ex)
    dt = time.perf_counter() - t0

    def _armed_batches():
        for _ in range(8):
            kids_, ts_, cols_ = src.next()
            pipe.submit(kids_, ts_, {"v": cols_["temp"]})
        pipe.flush()
        ex.drain_closed()
        ex.block_until_ready()

    device_cost = measure_device_cost(ex, _armed_batches)
    pipe.close()
    return {"events_per_sec": round(meas * BATCH / dt),
            "emitted_rows": rows,
            "device_time_ms": device_cost["device_time_ms"],
            "hbm_bytes": device_cost["hbm_bytes"]}


def _session_quantile_executor():
    from hstream_tpu.engine import ColumnType, Schema
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec, \
        SourceNode
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.window import SessionWindow

    schema = Schema.of(user=ColumnType.STRING, lat=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("user")],
        window=SessionWindow(5_000, grace_ms=0),
        aggs=[AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("lat"),
                      quantile=0.5),
              AggSpec(AggKind.APPROX_QUANTILE, "p99", input=Col("lat"),
                      quantile=0.99)])
    return SessionExecutor(node, schema, emit_changes=False)


def bench_config4_session_quantile() -> dict:
    """BASELINE config 4: APPROX_QUANTILE p50/p99 over session windows —
    now the DEVICE session path (ISSUE 10): per-batch chain merge as ONE
    fused lattice dispatch, columnar ingest (the server's
    _session_columns shape, pre-generated so the timed region measures
    the engine), deferred pow2-stacked close extracts (one fetch per
    drain, not per cycle — on a tunneled link each fetch is a round
    trip), ColumnarEmit decode. Batches are 16k rows, the columnar
    producer shape (the join bench's batching, scaled), over the same
    session dynamics as the r01-r05 rounds: 200 keys, 5s gap, 20s
    stride (> 2*gap, so prior sessions close every batch)."""
    ex = _session_quantile_executor()
    host_ref_eps = None
    rng = np.random.default_rng(4)
    n, batches = 1 << 14, 50
    base = 1_700_000_000_000
    stride = 20_000  # > 2*gap: prior sessions close every batch
    users = np.array([f"u{i}" for i in range(200)])
    kcols = [users[rng.integers(0, 200, n)] for _ in range(8)]
    vcols = [np.abs(rng.normal(50, 20, n)) for _ in range(8)]
    ts_template = (np.arange(n, dtype=np.int64) % 1000)
    ex.defer_close_decode = True

    def feed(ex_, b):
        return ex_.process_columnar(
            base + b * stride + ts_template,
            {"user": kcols[b % 8], "lat": vcols[b % 8]})

    for b in range(5):  # warmup/compile (activation + steady shapes)
        feed(ex, b)
    ex.drain_closed()
    best = None
    b0 = 5
    for _rep in range(2):
        dispatch_ms: list[float] = []
        stats0 = dict(ex.session_stats)
        emitted = 0
        t0 = time.perf_counter()
        for b in range(b0, b0 + batches):
            t1 = time.perf_counter()
            emitted += len(feed(ex, b))
            dispatch_ms.append((time.perf_counter() - t1) * 1e3)
        emitted += len(ex.drain_closed())  # deferred closes, stacked
        dt = time.perf_counter() - t0
        b0 += batches
        st = ex.session_stats
        d_batches = st["batches"] - stats0["batches"]
        d_steps = st["step_dispatches"] - stats0["step_dispatches"]
        res = {
            "events_per_sec": round(batches * n / dt),
            "emitted_rows": emitted,
            # fused-session contract: ONE step dispatch per micro-batch
            "session_dispatches_per_batch": round(
                d_steps / max(d_batches, 1), 3),
            "p50_session_dispatch_ms": round(
                float(np.percentile(dispatch_ms, 50)), 3),
            "p99_session_dispatch_ms": round(
                float(np.percentile(dispatch_ms, 99)), 3),
        }
        if best is None or res["events_per_sec"] > best["events_per_sec"]:
            best = res
    best["device_mode"] = (ex._dev or {}).get("mode")
    best["host_fallbacks"] = ex.device_fallbacks
    best["session_stats"] = dict(ex.session_stats)
    # end-to-end freshness (ISSUE 13): submit -> emitted session rows,
    # dispatch/drain split (stride > 2*gap, so every batch closes the
    # prior sessions — each batch samples)
    best["freshness_ms"] = measure_freshness(
        lambda b: feed(ex, b0 + b), ex.drain_closed, 20)
    b0 += 20

    def _armed_batches():
        for b in range(8):
            feed(ex, b0 + b)
        ex.drain_closed()
        ex.block_until_ready()

    device_cost = measure_device_cost(ex, _armed_batches)
    best["device_time_ms"] = device_cost["device_time_ms"]
    best["hbm_bytes"] = device_cost["hbm_bytes"]
    # the retained host engine on the same feed, for the r05 lineage
    # (3 batches only — it is ~10x slower; scaled to eps)
    exh = _session_quantile_executor()
    exh.use_device_sessions = False
    for b in range(2):
        feed(exh, b)
    t0 = time.perf_counter()
    for b in range(2, 5):
        feed(exh, b)
    host_ref_eps = round(3 * n / (time.perf_counter() - t0))
    best["host_reference_eps"] = host_ref_eps
    return best


def bench_config5_join_view() -> dict:
    """BASELINE config 5: stream-stream interval JOIN + GROUP BY into a
    materialized view — the DEVICE-RESIDENT join path: per-side device
    stores, ONE fused probe+insert+aggregate dispatch per micro-batch
    (matches scatter straight into the downstream lattice — zero
    per-batch D2H), columnar changelog decode on the deferred extract
    drains. Batches are pre-generated COLUMNAR (the server's join
    ingest shape), so the timed region measures the engine, not dict
    building."""
    from hstream_tpu.sql.codegen import make_executor, stream_codegen

    plan = stream_codegen(
        "SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
        "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k "
        "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    ex = make_executor(plan, sample_rows=[{"k": "k0", "x": 1.0}],
                       batch_capacity=1 << 15)
    rng = np.random.default_rng(5)
    n, batches = 8192, 20
    n_keys = 4000  # scaled with n so matches-per-record (~4) stays at
                   # the old 2048-row config's amplification
    base = 1_700_000_000_000
    keys = np.array([f"k{i}" for i in range(n_keys)], object)
    # pre-generated columnar batches (keys cycle, ts regenerated per
    # use so stream time advances)
    kcols = [keys[rng.integers(0, n_keys, n)] for _ in range(8)]
    xcol = np.ones(n, np.float32)

    def mk(b):
        ts = base + b * 500 + np.sort(rng.integers(0, 500, n)) \
            .astype(np.int64)
        return kcols[b % 8], ts

    joined = 0
    warm = 14
    # pipeline the changelog fetches behind later batches' host work,
    # fetch them in batched async device->host transfers (the knobs
    # proxy through the join onto its downstream aggregate), defer +
    # stack the probe match fetches the same way, and coalesce matches
    # so each inner step (a round trip) covers many input batches
    ex.defer_change_decode = True
    ex.change_drain_depth = 8
    ex.async_change_drain = True
    ex.match_drain_depth = 8
    for b in range(warm):  # warmup/compile (incl. coalesced step shapes)
        kk, ts = mk(b)
        ex.process_columnar(ts, {"k": kk, "x": xcol},
                            stream="l" if b % 2 else "r")
        if b == 1:
            ex.coalesce_rows = 1 << 15
    ex.flush_changes()
    ex.block_until_ready()
    # best-of-2 sustained runs (same methodology as the headline): the
    # link's run-to-run spread otherwise swamps the engine's number
    best = None
    b0 = warm
    for _rep in range(2):
        joined = 0
        probe_ms: list[float] = []
        stats0 = dict(getattr(ex, "join_stats", {}))
        t0 = time.perf_counter()
        for b in range(b0, batches + b0):
            kk, ts = mk(b)
            t1 = time.perf_counter()
            out = ex.process_columnar(ts, {"k": kk, "x": xcol},
                                      stream="l" if b % 2 else "r")
            probe_ms.append((time.perf_counter() - t1) * 1e3)
            joined += len(out)
        joined += len(ex.flush_changes())  # staged matches + changes
        dt = time.perf_counter() - t0
        b0 += batches
        js = getattr(ex, "join_stats", {})
        d_batches = js.get("probe_batches", 0) - stats0.get(
            "probe_batches", 0)
        d_disp = js.get("probe_dispatches", 0) - stats0.get(
            "probe_dispatches", 0)
        res = {
            "events_per_sec": round(batches * n / dt),
            "change_rows_per_sec": round(joined / dt),
            # fused-probe contract: ONE device dispatch per join
            # micro-batch (>1.0 = overflow redos or a fusion break)
            "probe_dispatches_per_batch": round(
                d_disp / max(d_batches, 1), 3),
            "p50_probe_dispatch_ms": round(
                float(np.percentile(probe_ms, 50)), 3),
            "p99_probe_dispatch_ms": round(
                float(np.percentile(probe_ms, 99)), 3),
        }
        if best is None or res["events_per_sec"] > best["events_per_sec"]:
            best = res
    best["join_stats"] = dict(getattr(ex, "join_stats", {}))

    # end-to-end freshness (ISSUE 13): submit -> changelog rows
    # decoded, dispatch/drain split (flush forces the deferred match
    # and change extracts per sample)
    def _join_feed(b):
        kk, ts = mk(b0 + b)
        return ex.process_columnar(ts, {"k": kk, "x": xcol},
                                   stream="l" if b % 2 else "r")

    best["freshness_ms"] = measure_freshness(
        _join_feed, ex.flush_changes, 16)

    def _armed_batches():
        for b in range(8):
            _join_feed(16 + b)
        ex.flush_changes()
        ex.block_until_ready()

    device_cost = measure_device_cost(ex, _armed_batches)
    best["device_time_ms"] = device_cost["device_time_ms"]
    best["hbm_bytes"] = device_cost["hbm_bytes"]
    best.update(bench_changelog_decode())
    return best


def bench_changelog_decode() -> dict:
    """Dedicated changelog-decode throughput: time the batched columnar
    decode (unpack_touched_rows -> key reverse-index gather ->
    ColumnarEmit) of one touched extract against the retained per-row
    reference — rows/s, engine-side only (no device in the loop)."""
    from hstream_tpu.engine import (
        AggKind, AggSpec, AggregateNode, ColumnType, QueryExecutor,
        Schema, SourceNode, TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("device")],
        window=TumblingWindow(10_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.SUM, "t", input=Col("temp"))])
    ex = QueryExecutor(node, schema, emit_changes=True,
                       initial_keys=4096, batch_capacity=1 << 15)
    ex.defer_change_decode = True
    rng = np.random.default_rng(9)
    n_keys = 4000
    for k in range(n_keys):
        ex.key_id_for((f"d{k}",))
    kids = rng.integers(0, n_keys, 1 << 14).astype(np.int32)
    temps = rng.normal(20, 5, 1 << 14).astype(np.float32)
    ts = 1_700_000_000_000 + np.arange(1 << 14, dtype=np.int64) % 200
    ex.process_columnar(kids, ts, {"temp": temps})
    epoch, buf = ex._pending_changes[0]
    pk = np.asarray(buf)
    rows = len(ex._decode_changes_rows(pk, epoch))
    reps = 20
    from hstream_tpu.common import columnar as _col

    # force all the way to the wire record: ColumnarEmit.to_payload
    # encodes straight from the columns, the per-row reference pays
    # dict rows + the row-wise payload scan — the two real sink paths
    t0 = time.perf_counter()
    for _ in range(reps):
        _col.rows_to_payload(ex._decode_changes(pk, epoch), 0)
    col_dt = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        _col.rows_to_payload(ex._decode_changes_rows(pk, epoch), 0)
    row_dt = (time.perf_counter() - t0) / reps
    return {
        "change_decode_rows_per_sec": round(rows / col_dt),
        "change_decode_rows_per_sec_perrow_ref": round(rows / row_dt),
    }


def bench_store_append(tmpdir: str) -> dict:
    """Native store append bench (the reference's writeBench.hs:29-60
    analogue): the SYNC fsync-per-call path (records/s, MB/s, avg/p99
    append latency) AND the async completion-queue path (ISSUE 12 /
    VERDICT weak #7: `append_async` existed unbenched while the ~93k
    rec/s sync number was quoted as the store's ceiling) — submissions
    pipeline into the C++ queue and group-commit, so the async number
    is the one the sharded append front actually feeds."""
    import shutil

    from hstream_tpu.store import open_store

    path = tmpdir + "/benchstore"
    shutil.rmtree(path, ignore_errors=True)
    store = open_store(path)
    try:
        store.create_log(4242)
        payload = bytes(256)
        batch = [payload] * 100
        for _ in range(20):  # warmup
            store.append_batch(4242, batch)
        lat = []
        t0 = time.perf_counter()
        n_batches = 400
        for _ in range(n_batches):
            t1 = time.perf_counter()
            store.append_batch(4242, batch)
            lat.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        recs = n_batches * len(batch)
        out = {
            "records_per_sec": round(recs / dt),
            "mb_per_sec": round(recs * len(payload) / dt / 1e6, 1),
            "avg_append_ms": round(float(np.mean(lat)) * 1e3, 3),
            "p99_append_ms": round(float(np.percentile(lat, 99)) * 1e3,
                                   3),
        }
        if hasattr(store, "append_async"):
            for _ in range(20):  # completion-queue warmup
                store.append_async(4242, batch).result(timeout=30)
            futs = []
            t0 = time.perf_counter()
            for _ in range(n_batches):
                futs.append(store.append_async(4242, batch))
            for f in futs:
                f.result(timeout=60)
            dt = time.perf_counter() - t0
            out["records_per_sec_async"] = round(recs / dt)
            out["mb_per_sec_async"] = round(
                recs * len(payload) / dt / 1e6, 1)
            out["async_vs_sync"] = round(
                out["records_per_sec_async"]
                / max(out["records_per_sec"], 1), 2)
        else:
            # mem:// fallback: same record shape, all-None async keys
            out["records_per_sec_async"] = None
            out["mb_per_sec_async"] = None
            out["async_vs_sync"] = None
        return out
    finally:
        store.close()
        shutil.rmtree(path, ignore_errors=True)


def bench_snapshot_overhead() -> dict:
    """Snapshot stall under sustained ingest at 100K live keys
    (VERDICT r4 weak #7 / SURVEY §7 item 8): ingest eps with the
    periodic snapshot+checkpoint machinery ON (500ms cadence) vs OFF,
    through the real server path. Captures are device-side references;
    serialization + store writes ride the background persist worker,
    so the overhead target is <5%."""
    import grpc

    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    KEYS = 100_000
    n, batches = 1 << 16, 6
    rng = np.random.default_rng(7)
    base = 1_700_000_000_000
    devs = np.array([f"dev{k}" for k in range(KEYS)])

    def run(interval_ms: int) -> float:
        server, ctx = serve("127.0.0.1", 0, "mem://",
                            snapshot_interval_ms=interval_ms)
        ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
        stub = HStreamApiStub(ch)
        try:
            stub.CreateStream(pb.Stream(stream_name="snap"))
            # close-based emission (no EMIT CHANGES): nothing emits
            # during the run, so the measurement isolates ingest +
            # snapshot machinery, not changelog decode
            stub.ExecuteQuery(pb.CommandQuery(
                stmt_text="CREATE STREAM snapout AS SELECT device, "
                          "COUNT(*) AS c, SUM(t) AS s FROM snap "
                          "GROUP BY device, "
                          "TUMBLING (INTERVAL 600 SECOND) "
                          "GRACE BY INTERVAL 0 SECOND;"))
            time.sleep(0.5)
            task = next(iter(ctx.running_queries.values()))
            payloads = []
            for b in range(batches + 2):
                ts = base + b * 200 + np.sort(rng.integers(0, 200, n))
                payloads.append((int(ts[-1]), rec.build_columnar_record(
                    ts.astype(np.int64),
                    {"device": devs[rng.integers(0, KEYS, n)],
                     "t": rng.normal(20, 5, n).astype(np.float32)})))

            def drain_to(target: int) -> None:
                deadline = time.time() + 180
                while time.time() < deadline:
                    ex = task.executor
                    if ex is not None and ex.watermark_abs >= target:
                        return
                    time.sleep(0.02)
                raise TimeoutError("snapshot bench did not drain")

            for last, p in payloads[:2]:  # warmup/compile
                req = pb.AppendRequest(stream_name="snap")
                req.records.append(p)
                stub.Append(req)
            drain_to(payloads[1][0])
            t0 = time.perf_counter()
            for last, p in payloads[2:]:
                req = pb.AppendRequest(stream_name="snap")
                req.records.append(p)
                stub.Append(req)
            drain_to(payloads[-1][0])
            return batches * n / (time.perf_counter() - t0)
        finally:
            ch.close()
            server.stop(grace=1)
            ctx.shutdown()

    eps_off = run(1 << 30)
    eps_on = run(500)
    return {
        "keys": KEYS,
        "events_per_sec_snapshots_off": round(eps_off),
        "events_per_sec_snapshots_on": round(eps_on),
        "overhead_pct": round(max(0.0, (eps_off - eps_on) / eps_off)
                              * 100, 2),
    }


def server_path_eps() -> dict:
    """Measured Append -> push-query throughput through the REAL gRPC
    server (loopback socket): the product path, not the library fast
    path. Returns three ingest numbers —
      server_columnar_eps     framed AppendColumnarStream micro-batches
                              (THE guarded served-path metric, ISSUE 12)
      server_columnar_pb_eps  the same batches as protobuf Append
                              records (the legacy columnar path)
      server_json_eps         per-record JSON appends
    — plus per-stage append timings (decode/admit/handoff/store) from
    the stage histograms and the append-front counters."""
    import grpc

    from hstream_tpu.client.producer import encode_batch
    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    server, ctx = serve("127.0.0.1", 0, "mem://")
    # fetch responses expand columnar records per-row: raise the
    # client-side receive cap to the server's send cap
    ch = grpc.insecure_channel(
        f"127.0.0.1:{ctx.port}",
        options=[("grpc.max_receive_message_length", 64 * 1024 * 1024)])
    stub = HStreamApiStub(ch)
    out: dict[str, float] = {}
    try:
        stub.CreateStream(pb.Stream(stream_name="bsrc"))
        q = stub.CreateQuery(pb.CreateQueryRequest(
            query_text="SELECT device, COUNT(*) AS c, SUM(temp) AS s "
                       "FROM bsrc GROUP BY device, "
                       "TUMBLING (INTERVAL 10 SECOND) "
                       "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
        time.sleep(0.5)  # task attach
        task = ctx.running_queries[q.id]
        rng = np.random.default_rng(1)

        def drain_to(ts_target: float) -> None:
            deadline = time.time() + 120
            while time.time() < deadline:
                ex = task.executor
                if ex is not None and ex.watermark_abs >= ts_target:
                    return
                time.sleep(0.02)
            raise TimeoutError("server path did not drain")

        # columnar batches, protobuf Append records (the legacy path)
        n, batches = 1 << 18, 12
        base = 1_700_000_000_000
        devs = np.array([f"d{k}" for k in range(N_KEYS)])

        def mk_cols(b):
            return {"device": devs[rng.integers(0, N_KEYS, n)],
                    "temp": (np.rint(rng.normal(20, 5, n) * 10)
                             .astype(np.float32) * np.float32(0.1))}

        payloads = []
        for b in range(2):
            ts = base + b * 200 + np.sort(rng.integers(0, 200, n))
            payloads.append((int(ts[-1]), rec.build_columnar_record(
                ts.astype(np.int64), mk_cols(b))))
        for last, p in payloads:  # warmup (compile)
            req = pb.AppendRequest(stream_name="bsrc")
            req.records.append(p)
            stub.Append(req)
        drain_to(payloads[1][0])

        # columnar phases: the FRAMED fast path (ISSUE 12) — THE
        # guarded served-path number, N micro-batches in ONE
        # AppendColumnarStream call (bounds-check + handoff, no
        # per-record protobuf) — vs the legacy protobuf-record path.
        # Both are drain-bound at this batch size, so a single-shot
        # phase is noise-dominated: best-of-2, INTERLEAVED, so neither
        # path owns the warmer slot.
        slot = [0]  # each phase takes a fresh ts window slot

        def run_framed() -> int:
            slot[0] += 1
            fb = base + slot[0] * 10 * 60_000
            frames = []
            for b in range(batches + 2):
                ts = fb + b * 200 + np.sort(rng.integers(0, 200, n))
                frames.append((int(ts[-1]), encode_batch(
                    ts.astype(np.int64), mk_cols(b))))
            stub.AppendColumnarStream(iter(
                [pb.AppendColumnarRequest(stream_name="bsrc",
                                          blocks=[f])
                 for _last, f in frames[:2]]))
            drain_to(frames[1][0])
            t0 = time.perf_counter()
            resp = stub.AppendColumnarStream(iter(
                [pb.AppendColumnarRequest(stream_name="bsrc",
                                          blocks=[f])
                 for _last, f in frames[2:]]))
            drain_to(frames[-1][0])
            eps = round(batches * n / (time.perf_counter() - t0))
            assert resp.rows == batches * n
            return eps

        def run_pb() -> int:
            slot[0] += 1
            pbase = base + slot[0] * 10 * 60_000
            payloads = []
            for b in range(batches + 2):
                ts = pbase + b * 200 + np.sort(rng.integers(0, 200, n))
                payloads.append((int(ts[-1]), rec.build_columnar_record(
                    ts.astype(np.int64), mk_cols(b))))
            for last, p in payloads[:2]:
                req = pb.AppendRequest(stream_name="bsrc")
                req.records.append(p)
                stub.Append(req)
            drain_to(payloads[1][0])
            t0 = time.perf_counter()
            for last, p in payloads[2:]:
                req = pb.AppendRequest(stream_name="bsrc")
                req.records.append(p)
                stub.Append(req)
            drain_to(payloads[-1][0])
            return round(batches * n / (time.perf_counter() - t0))

        framed_runs = [run_framed()]
        pb_runs = [run_pb()]
        framed_runs.append(run_framed())
        pb_runs.append(run_pb())
        out["server_columnar_eps"] = max(framed_runs)
        out["server_columnar_eps_runs"] = framed_runs
        out["server_columnar_pb_eps"] = max(pb_runs)
        out["server_columnar_pb_eps_runs"] = pb_runs
        front = getattr(ctx, "append_front", None)
        if front is not None:
            out["append_front"] = front.stats()

        def stage_pct(stage: str, q: float):
            v = ctx.stats.histogram_percentile("stage_latency_ms",
                                               stage, q)
            return None if v is None else round(v, 3)

        # profile-first (ISSUE 12): where the append milliseconds live
        out["append_stages_ms"] = {
            f"{s.removeprefix('append_')}_{q}": stage_pct(s, qq)
            for s in ("append_decode", "append_admit",
                      "append_handoff", "append_store")
            for q, qq in (("p50", 50), ("p99", 99))}

        # per-record JSON appends (the reference-style path); warmup
        # compiles BOTH coalesced step shapes the timed phase can hit:
        # single-append polls (small cap) and burst coalesces (big cap)
        jn, jb, jwarm = 1000, 50, 10
        base2 = base + 60 * 60_000
        reqs = []
        for b in range(jb):
            req = pb.AppendRequest(stream_name="bsrc")
            for i in range(jn):
                req.records.append(rec.build_record(
                    {"device": f"d{i % N_KEYS}", "temp": 21.5},
                    publish_time_ms=base2 + b * 200 + i // 5))
            reqs.append((base2 + b * 200 + (jn - 1) // 5, req))
        for last, req in reqs[:3]:          # slow: one append per poll
            stub.Append(req)
            drain_to(last)
        for last, req in reqs[3:jwarm]:     # burst: big coalesce shape
            stub.Append(req)
        drain_to(reqs[jwarm - 1][0])
        t0 = time.perf_counter()
        for last, req in reqs[jwarm:]:
            stub.Append(req)
        drain_to(reqs[-1][0])
        out["server_json_eps"] = round(
            (jb - jwarm) * jn / (time.perf_counter() - t0))

        # exercise the Fetch RPC so the BENCH record carries fetch
        # percentiles alongside append's (ISSUE 3: host-side breakdown)
        stub.CreateSubscription(pb.Subscription(
            subscription_id="bench-sub", stream_name="bsrc"))
        for _ in range(50):
            # max_size counts store BATCHES and the subscription
            # expands columnar records per-row at the wire, so one
            # 256k-row batch is already ~16MB of response — larger
            # windows blow the 64MB gRPC message cap
            stub.Fetch(pb.FetchRequest(subscription_id="bench-sub",
                                       timeout_ms=10, max_size=1))

        # RPC latency percentiles from the server's fixed-bucket
        # histograms + the running task's stage occupancy: the
        # host-side breakdown, not just ev/s
        stats = ctx.stats

        def pct(metric: str, q: float):
            v = stats.histogram_percentile(metric, "", q)
            return None if v is None else round(v, 3)

        out["rpc_histograms_ms"] = {
            "append_p50": pct("append_latency_ms", 50),
            "append_p99": pct("append_latency_ms", 99),
            "fetch_p50": pct("fetch_latency_ms", 50),
            "fetch_p99": pct("fetch_latency_ms", 99),
        }

        # end-to-end freshness of the SERVED path (ISSUE 13): the
        # server's own freshness plane, observed during the phases
        # above — append->visible p50/p99 plus the per-stage lag
        # breakdown (ingest / engine / delivery; delivery samples come
        # from the subscription fetches)
        def fpct(metric: str, label: str, q: float):
            v = stats.histogram_percentile(metric, label, q)
            return None if v is None else round(v, 3)

        out["freshness_ms"] = {
            "p50": fpct("append_visible_latency_ms", "", 50),
            "p99": fpct("append_visible_latency_ms", "", 99),
        }
        out["freshness_stages_ms"] = {
            f"{stage}_{qn}": fpct("freshness_lag_ms", stage, qq)
            for stage in ("ingest", "engine", "delivery")
            for qn, qq in (("p50", 50), ("p99", 99))}
        pipe = getattr(task, "_pipe", None)
        if pipe is not None:
            out["server_pipeline_stages"] = {
                k: round(v, 4) for k, v in pipe.stats().items()}
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()
    return out


def _loopback_server_path() -> dict:
    """Run `bench.py --loopback` in a subprocess pinned to the local
    CPU backend and return its server-path metrics. A subprocess
    because JAX's platform is fixed at first import — the parent may
    already hold the tunneled accelerator."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--loopback"],
        capture_output=True, text=True, timeout=900, env=env)
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            d = json.loads(line)
            for k in ("metric", "unit", "mode", "value"):
                d.pop(k, None)
            d["server_bench_platform"] = d.pop("platform", "cpu")
            return d
    raise RuntimeError(
        f"loopback bench emitted no JSON (rc {proc.returncode}): "
        f"{proc.stderr[-400:]}")


def bench_read_plane() -> dict:
    """Read plane (ISSUE 20): N concurrent pull readers over one live
    view — the snapshot cache must collapse them onto ~one executor
    extract per close cycle (extracts_per_read -> 1/N) — plus the
    shared-encode fan-out phase: one columnar sink record delivered to
    M consumers costs ONE expansion (encode_amortization -> M)."""
    import threading

    from hstream_tpu.common import columnar, locktrace
    from hstream_tpu.common import records as rec
    from hstream_tpu.server.readcache import ReadCache
    from hstream_tpu.server.subscriptions import _expand_columnar
    from hstream_tpu.server.views import Materialization
    from hstream_tpu.sql.codegen import stream_codegen

    N_READERS = 8
    DURATION_S = 3.0
    ex, feed, warm = _smoke_tumbling_config()

    class _Owner:  # the QueryTask surface the read path needs
        state_lock = locktrace.rlock("tasks.state")
        executor = ex

    mat = Materialization(group_cols=["device"])
    mat.task = _Owner()
    sel = stream_codegen("SELECT * FROM v;").select
    cache = ReadCache()

    batch_i = [0]

    def feed_locked():
        # engine mutations under the task lock, exactly like the real
        # query loop — the version probe's exactness depends on it
        with _Owner.state_lock:
            i = batch_i[0]
            batch_i[0] += 1
            rows = feed(i)
            if rows is not None and len(rows):
                mat.add_closed(rows)

    for _ in range(warm):
        feed_locked()
    cache.serve_view("v", mat, sel, "q")  # warm the extract shapes
    ex.block_until_ready()

    stop = threading.Event()
    reads = [0] * N_READERS

    def reader(slot):
        while not stop.is_set():
            cache.serve_view("v", mat, sel, "q")
            reads[slot] += 1

    extracts0 = cache.extracts
    threads = [threading.Thread(target=reader, args=(i,))
               for i in range(N_READERS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    fed = 0
    while time.perf_counter() - t0 < DURATION_S:
        feed_locked()
        fed += 1
        time.sleep(0.005)
    stop.set()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    total_reads = sum(reads)
    extracts = cache.extracts - extracts0

    # fan-out phase: M consumers of one immutable columnar record
    M = 256
    rows = [{"k": f"g{i}", "c": i} for i in range(64)]
    payload = rec.build_record(
        columnar.rows_to_payload(rows, 1_700_000_000_000)
    ).SerializeToString()
    t1 = time.perf_counter()
    for _ in range(M):
        frames = _expand_columnar(payload)
    t_direct = time.perf_counter() - t1
    fan = ReadCache()
    t2 = time.perf_counter()
    for _ in range(M):
        frames = fan.expand_frames(1, 1, 0, payload, _expand_columnar)
    t_shared = time.perf_counter() - t2
    assert frames is not None and fan.stats()["expand_misses"] == 1
    return {
        "readers": N_READERS,
        "reads_per_sec": round(total_reads / dt),
        "batches_fed": fed,
        "extracts": extracts,
        # ~1/N: one extract serves every concurrent reader of a cycle
        "extracts_per_read": round(extracts / max(total_reads, 1), 4),
        "extracts_per_reader": round(
            extracts / max(total_reads / N_READERS, 1), 4),
        "hit_ratio": round(cache.hit_ratio(), 4),
        "fanout_consumers": M,
        # M consumers per single encode (expand_misses == 1)
        "encode_amortization": M / fan.stats()["expand_misses"],
        "encode_once_speedup": round(t_direct / max(t_shared, 1e-9), 1),
    }


def main() -> None:
    import jax

    from hstream_tpu.engine import transport as tp
    from hstream_tpu.engine.pipeline import IngestPipeline

    ex = build_executor()
    src = BatchSource()
    pipe = IngestPipeline(ex, depth=PIPELINE_DEPTH,
                          workers=ENCODE_WORKERS)

    for _ in range(WARMUP_BATCHES):
        kids, ts, cols = src.next()
        pipe.submit(kids, ts, cols)
    pipe.flush()
    ex.drain_closed()
    force(ex)
    try:
        # warmup RUN, excluded from best-of-3 (and from the profiler
        # trace + stage occupancies): same shape as a timed run, so the
        # first measured run pays no cold-link/allocator tax
        for _ in range(WARMUP_RUN_BATCHES):
            kids, ts, cols = src.next()
            pipe.submit(kids, ts, cols)
        pipe.flush()
        ex.drain_closed()
        force(ex)
    except Exception as e:  # noqa: BLE001 — warmup is best-effort
        print(f"# warmup run failed: {type(e).__name__}: {e}",
              flush=True)
    pipe.reset_stats()  # stage occupancies cover the timed region only

    import contextlib
    import os

    from hstream_tpu.common.tracing import jax_profiler

    profile_dir = os.environ.get("HSTREAM_PROFILE_DIR")
    prof = (jax_profiler(profile_dir) if profile_dir
            else contextlib.nullcontext())
    # 3 sustained runs: the timed region includes the host->device
    # uploads, and the dev chip rides a shared tunnel whose bandwidth
    # swings >10x between minutes — the headline is EXPLICITLY the best
    # run ("methodology" field); every run and the median are reported
    # so cross-round comparisons can use either
    from hstream_tpu.common.tracing import RetraceGuard

    runs: list[tuple[float, float]] = []  # (eps, measured elapsed_s)
    run_recompiles: list[int] = []        # XLA compiles per timed run
    emitted_rows = 0
    events = MEASURE_BATCHES * BATCH
    budget_t0 = time.perf_counter()
    with prof:  # HSTREAM_PROFILE_DIR=... captures a TensorBoard trace
        for _run in range(3):
            if runs and time.perf_counter() - budget_t0 > 240:
                # slow-link window: stop re-running so the whole bench
                # stays inside the driver's time budget
                print(f"# headline budget hit after {len(runs)} run(s)",
                      flush=True)
                break
            try:
                guard = RetraceGuard()
                t_start = time.perf_counter()
                with guard:
                    for _ in range(MEASURE_BATCHES):
                        kids, ts, cols = src.next()
                        pipe.submit(kids, ts, cols)
                    pipe.flush()
                    emitted_rows += len(ex.drain_closed())
                    force(ex)  # all dispatched work in timed region
                dt = time.perf_counter() - t_start
                runs.append((events / dt, dt))
                run_recompiles.append(guard.count)
            except Exception as e:  # noqa: BLE001 — transient tunnel
                # failures must not void the whole benchmark record
                print(f"# run {_run} failed: {type(e).__name__}: {e}",
                      flush=True)
                try:  # drain leftovers so the next run starts clean
                    pipe.flush()
                    ex.drain_closed()
                    force(ex)
                except Exception:
                    pass
    if not runs:
        raise RuntimeError("all headline runs failed")
    eps, elapsed = max(runs)  # best run, with ITS measured wall time
    # per-stage pipeline occupancy over the timed region: encode (host
    # wire pack, summed over workers), upload wait (H2D double-buffer
    # backpressure), step (ordered dispatch + bookkeeping), drain
    # (deferred change/close decode)
    pipeline_stages = {k: round(v, 4) for k, v in pipe.stats().items()}

    close_ms, close_dispatch_ms = measure_close_latency(ex, pipe, src)
    p99_close = (float(np.percentile(close_ms, 99)) if close_ms else None)
    # end-to-end freshness of the tumbling config (ISSUE 13): the
    # close-latency samples ARE emit freshness — submit of the
    # boundary-crossing batch -> closed rows decoded on host — split
    # into dispatch (ingest + extract/reset dispatch) and drain (the
    # D2H fetch + columnar decode)
    if close_ms:
        drain_ms = [t - d for t, d in zip(close_ms, close_dispatch_ms)]

        def _pctf(xs, q):
            return round(float(np.percentile(xs, q)), 3)

        freshness = {
            "samples": len(close_ms),
            "p50": _pctf(close_ms, 50), "p99": _pctf(close_ms, 99),
            "stages_ms": {
                "dispatch_p50": _pctf(close_dispatch_ms, 50),
                "dispatch_p99": _pctf(close_dispatch_ms, 99),
                "drain_p50": _pctf(drain_ms, 50),
                "drain_p99": _pctf(drain_ms, 99),
            },
        }
    else:
        freshness = {"samples": 0}
    kernel_eps = kernel_only_eps(ex, src)
    rtt_ms = measure_rtt()

    # wire footprint of the steady-state combo
    staged = ex.stage_columnar(*src.next())
    wire_bpe = tp.wire_bytes(staged.combo, staged.cap) / staged.cap

    def _headline_armed_batches():
        for _ in range(8):
            pipe.submit(*src.next())
        pipe.flush()
        ex.drain_closed()
        ex.block_until_ready()

    device_cost = measure_device_cost(ex, _headline_armed_batches)

    result = {
        "metric": "events_per_sec",
        "value": round(eps),
        "unit": "events/s",
        "vs_baseline": round(eps / TARGET, 4),
        "batch": BATCH,
        "batches": MEASURE_BATCHES,
        "keys": N_KEYS,
        "elapsed_s": round(elapsed, 3),
        "methodology": "warmup_run_then_best_of_3_sustained_runs",
        "runs_eps": [round(r) for r, _ in runs],
        "median_eps": round(sorted(r for r, _ in runs)[len(runs) // 2]),
        # run-to-run spread (the regression guard reads median +-
        # stddev, not just the best run)
        "stddev_eps": round(float(np.std([r for r, _ in runs]))),
        "total_events": len(runs) * MEASURE_BATCHES * BATCH,
        "emitted_rows": emitted_rows,  # across all 3 runs
        "freshness_ms": freshness,
        # device cost plane (ISSUE 18): fenced per-dispatch device time
        # (sampler rate 1, post-timed-region pass) + exact arena bytes
        "device_time_ms": device_cost["device_time_ms"],
        "hbm_bytes": device_cost["hbm_bytes"],
        "p99_window_close_ms": (round(p99_close, 2)
                                if p99_close is not None else None),
        "p50_window_close_ms": (round(float(np.percentile(close_ms, 50)),
                                      2) if close_ms else None),
        # close cost NET of the device->host link: ingest + extract/
        # reset dispatch, before the blocking row fetch (the fetch is
        # floored by rtt_ms on tunneled dev chips)
        "p99_close_dispatch_ms": (round(float(np.percentile(
            close_dispatch_ms, 99)), 2) if close_dispatch_ms else None),
        "p50_close_dispatch_ms": (round(float(np.percentile(
            close_dispatch_ms, 50)), 2) if close_dispatch_ms else None),
        "n_close_samples": len(close_ms),
        # fused-close accounting: the close path's contract is one
        # lattice dispatch + one D2H fetch per cycle however many
        # windows are due — a ratio above 1.0 means the fusion regressed
        "close_dispatches_per_cycle": (round(
            ex.close_stats["close_dispatches"]
            / max(ex.close_stats["close_cycles"], 1), 3)),
        "close_fetches_per_cycle": (round(
            ex.close_stats["close_fetches"]
            / max(ex.close_stats["close_cycles"], 1), 3)),
        # retrace contract: steady-state runs compile ZERO new XLA
        # executables (the warmup run absorbs every shape) — a nonzero
        # LAST run means a shape/caching regression (RetraceGuard)
        "recompiles_per_run": (run_recompiles[-1]
                               if run_recompiles else None),
        "recompiles_runs": run_recompiles,
        "kernel_events_per_sec": round(kernel_eps),
        "wire_bytes_per_event": round(wire_bpe, 2),
        "rtt_ms": round(rtt_ms, 1),
        "pipeline_depth": PIPELINE_DEPTH,
        "encode_workers": ENCODE_WORKERS,
        "pipeline_stages": pipeline_stages,
        "platform": jax.devices()[0].platform,
    }
    def safe(label, fn, *a):
        t0 = time.perf_counter()
        try:
            return fn(*a)
        except Exception as e:  # noqa: BLE001 — keep the record partial
            print(f"# {label} failed: {type(e).__name__}: {e}",
                  flush=True)
            return {"error": f"{type(e).__name__}: {e}"}
        finally:
            print(f"# {label}: {time.perf_counter() - t0:.1f}s",
                  flush=True)

    # the RECORDED server-path numbers are measured under --loopback in
    # a subprocess pinned to the local CPU backend (ISSUE 12 satellite):
    # the tunneled dev link swings >10x minute-to-minute (BENCH_r05 rtt
    # 124.6ms), so guarding regressions on a tunneled measurement was
    # noise — the link's cost stays visible separately as rtt_ms
    sp = safe("server_path_loopback", _loopback_server_path)
    if "error" in sp:
        # loopback subprocess unavailable: fall back to in-process so
        # the record is degraded, not absent (flagged by the key)
        result["server_path_loopback_error"] = sp["error"]
        sp = safe("server_path", server_path_eps)
    if "error" in sp:
        result["server_path_error"] = sp["error"]
    else:
        result.update(sp)
    import tempfile

    result["configs"] = {
        "hop_multi_agg": safe("cfg2", bench_config2_hop_multi),
        "session_quantile": safe("cfg4", bench_config4_session_quantile),
        "join_groupby": safe("cfg5", bench_config5_join_view),
        "store_append": safe("store", bench_store_append,
                             tempfile.gettempdir()),
        "snapshot_100k": safe("snap", bench_snapshot_overhead),
        "read_plane": safe("read_plane", bench_read_plane),
    }
    print(json.dumps(result))
    pipe.close()


def _smoke_tumbling_config():
    """(executor, feed(i), warm_batches) for the fused-close retrace
    gate — shared by `--smoke` and the tier-1 RetraceGuard tests."""
    from hstream_tpu.engine import (
        AggKind, AggSpec, AggregateNode, ColumnType, QueryExecutor,
        Schema, SourceNode, TumblingWindow,
    )
    from hstream_tpu.engine.expr import Col

    schema = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("device")],
        window=TumblingWindow(1_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.SUM, "t", input=Col("temp"))])
    ex = QueryExecutor(node, schema, emit_changes=False,
                       initial_keys=256, batch_capacity=1024)
    for k in range(100):
        ex.key_id_for((f"d{k}",))
    rng = np.random.default_rng(0)
    base = 1_700_000_000_000
    n = 512
    # cycled pre-generated batches with a FIXED ts template (the
    # BatchSource pattern): the adaptive wire codec's combo — and so
    # the compiled step — is identical batch to batch; fresh random
    # data per batch would legitimately grow a new combo mid-run
    uniq = [(rng.integers(0, 100, n).astype(np.int32),
             (np.rint(rng.normal(20, 5, n) * 10).astype(np.float32)
              * np.float32(0.1)))
            for _ in range(4)]
    ts_template = (np.arange(n, dtype=np.int64) * 200) // n

    def feed(i):
        kids, temps = uniq[i % 4]
        return ex.process_columnar(kids, base + i * 200 + ts_template,
                                   {"temp": temps})

    # warmup spans >= 2 close cycles at 1s windows / 200ms batches
    return ex, feed, 15


def _smoke_join_config(mesh=None):
    """(executor, feed(b), warm_batches) for the device-join retrace
    gate — shared by `--smoke` and the tier-1 RetraceGuard tests. With
    `mesh`, the join runs key-sharded (ISSUE 16) and the feed asserts
    the sharded stores actually activated (no silent degrade)."""
    from hstream_tpu.sql.codegen import make_executor, stream_codegen

    plan = stream_codegen(
        "SELECT l.k, COUNT(*) AS c FROM l INNER JOIN r "
        "WITHIN (INTERVAL 1 SECOND) ON l.k = r.k "
        "GROUP BY l.k, TUMBLING (INTERVAL 2 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")
    ex = make_executor(plan, sample_rows=[{"k": "k0", "x": 1.0}],
                       batch_capacity=4096, mesh=mesh)
    rng = np.random.default_rng(1)
    base = 1_700_000_000_000
    keys = np.array([f"k{i}" for i in range(500)], object)
    n = 256
    xcol = np.ones(n, np.float32)
    kcols = [keys[rng.integers(0, 500, n)] for _ in range(4)]
    ts_template = (np.arange(n, dtype=np.int64) * 200) // n

    def feed(b):
        ex.process_columnar(
            base + b * 200 + ts_template,
            {"k": kcols[b % 4], "x": xcol},
            stream="l" if b % 2 else "r")
        if mesh is not None and b == 5:
            assert ex._dev is not None and \
                ex._dev.get("sjl") is not None, \
                f"join did not shard: {ex._device_refusal}"

    # warmup must reach the FIRST real eviction (stores half full at
    # ~32 batches) so the evict kernel's shape compiles before the
    # guarded region, alongside activation, fused-probe and close
    return ex, feed, 40


def _smoke_session_config(mesh=None):
    """(executor, feed(b), warm_batches) for the device-session retrace
    gate — shared by `--smoke` and the tier-1 RetraceGuard tests. With
    `mesh`, the session arena runs key-sharded (ISSUE 16) and the feed
    asserts the sharded arena actually activated."""
    from hstream_tpu.engine import ColumnType, Schema
    from hstream_tpu.engine.expr import Col
    from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec, \
        SourceNode
    from hstream_tpu.engine.session import SessionExecutor
    from hstream_tpu.engine.window import SessionWindow

    schema = Schema.of(user=ColumnType.STRING, lat=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("user")],
        window=SessionWindow(2_000, grace_ms=0),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("lat"),
                      quantile=0.5)])
    kw = {} if mesh is None else {"mesh": mesh}
    ex = SessionExecutor(node, schema, emit_changes=False, **kw)
    ex.defer_close_decode = True
    rng = np.random.default_rng(2)
    base = 1_700_000_000_000
    n = 512
    users = np.array([f"u{i}" for i in range(64)])
    # cycled pre-generated batches with a FIXED ts template (the
    # BatchSource pattern) so shapes and segment counts are stable
    kcols = [users[rng.integers(0, 64, n)] for _ in range(4)]
    vcols = [np.abs(rng.normal(50, 20, n)) for _ in range(4)]
    ts_template = (np.arange(n, dtype=np.int64) % 500)
    stride = 10_000  # > 2*gap: prior sessions close every batch

    def feed(b):
        ex.process_columnar(base + b * stride + ts_template,
                            {"user": kcols[b % 4], "lat": vcols[b % 4]})
        if b % 8 == 7:
            ex.drain_closed()  # stacked-drain shapes compile in warmup
        if mesh is not None and b == 5:
            assert ex._dev is not None and \
                ex._dev.get("ssl") is not None, \
                f"sessions did not shard: {ex._device_refusal}"

    # warmup spans activation, the first grow, close cycles, and every
    # stacked-drain depth the steady state uses
    return ex, feed, 20


def _smoke_server_columnar(batches: int = 50) -> int:
    """50-batch framed columnar-append SERVER run gating 0 steady-state
    recompiles (ISSUE 12): the whole served path — AppendColumnarStream
    -> frame door -> append front -> store -> query task -> staged
    device step -> window close — must hit only shapes the warmup
    compiled. Returns the XLA compile count over the steady batches."""
    import grpc

    from hstream_tpu.client.producer import encode_batch
    from hstream_tpu.common.tracing import RetraceGuard
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    # tracing ARMED at sample rate 1 (ISSUE 13 acceptance): every RPC
    # and task stage records spans, and the steady state must still
    # compile nothing — the span plane is host-only by construction.
    # The stats plane is likewise armed hot (ISSUE 15): the load
    # reporter folds the holder every 500ms DURING the guarded run,
    # and the guarded region itself scrapes the stats/cluster-stats
    # verbs — rate ladders, federation fold, and exposition are
    # host-only by construction too
    # the placer loop is likewise armed DURING the guarded run (ISSUE
    # 17): node-record publishes, scheduler heartbeats and the adopt/
    # rebalance sweep are config-store + host work only — steady state
    # must still compile nothing with placement decisions live
    server, ctx = serve("127.0.0.1", 0, "mem://", trace_sample=1.0,
                        load_report_interval_ms=500,
                        placer_interval_ms=200)
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="smk"))
        # request ids make every call's trace SAMPLED (trace id = rid),
        # so the guarded region below runs with span recording live on
        # the RPC path AND the query task's stage spans
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE STREAM smkout AS SELECT device, "
                      "COUNT(*) AS c, SUM(temp) AS s FROM smk "
                      "GROUP BY device, TUMBLING (INTERVAL 1 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"),
            metadata=(("x-request-id", "smoke-create"),))
        deadline = time.time() + 30
        task = None
        while time.time() < deadline:
            running = list(ctx.running_queries.values())
            if running and running[0].attached.wait(0.05):
                task = running[0]
                break
            time.sleep(0.01)
        if task is None:
            raise TimeoutError("smoke query never attached")
        rng = np.random.default_rng(6)
        n, warm = 512, 20
        base = 1_700_000_000_000
        devs = np.array([f"d{k}" for k in range(100)])
        # cycled pre-generated batches, fixed ts template (the
        # BatchSource pattern): stable wire combos -> stable shapes
        uniq = [(devs[rng.integers(0, 100, n)],
                 (np.rint(rng.normal(20, 5, n) * 10).astype(np.float32)
                  * np.float32(0.1)))
                for _ in range(4)]
        ts_template = (np.arange(n, dtype=np.int64) * 200) // n

        def frame(b):
            dv, tp = uniq[b % 4]
            ts = base + b * 200 + ts_template
            return int(ts[-1]), encode_batch(ts, {"device": dv,
                                                  "temp": tp})

        def drain_to(target: int) -> None:
            dl = time.time() + 60
            while time.time() < dl:
                ex = task.executor
                if ex is not None and ex.watermark_abs >= target:
                    return
                time.sleep(0.01)
            raise TimeoutError("server smoke did not drain")

        def stream_batches(lo: int, hi: int):
            reqs = [frame(b) for b in range(lo, hi)]
            stub.AppendColumnarStream(iter(
                [pb.AppendColumnarRequest(stream_name="smk",
                                          blocks=[f])
                 for _l, f in reqs]),
                metadata=(("x-request-id", f"smoke-{lo}"),))
            drain_to(reqs[-1][0])

        for b in range(3):  # slow path first: one batch per poll
            last, f = frame(b)
            stub.AppendColumnar(pb.AppendColumnarRequest(
                stream_name="smk", blocks=[f]))
            drain_to(last)
        stream_batches(3, warm)  # burst: spans window closes
        with RetraceGuard() as g:
            stream_batches(warm, warm + batches)
            # stats plane armed mid-steady-state: one scrape + one
            # federation fold must compile nothing
            from hstream_tpu.common import records as _rec
            from hstream_tpu.stats.prometheus import render_metrics

            render_metrics(ctx)
            stub.SendAdminCommand(pb.AdminCommandRequest(
                command="stats",
                args=_rec.dict_to_struct({"entity": "streams"})))
            stub.SendAdminCommand(pb.AdminCommandRequest(
                command="placer", args=_rec.dict_to_struct({})))
            stub.ClusterStats(pb.ClusterStatsRequest())
        return g.count
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def _smoke_read_plane(batches: int = 50) -> int:
    """Read-plane retrace gate (ISSUE 20): steady-state pull serves —
    cache hits, version-miss recomputes (one batched peek extract), and
    closed-only fast-path serves — over a live fused-close run must
    compile ZERO new XLA executables. Returns the compile count."""
    from hstream_tpu.common import locktrace
    from hstream_tpu.common.tracing import RetraceGuard
    from hstream_tpu.server.readcache import ReadCache
    from hstream_tpu.server.views import Materialization
    from hstream_tpu.sql.codegen import stream_codegen

    ex, feed, warm = _smoke_tumbling_config()

    class _Owner:
        state_lock = locktrace.rlock("tasks.state")
        executor = ex

    mat = Materialization(group_cols=["device"])
    mat.task = _Owner()
    cache = ReadCache()
    sel_all = stream_codegen("SELECT * FROM v;").select
    sel_closed = stream_codegen(
        "SELECT * FROM v WHERE winEnd < 1;").select  # never peeks

    def step(i):
        with _Owner.state_lock:
            rows = feed(i)
            if rows is not None and len(rows):
                mat.add_closed(rows)
        cache.serve_view("v", mat, sel_all, "all")     # miss: one peek
        cache.serve_view("v", mat, sel_all, "all")     # hit: no device
        cache.serve_view("v", mat, sel_closed, "cl")   # fast path

    for i in range(warm):
        step(i)
    ex.block_until_ready()
    with RetraceGuard() as g:
        for i in range(warm, warm + batches):
            step(i)
        ex.block_until_ready()
    return g.count


def _smoke_run(config, batches: int = 50) -> int:
    """Warm one smoke config, then count XLA compiles over `batches`
    steady-state batches (contract: 0)."""
    from hstream_tpu.common.tracing import RetraceGuard

    ex, feed, warm = config()
    for i in range(warm):
        feed(i)
    if hasattr(ex, "flush_changes"):
        ex.flush_changes()
    ex.block_until_ready()
    with RetraceGuard() as g:
        for i in range(warm, warm + batches):
            feed(i)
        if hasattr(ex, "flush_changes"):
            ex.flush_changes()
        ex.block_until_ready()
    return g.count


def _forced_device_env(n_devices: int) -> dict:
    """A child env with the CPU backend pinned and EXACTLY n virtual
    host devices — both must land before the child's first jax import
    (the only moment XLA_FLAGS is read)."""
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    flags = " ".join(
        f for f in flags.split()
        if not f.startswith("--xla_force_host_platform_device_count"))
    env["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
        .strip())
    here = os.path.abspath(__file__)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in [os.path.dirname(here),
                    env.get("PYTHONPATH", "")] if p)
    return env


def _mesh_1xn(n_key: int):
    """A (1, n_key) mesh: all shards on the key axis — the layout the
    sharded join stores and session arenas split over."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    assert len(devs) >= n_key, f"{len(devs)} devices, need {n_key}"
    return Mesh(np.asarray(devs[:n_key]).reshape(1, n_key),
                ("data", "key"))


def smoke_sharded_child_main() -> None:
    """`python bench.py --smoke-sharded-child` (spawned by --smoke with
    8 forced virtual devices): the sharded join + sharded session
    retrace gate. Same contract as the single-chip gate — ZERO XLA
    executables compiled over the steady-state batches; every shape
    (sharded activation, fused probe+insert, arena step/merge, stacked
    drains, evict) must be compiled during warmup."""
    import sys

    import jax

    jax.config.update("jax_platforms", "cpu")
    n = jax.device_count()
    assert n >= 8, f"child has {n} devices, need 8"
    mesh = _mesh_1xn(8)
    join = _smoke_run(lambda: _smoke_join_config(mesh=mesh))
    session = _smoke_run(lambda: _smoke_session_config(mesh=mesh))
    print(json.dumps({
        "sharded_join_recompiles": join,
        "sharded_session_recompiles": session,
        "devices": n,
    }))
    sys.exit(1 if join or session else 0)


def _smoke_sharded_subprocess() -> dict:
    """Run the forced-8-device sharded retrace gate in a clean child
    (the parent's jax is already initialized with the ambient device
    count, so the virtual mesh must be provisioned pre-import)."""
    import subprocess
    import sys

    proc = subprocess.run(
        [sys.executable, __file__, "--smoke-sharded-child"],
        env=_forced_device_env(8), capture_output=True, text=True,
        timeout=600)
    sys.stderr.write(proc.stderr)
    line = proc.stdout.strip().splitlines()
    out = json.loads(line[-1]) if line else {}
    out["rc"] = proc.returncode
    return out


def smoke_main() -> None:
    """`python bench.py --smoke`: the CI retrace gate (CPU backend) —
    a small fused-close run and a small device-join run must compile
    ZERO XLA executables in steady state. Exit 1 on any recompile, so
    a shape-key or factory-cache regression fails the tier-1 job in
    seconds instead of surfacing as a silent 22x on real hardware.
    A forced-8-virtual-device child re-runs the join and session
    configs SHARDED (ISSUE 16) under the same zero-recompile gate."""
    import os
    import sys

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # disarmed-witness contract (ISSUE 14): the whole smoke — incl.
    # the SERVER run over the instrumented append-front/task/
    # subscription locks — executes with the lock-order witness
    # disarmed, and must leave it with ZERO state: no held-set, no
    # graph edges, no per-lock accounting. A regression here means a
    # TracedLock started paying witness bookkeeping on the disarmed
    # path (the one-attribute-read + one-branch contract broke).
    from hstream_tpu.common.locktrace import LOCKTRACE

    assert not LOCKTRACE.active, "smoke must run witness-disarmed"
    # device-time sampler contract (ISSUE 18), both directions: a
    # DISARMED run must record ZERO sampler state (the one-attribute-
    # read + one-branch disarmed path, like the lock witness), and the
    # main gates below then run with the sampler ARMED at rate 1 —
    # every dispatch fenced + timed — and must still compile nothing
    # (block_until_ready is a sync, never a trace)
    from hstream_tpu.stats.devicecost import DEVICE_TIME

    assert not DEVICE_TIME.active, "smoke must start sampler-disarmed"
    disarmed_probe = _smoke_run(_smoke_tumbling_config, batches=10)
    ds = DEVICE_TIME.state()
    sampler_disarmed_state = (sum(ds["counts"].values())
                              + sum(ds["samples"].values()))
    DEVICE_TIME.arm(1)
    try:
        tumbling = _smoke_run(_smoke_tumbling_config)
        join = _smoke_run(_smoke_join_config)
        session = _smoke_run(_smoke_session_config)
        server_columnar = _smoke_server_columnar()
        read_plane = _smoke_read_plane()
    finally:
        armed = DEVICE_TIME.state()
        sampler_armed_samples = sum(armed["samples"].values())
        DEVICE_TIME.disarm()
        DEVICE_TIME.reset()
    sharded = _smoke_sharded_subprocess()
    sharded_join = int(sharded.get("sharded_join_recompiles", -1))
    sharded_session = int(sharded.get("sharded_session_recompiles", -1))
    sharded_bad = (sharded.get("rc") != 0 or sharded_join != 0
                   or sharded_session != 0)
    lock_edges = LOCKTRACE.edge_count()
    lock_state = len(LOCKTRACE.status()["locks"])
    result = {
        "metric": "recompiles_per_run",
        "mode": "smoke",
        "value": tumbling + join + session + server_columnar
        + read_plane + max(sharded_join, 0) + max(sharded_session, 0),
        "tumbling_recompiles": tumbling,
        "join_recompiles": join,
        "session_recompiles": session,
        "server_columnar_recompiles": server_columnar,
        "read_plane_recompiles": read_plane,
        "sharded_join_recompiles": sharded_join,
        "sharded_session_recompiles": sharded_session,
        "sharded_devices": sharded.get("devices"),
        "locktrace_disarmed_edges": lock_edges,
        "locktrace_disarmed_locks": lock_state,
        "sampler_disarmed_probe_recompiles": disarmed_probe,
        "sampler_disarmed_state": sampler_disarmed_state,
        "sampler_armed_samples": sampler_armed_samples,
        "batches": 50,
        "platform": jax.devices()[0].platform,
    }
    print(json.dumps(result))
    if tumbling or join or session or server_columnar or read_plane \
            or sharded_bad or disarmed_probe:
        print("# retrace gate FAILED: steady-state batches compiled "
              "new XLA executables", flush=True)
        sys.exit(1)
    if lock_edges or lock_state:
        print("# locktrace gate FAILED: the DISARMED witness recorded "
              "state — the one-branch disarmed contract broke",
              flush=True)
        sys.exit(1)
    if sampler_disarmed_state:
        print("# device-time gate FAILED: the DISARMED sampler "
              "recorded state — the one-branch disarmed contract "
              "broke", flush=True)
        sys.exit(1)
    if sampler_armed_samples == 0:
        print("# device-time gate FAILED: the rate-1 armed sampler "
              "recorded no device-time samples", flush=True)
        sys.exit(1)


def multichip_child_main(n_devices: int) -> None:
    """`python bench.py --multichip-child N` (spawned by --multichip
    with N forced virtual devices): run the sharded join and sharded
    session dryrun configs and report eps + engine dispatches per
    micro-batch — the kernel-contract number (one fused dispatch per
    batch) the sharded paths must hold at every device count."""
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    assert jax.device_count() >= n_devices
    mesh = _mesh_1xn(n_devices) if n_devices > 1 else None
    # rows per feed batch, fixed by the config builders
    rows_per_batch = {"join": 256, "session": 512}
    out = {"n_devices": n_devices}
    for name, cfg in (("join", _smoke_join_config),
                      ("session", _smoke_session_config)):
        ex, feed, warm = cfg(mesh=mesh)
        dispatches = [0]

        def observe(_family, _seconds, _d=dispatches):
            _d[0] += 1

        ex.dispatch_observer = observe
        for i in range(warm):
            feed(i)
        if hasattr(ex, "flush_changes"):
            ex.flush_changes()
        ex.block_until_ready()
        dispatches[0] = 0
        batches = 40
        t0 = time.perf_counter()
        for i in range(warm, warm + batches):
            feed(i)
        if hasattr(ex, "flush_changes"):
            ex.flush_changes()
        ex.block_until_ready()
        dt = time.perf_counter() - t0
        out[name] = {
            "eps": round(batches * rows_per_batch[name] / dt, 1),
            "dispatches_per_batch": round(dispatches[0] / batches, 3),
            "sharded_dispatches": int(
                getattr(ex, "sharded_dispatches", 0) or 0),
        }
        if mesh is not None:
            assert out[name]["sharded_dispatches"] > 0, \
                f"{name}: mesh set but no sharded dispatches ran"
    print(json.dumps(out))


def multichip_main() -> None:
    """`python bench.py --multichip`: sharded join + sharded session
    dryruns per device count (1 / 2 / 8 virtual CPU devices, each in a
    clean child so the mesh is provisioned before jax import), eps and
    dispatches-per-batch recorded into MULTICHIP_r06.json."""
    import os
    import subprocess
    import sys

    runs = []
    ok = True
    for n in (1, 2, 8):
        proc = subprocess.run(
            [sys.executable, __file__, "--multichip-child", str(n)],
            env=_forced_device_env(n), capture_output=True, text=True,
            timeout=900)
        sys.stderr.write(proc.stderr)
        lines = proc.stdout.strip().splitlines()
        rec = json.loads(lines[-1]) if (proc.returncode == 0 and lines) \
            else {"n_devices": n}
        rec["rc"] = proc.returncode
        ok = ok and proc.returncode == 0
        runs.append(rec)
    result = {"metric": "multichip_dryrun", "ok": ok, "runs": runs}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r06.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps({"metric": "multichip_dryrun", "ok": ok,
                      "wrote": path}))
    if not ok:
        sys.exit(1)


def loopback_main() -> None:
    """`python bench.py --loopback`: server-path bench with the device
    link OUT of the measurement — JAX pinned to the local CPU backend
    before any jax import, so the number isolates the server path
    (protobuf decode, RPC, pipeline) from the tunneled dev chip whose
    bandwidth swings >10x minute-to-minute. Use this mode to guard
    server-path regressions; the accelerator-path numbers stay in the
    default mode."""
    import os

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    result = {
        "metric": "server_loopback_eps",
        "unit": "events/s",
        "mode": "loopback",
        "platform": jax.devices()[0].platform,
    }
    sp = server_path_eps()
    result.update(sp)
    result["value"] = sp.get("server_columnar_eps")
    print(json.dumps(result))


if __name__ == "__main__":
    import sys

    if "--loopback" in sys.argv[1:]:
        loopback_main()
    elif "--smoke-sharded-child" in sys.argv[1:]:
        smoke_sharded_child_main()
    elif "--multichip-child" in sys.argv[1:]:
        idx = sys.argv.index("--multichip-child")
        multichip_child_main(int(sys.argv[idx + 1]))
    elif "--multichip" in sys.argv[1:]:
        multichip_main()
    elif "--smoke" in sys.argv[1:]:
        smoke_main()
    else:
        main()
