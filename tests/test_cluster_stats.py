"""Cluster stats plane tests (ISSUE 15): MultiLevelTimeSeries
exactness against brute-force recounts (rotation, idle gaps, level
boundaries, late adds), the declarative-family admin verbs, the
gateway /stats endpoint, the periodic node_load_report journal event,
and a seeded 3-node federation merge whose per-node per-stream rates
must match direct recounts exactly."""

import json
import random
import urllib.request

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.http_gateway import serve_gateway
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve
from hstream_tpu.stats import StatsHolder
from hstream_tpu.stats.timeseries import (
    DEFAULT_LEVELS,
    INTERVAL_NAMES,
    MultiLevelTimeSeries,
)

BASE_S = 1_700_000_000


# ---- multilevel exactness vs brute force -----------------------------------


def _brute_window(adds, now, width, n):
    """Reference recount: (sum, count) of adds whose second lands in
    the trailing ``n`` bucket slots of width ``width`` aligned to the
    bucket grid — the exact semantics the rings implement."""
    cur = int(now) // width
    lo = cur - n + 1
    hits = [v for t, v in adds if lo <= int(t) // width <= cur]
    return sum(hits), len(hits)


def test_multilevel_exactness_random_walk():
    """2000 seeded adds over a time walk mixing sub-bucket steps,
    level-boundary hops, and idle gaps wider than every ring; at
    checkpoints every level's sum/count/rate must equal the brute-force
    recount exactly (not approximately)."""
    rng = random.Random(0xC1A5)
    ts = MultiLevelTimeSeries()
    adds = []
    t = float(BASE_S)
    qmax = t
    for step in range(2000):
        t += rng.choice([0.0, 0.0, 0.3, 0.7, 1.0, 1.0, 2.0, 9.0,
                         10.0, 59.0, 60.0, 61.0, 599.0, 601.0, 3601.0])
        v = float(rng.randint(1, 1000))
        ts.add(v, now=t)
        adds.append((t, v))
        if step % 37 == 0:
            # queries are monotone (rings only rotate forward); a
            # later add BEFORE the queried now is a late add and must
            # still land in its exact bucket
            q = max(t, qmax) + rng.choice([0.0, 0.5, 1.0, 30.0, 120.0])
            qmax = q
            for name, (w, n) in zip(INTERVAL_NAMES, DEFAULT_LEVELS):
                want_sum, want_count = _brute_window(adds, q, w, n)
                assert ts.sum(name, now=q) == want_sum, (step, name)
                assert ts.count(name, now=q) == want_count, (step, name)
                assert ts.rate(name, now=q) == want_sum / (w * n)
    total_sum, total_count = ts.all_time()
    assert total_sum == sum(v for _t, v in adds)
    assert total_count == len(adds)


def test_multilevel_idle_gap_clears_narrow_keeps_wide():
    ts = MultiLevelTimeSeries()
    for i in range(10):
        ts.add(2.0, now=BASE_S + i)
    # 90s later: outside the 1min ring, inside 10min and 1h
    q = BASE_S + 9 + 90
    assert ts.sum("1min", now=q) == 0.0
    assert ts.sum("10min", now=q) == 20.0
    assert ts.sum("1h", now=q) == 20.0
    # 11 minutes later: only the 1h ring still holds the adds
    q = BASE_S + 9 + 660
    assert ts.sum("10min", now=q) == 0.0
    assert ts.sum("1h", now=q) == 20.0
    assert ts.all_time() == (20.0, 10)


def test_multilevel_late_add_lands_in_its_bucket():
    ts = MultiLevelTimeSeries()
    ts.add(1.0, now=BASE_S + 30)
    # a late add 5s in the past still belongs to the 1min window
    ts.add(4.0, now=BASE_S + 25)
    assert ts.sum("1min", now=BASE_S + 30) == 5.0
    # a late add older than the whole 1min ring is dropped from that
    # level but kept by the wider rings and the all-time sum
    ts.add(8.0, now=BASE_S - 40)
    assert ts.sum("1min", now=BASE_S + 30) == 5.0
    assert ts.sum("10min", now=BASE_S + 30) == 13.0
    assert ts.all_time() == (13.0, 3)


def test_multilevel_avg_and_interval_names():
    ts = MultiLevelTimeSeries()
    for v in (2.0, 4.0, 6.0):
        ts.add(v, now=BASE_S)
    assert ts.avg("1min", now=BASE_S) == 4.0
    ladder = ts.ladder(now=BASE_S)
    assert set(ladder) == {"1min", "10min", "1h", "total",
                           "total_count"}
    assert ladder["total"] == 12.0
    with pytest.raises(KeyError):
        ts.sum("5min")


def test_holder_family_api_rejects_undeclared():
    stats = StatsHolder()
    with pytest.raises(KeyError):
        stats.stat_add("no_such_family", "k")
    with pytest.raises(KeyError):
        stats.stat_rate("no_such_family", "k")
    with pytest.raises(KeyError):
        stats.stat_keys("no_such_family")
    # declared-but-unseen keys peek 0.0 without allocating
    assert stats.stat_rate("delivered_records", "nope") == 0.0
    assert stats.stat_keys("delivered_records") == []


# ---- admin verbs + gateway /stats on a live server -------------------------


@pytest.fixture(scope="module")
def stack():
    server, ctx = serve("127.0.0.1", 0, "mem://",
                        load_report_interval_ms=400)
    addr = f"127.0.0.1:{ctx.port}"
    httpd, gw = serve_gateway(addr, port=0)
    base = f"http://127.0.0.1:{httpd.server_port}"
    channel = grpc.insecure_channel(addr)
    stub = HStreamApiStub(channel)
    yield addr, base, stub, ctx
    channel.close()
    httpd.shutdown()
    gw.close()
    server.stop(grace=1)
    ctx.shutdown()


def _admin(stub, command, **kwargs):
    resp = stub.SendAdminCommand(pb.AdminCommandRequest(
        command=command, args=rec.dict_to_struct(kwargs)))
    return json.loads(resp.result)


def _append(stub, stream, rows):
    req = pb.AppendRequest(stream_name=stream)
    for i in range(rows):
        req.records.append(rec.build_record({"k": "a", "v": i}))
    stub.Append(req)


def test_admin_stats_verbs_all_scopes(stack):
    addr, base, stub, ctx = stack
    stub.CreateStream(pb.Stream(stream_name="cs1"))
    _append(stub, "cs1", 6)
    # streams table: every stream-scoped family at the 1min ladder,
    # the record rate matching the appended count exactly
    out = _admin(stub, "stats", entity="streams", interval="1min")
    row = out["cs1"]
    assert row["interval"] == "1min"
    assert row["append_in_records_total"] == 6.0
    assert row["append_in_records_per_s"] == round(6.0 / 60.0, 3)
    assert row["append_in_bytes_total"] == \
        ctx.stats.stream_stat_get("append_payload_bytes", "cs1")
    # subscription scope: a fetch feeds the delivered_* families
    stub.CreateSubscription(pb.Subscription(
        subscription_id="cssub", stream_name="cs1"))
    got = stub.Fetch(pb.FetchRequest(subscription_id="cssub",
                                     timeout_ms=500, max_size=64))
    n = len(got.received_records)
    assert n == 6
    stub.Acknowledge(pb.AcknowledgeRequest(
        subscription_id="cssub",
        ack_ids=[r.record_id for r in got.received_records]))
    out = _admin(stub, "stats", entity="subscriptions")
    assert out["cssub"]["delivered_records_total"] == float(n)
    assert out["cssub"]["acks_received_total"] == float(n)
    # queries scope exists even while empty; the 10min/1h intervals
    # and bad inputs are typed refusals, not 500s
    assert _admin(stub, "stats", entity="queries") == {}
    assert _admin(stub, "stats", entity="streams",
                  interval="10min")["cs1"]["interval"] == "10min"
    with pytest.raises(grpc.RpcError):
        _admin(stub, "stats", entity="nonsense")
    with pytest.raises(grpc.RpcError):
        _admin(stub, "stats", interval="5min")
    stub.DeleteSubscription(pb.DeleteSubscriptionRequest(
        subscription_id="cssub"))


def test_admin_cli_stats_table(stack):
    """The CLI face: `admin stats` renders the verb output with the
    scope label as the first column."""
    from argparse import Namespace

    from hstream_tpu.admin import cmd_stats

    rows = cmd_stats(stub=stack[2],
                     args=Namespace(entity="streams", interval="1min",
                                    json=False))
    assert any(r.get("stream") == "cs1" for r in rows)
    row = next(r for r in rows if r.get("stream") == "cs1")
    assert "append_in_records_per_s" in row


def test_gateway_stats_endpoint(stack):
    addr, base, stub, ctx = stack
    with urllib.request.urlopen(f"{base}/stats?entity=streams"
                                f"&interval=1min") as r:
        assert r.status == 200
        out = json.loads(r.read())
    assert "cs1" in out
    assert out["cs1"]["interval"] == "1min"
    with urllib.request.urlopen(f"{base}/cluster-stats") as r:
        nodes = json.loads(r.read())
    (rep,) = nodes.values()
    assert rep["streams"]["cs1"]["append_in_records"]["total"] == 6.0
    assert rep["rss_bytes"] > 0


def test_metrics_carries_stream_rate_ladder(stack):
    addr, base, stub, ctx = stack
    from hstream_tpu.stats.prometheus import render_metrics

    text = render_metrics(ctx)
    for interval in INTERVAL_NAMES:
        assert (f'hstream_stream_rate{{stream="cs1",'
                f'metric="append_in_records",interval="{interval}"}}'
                in text)
    assert "hstream_node_rss_bytes" in text
    assert "hstream_append_inflight" in text


def test_node_load_report_journal_event(stack):
    addr, base, stub, ctx = stack
    import time

    deadline = time.time() + 10
    events = []
    while time.time() < deadline:
        events = ctx.events.query(kind="node_load_report", limit=10)
        if events:
            break
        time.sleep(0.1)
    assert events, "no node_load_report journaled"
    ev = events[-1]
    for field in ("node", "rss_bytes", "running_queries",
                  "append_inflight", "health", "streams"):
        assert field in ev, ev
    assert ev["rss_bytes"] > 0
    # the admin events verb sees it too (the placer's query path)
    out = _admin(stub, "events", kind="node_load_report", limit=5)
    assert out["events"]


def test_stale_family_series_dropped_at_scrape(stack):
    """A deleted entity's rate ladder stops rendering AND frees its
    cap slot: the scrape-time stat_drop_stale sweep is what keeps
    entity churn from folding every new entity into _overflow."""
    addr, base, stub, ctx = stack
    from hstream_tpu.stats.prometheus import render_metrics

    stub.CreateStream(pb.Stream(stream_name="tmp-s"))
    stub.CreateSubscription(pb.Subscription(
        subscription_id="tmpsub", stream_name="tmp-s"))
    _append(stub, "tmp-s", 3)
    got = stub.Fetch(pb.FetchRequest(subscription_id="tmpsub",
                                     timeout_ms=500, max_size=16))
    assert len(got.received_records) == 3
    assert "tmpsub" in ctx.stats.stat_keys("delivered_records")
    assert "tmp-s" in ctx.stats.stat_keys("append_in_records")
    # the admin table hides a just-deleted entity even BEFORE a scrape
    stub.DeleteSubscription(pb.DeleteSubscriptionRequest(
        subscription_id="tmpsub"))
    stub.DeleteStream(pb.DeleteStreamRequest(stream_name="tmp-s"))
    assert "tmpsub" not in _admin(stub, "stats", entity="subscriptions")
    assert "tmp-s" not in _admin(stub, "stats", entity="streams")
    # the scrape sweep retires the storage itself
    render_metrics(ctx)
    assert "tmpsub" not in ctx.stats.stat_keys("delivered_records")
    assert "tmpsub" not in ctx.stats.stat_keys("delivered_bytes")
    assert "tmp-s" not in ctx.stats.stat_keys("append_in_records")
    # "_"-prefixed pseudo-keys survive the sweep (the overflow fold)
    ctx.stats.stat_add("append_in_bytes", "_overflow", 1.0)
    render_metrics(ctx)
    assert "_overflow" in ctx.stats.stat_keys("append_in_bytes")


# ---- seeded 3-node federation ----------------------------------------------


def test_three_node_federation_merge_exact():
    """Three in-process servers, seeded per-node append counts; `admin
    cluster-stats` against node 0 with --peers must return one report
    per node whose per-stream 1min/10min rates equal the direct
    recounts exactly — including a same-named stream on two nodes
    staying attributed per node, never re-aggregated."""
    rng = random.Random(42)
    nodes = []
    try:
        for i in range(3):
            server, ctx = serve("127.0.0.1", 0, "mem://",
                                load_report_interval_ms=60_000)
            addr = f"127.0.0.1:{ctx.port}"
            ch = grpc.insecure_channel(addr)
            nodes.append((server, ctx, addr, ch, HStreamApiStub(ch)))
        counts = []
        for i, (_s, _c, _a, _ch, stub) in enumerate(nodes):
            k = rng.randint(3, 9)
            stub.CreateStream(pb.Stream(stream_name=f"fed-s{i}"))
            _append(stub, f"fed-s{i}", k)
            shared = 0
            if i < 2:  # same stream name on two nodes, different load
                shared = rng.randint(2, 7) + i * 10
                stub.CreateStream(pb.Stream(stream_name="fed-shared"))
                _append(stub, "fed-shared", shared)
            counts.append((k, shared))
        stub0 = nodes[0][4]
        peers = ",".join(a for _s, _c, a, _ch, _stub in nodes[1:])
        merged = _admin(stub0, "cluster-stats", peers=peers)
        assert len(merged) == 3, list(merged)
        by_addr = {rep["addr"]: rep for rep in merged.values()}
        for i, (_s, ctx, addr, _ch, _stub) in enumerate(nodes):
            rep = by_addr[addr]
            assert "error" not in rep
            k, shared = counts[i]
            lad = rep["streams"][f"fed-s{i}"]["append_in_records"]
            # exact recount: every append landed inside the trailing
            # 1min window, so the ladder sums to exactly k
            assert lad["total"] == float(k)
            assert lad["1min"] == k / 60.0
            assert lad["10min"] == k / 600.0
            if shared:
                sl = rep["streams"]["fed-shared"]["append_in_records"]
                assert sl["total"] == float(shared)
                assert sl["1min"] == shared / 60.0
            # byte ladder cross-checked against the counter registry
            assert rep["streams"][f"fed-s{i}"]["append_in_bytes"][
                "total"] == ctx.stats.stream_stat_get(
                    "append_payload_bytes", f"fed-s{i}")
        # the two fed-shared loads stayed per-node
        s0 = by_addr[nodes[0][2]]["streams"]["fed-shared"][
            "append_in_records"]["total"]
        s1 = by_addr[nodes[1][2]]["streams"]["fed-shared"][
            "append_in_records"]["total"]
        assert s0 == float(counts[0][1]) and s1 == float(counts[1][1])
        assert s0 != s1
        # the merged table shape: 3 node rows + one row per
        # (node, stream), rates carried at the requested interval
        from hstream_tpu.stats.cluster import merge_rows

        rows = merge_rows(list(merged.values()), interval="1min")
        node_rows = [r for r in rows if r["stream"] == "(node)"]
        assert len(node_rows) == 3
        stream_rows = [(r["node"], r["stream"]) for r in rows
                       if r["stream"] != "(node)"]
        assert len(stream_rows) == len(set(stream_rows)) == 5
        # a dead peer reads as an unreachable row, not a missing one
        dead = _admin(stub0, "cluster-stats",
                      peers="127.0.0.1:1", timeout_s=1.0)
        assert any(r.get("role") == "unreachable"
                   for r in dead.values())
    finally:
        for server, ctx, _a, ch, _stub in nodes:
            ch.close()
            server.stop(grace=1)
            ctx.shutdown()


def test_node_load_report_carries_bound_identity(stack):
    """The boot-time report journals the REAL bound address: a
    reporter started before the ephemeral-port bind would journal a
    phantom `host:0` node the placer can't match to later reports."""
    addr, base, stub, ctx = stack
    events = ctx.events.query(kind="node_load_report", limit=1000)
    assert events
    for ev in events:
        assert ev["addr"] == addr, ev["addr"]
        assert not ev["addr"].endswith(":0")


def test_cluster_stats_merge_disambiguates_node_name_collisions():
    """Two bare followers with the default node id must BOTH stay
    visible in the merged table — never silently last-writer-wins."""
    import socket

    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import serve_follower

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    stores, followers = [], []
    try:
        peers = []
        for _ in range(2):
            st = open_store("mem://")
            port = free_port()
            fs, svc = serve_follower(st, f"127.0.0.1:{port}",
                                     node_id="follower")
            stores.append(st)
            followers.append((fs, svc))
            peers.append(f"127.0.0.1:{port}")
        merged = _admin(stub, "cluster-stats", peers=",".join(peers))
        assert len(merged) == 3, list(merged)
        roles = sorted(r["role"] for r in merged.values())
        assert roles == ["follower", "follower", "single"]
    finally:
        ch.close()
        for fs, svc in followers:
            fs.stop(grace=1)
            svc.close()
        for st in stores:
            st.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_query_overflow_fold_survives_liveness_filter():
    """The "_overflow" aggregate renders in EVERY scope even when the
    live-entity filter is active — bounded-cardinality traffic must
    stay visible exactly when the cap engages."""
    from hstream_tpu.stats.prometheus import render_holder

    stats = StatsHolder()
    stats.stat_add("emit_rows", "_overflow", 3.0)
    stats.stat_add("append_in_bytes", "_overflow", 7.0)
    text = render_holder(stats, live_streams=set(), live_queries=set())
    assert 'hstream_emit_rows_rate{query="_overflow"}' in text
    assert 'hstream_append_in_bytes_rate{stream="_overflow"}' in text


def test_bare_follower_answers_cluster_stats(tmp_path):
    """The StoreReplica face: a bare follower process (no HStreamApi)
    still reports into the federation fan-out."""
    from hstream_tpu.stats.cluster import _fetch_peer
    from hstream_tpu.store import open_store
    from hstream_tpu.store.replica import serve_follower

    local = open_store("mem://")
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    server, svc = serve_follower(local, f"127.0.0.1:{port}",
                                 node_id="fed-follower")
    try:
        rep = _fetch_peer(f"127.0.0.1:{port}", timeout=5.0)
        assert rep["node"] == "fed-follower"
        assert rep["role"] == "follower"
        assert rep["rss_bytes"] > 0
        assert rep["streams"] == {}
    finally:
        server.stop(grace=1)
        svc.close()
        local.close()
