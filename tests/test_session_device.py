"""Device session lattice vs the host reference engine (ISSUE 10).

The device path (engine.lattice session kernels + the SessionExecutor
mirror) must be row-equivalent to the retained host merge engine across
out-of-order rows straddling the gap timeout, late-record drops,
cross-batch session extension, key growth + code-space compaction,
snapshot roundtrips, and watermark-driven closes — in BOTH kernel modes
(record: fully fused sort+scan step; segment: host-pre-reduced segment
planes merged on device). Float aggregates compare with a small relative
tolerance (the device accumulates in f32, the host in f64); counts,
min/max of f32-exact values, and HLL registers compare exactly;
APPROX_QUANTILE compares within one DDSketch bucket (bin edges are
computed in f32 on device, f64 on host).
"""
from __future__ import annotations

import numpy as np
import pytest

from hstream_tpu.engine import ColumnType, Schema
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec, SourceNode
from hstream_tpu.engine.session import SessionExecutor
from hstream_tpu.engine.window import SessionWindow

BASE = 1_700_000_000_000

MODES = ["segment", "record"]

SCHEMA = Schema.of(k=ColumnType.STRING, v=ColumnType.FLOAT)


def make_ex(aggs, *, device, mode=None, gap=1000, grace=500,
            emit_changes=False, having=None, projections=None):
    node = AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("k")],
        window=SessionWindow(gap, grace_ms=grace), aggs=aggs,
        having=having, post_projections=projections or [])
    ex = SessionExecutor(node, SCHEMA, emit_changes=emit_changes)
    ex.use_device_sessions = device
    ex.device_session_mode = mode
    return ex


def gen(seed, n_batches=8, batch=300, keys=12, late_frac=0.15):
    """Randomized workload with out-of-order rows straddling the gap
    timeout and genuinely-late records (past grace under the
    watermark). Values are small integers so f32 sums stay exact."""
    rng = np.random.default_rng(seed)
    batches, t = [], BASE
    for _ in range(n_batches):
        ks = rng.integers(0, keys, batch)
        ts = t + rng.integers(0, 4000, batch)
        late = rng.random(batch) < late_frac
        ts = np.where(late, ts - rng.integers(3000, 20_000, batch), ts)
        vs = rng.integers(0, 1000, batch)
        rows = [{"k": f"u{int(k)}", "v": float(v)}
                for k, v in zip(ks, vs)]
        batches.append((rows, ts.tolist()))
        t += 2500
    return batches


def assert_rows_close(got, want, rtol=1e-5):
    """Row-set equality with relative tolerance on float fields (rows
    matched by their exact non-float fields)."""
    def key(r):
        return tuple(sorted((k, v) for k, v in r.items()
                            if not isinstance(v, float)))

    gd: dict = {}
    wd: dict = {}
    for r in got:
        gd.setdefault(key(r), []).append(r)
    for r in want:
        wd.setdefault(key(r), []).append(r)
    assert set(gd) == set(wd), sorted(set(gd) ^ set(wd))[:4]
    for k in gd:
        assert len(gd[k]) == len(wd[k]), k
        for rg, rw in zip(
                sorted(gd[k], key=lambda r: sorted(r.items(), key=str)),
                sorted(wd[k], key=lambda r: sorted(r.items(), key=str))):
            for c, v in rw.items():
                if isinstance(v, float):
                    assert np.isclose(rg[c], v, rtol=rtol,
                                      atol=1e-9), (k, c, rg[c], v)


EXACT_AGGS = [
    AggSpec(AggKind.COUNT_ALL, "c"),
    AggSpec(AggKind.COUNT, "n", input=Col("v")),
    AggSpec(AggKind.SUM, "s", input=Col("v")),
    AggSpec(AggKind.AVG, "a", input=Col("v")),
    AggSpec(AggKind.MIN, "lo", input=Col("v")),
    AggSpec(AggKind.MAX, "hi", input=Col("v")),
    AggSpec(AggKind.APPROX_COUNT_DISTINCT, "d", input=Col("v")),
]

SKETCH_AGGS = [
    AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("v"), quantile=0.5),
    AggSpec(AggKind.APPROX_QUANTILE, "p99", input=Col("v"),
            quantile=0.99),
]


@pytest.mark.parametrize("mode", MODES)
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_device_host_equivalence_out_of_order(mode, seed):
    """Random out-of-order + late workload: closed rows, open-session
    peeks, and final state agree between engines in both modes."""
    exd = make_ex(EXACT_AGGS, device=True, mode=mode)
    exh = make_ex(EXACT_AGGS, device=False)
    od, oh = [], []
    for rows, ts in gen(seed):
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is not None and exd._dev["mode"] == mode
    assert exd.device_fallbacks == 0
    assert_rows_close(od, oh)
    assert_rows_close(list(exd.peek()), list(exh.peek()))


@pytest.mark.parametrize("mode", MODES)
def test_quantile_within_one_bucket(mode):
    exd = make_ex(SKETCH_AGGS, device=True, mode=mode)
    exh = make_ex(SKETCH_AGGS, device=False)
    od, oh = [], []
    for rows, ts in gen(7):
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is not None
    # one-bucket tolerance: DDSketch bin edges are f32 on device
    assert_rows_close(od, oh, rtol=0.08)


@pytest.mark.parametrize("mode", MODES)
def test_cross_batch_session_extension(mode):
    """A session extended across many batches (every batch within gap)
    closes once, with the accumulated aggregates of all batches."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode, gap=1000, grace=0)
    exh = make_ex(aggs, device=False, gap=1000, grace=0)
    for b in range(6):
        rows = [{"k": "a", "v": 1.0}]
        for ex in (exd, exh):
            out = ex.process(rows, [BASE + b * 900])
            assert list(out) == []
    closed_d, closed_h = None, None
    for ex in (exd, exh):
        out = ex.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
        rows = [r for r in out if r["k"] == "a"]
        assert len(rows) == 1
        if ex is exd:
            closed_d = rows[0]
        else:
            closed_h = rows[0]
    assert closed_d == closed_h
    assert closed_d["c"] == 6 and closed_d["s"] == 6.0
    assert closed_d["winStart"] == BASE
    assert closed_d["winEnd"] == BASE + 5 * 900 + 1000


@pytest.mark.parametrize("mode", MODES)
def test_multi_session_merge_within_limit(mode):
    """A batch bridging several open sessions of one key merges them
    all (within chain_merge_limit) identically to the host. Grace keeps
    the disjoint sessions open and the bridge records in-grace."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.MIN, "lo", input=Col("v")),
            AggSpec(AggKind.MAX, "hi", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode, gap=100, grace=5000)
    exh = make_ex(aggs, device=False, gap=100, grace=5000)
    # 5 disjoint sessions (400ms apart >> gap), all open under grace
    opens = [({"k": "a", "v": float(i)}, BASE + i * 400)
             for i in range(5)]
    for ex in (exd, exh):
        for row, t in opens:
            ex.process([row], [t])
    assert len(list(exh.peek())) == 5
    # one batch of bridge records every 80ms chains them all into ONE
    bridge_ts = list(range(BASE + 50, BASE + 5 * 400, 80))
    bridge = [{"k": "a", "v": 99.0} for _ in bridge_ts]
    for ex in (exd, exh):
        ex.process(bridge, bridge_ts)
    assert exd.device_fallbacks == 0  # within the limit: no fallback
    pd, ph = list(exd.peek()), list(exh.peek())
    assert_rows_close(pd, ph)
    assert len(pd) == 1 and pd[0]["c"] == 5 + len(bridge)
    assert pd[0]["lo"] == 0.0 and pd[0]["hi"] == 99.0


def test_chain_limit_triggers_host_fallback():
    """A batch merging more open sessions than chain_merge_limit
    degrades to the host engine — identical results, counted."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    exd = make_ex(aggs, device=True, mode="segment", gap=100,
                  grace=5000)
    exh = make_ex(aggs, device=False, gap=100, grace=5000)
    exd.chain_merge_limit = 3
    opens_ts = [BASE + i * 400 for i in range(6)]
    for ex in (exd, exh):
        for t in opens_ts:
            ex.process([{"k": "a", "v": 1.0}], [t])
    assert exd._dev is not None
    bridge_ts = list(range(BASE + 50, BASE + 6 * 400, 80))
    bridge = [{"k": "a", "v": 1.0} for _ in bridge_ts]
    od = exd.process(bridge, bridge_ts)
    oh = exh.process(bridge, bridge_ts)
    assert exd._dev is None and exd.use_device_sessions is False
    assert exd.device_fallbacks == 1
    assert list(od) == list(oh)
    # the degraded executor carries on, still host-identical
    od = exd.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    oh = exh.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    assert_rows_close(od, oh)
    assert exd.sessions.keys() == exh.sessions.keys()


@pytest.mark.parametrize("mode", MODES)
def test_key_growth_and_code_compaction(mode):
    """Key cardinality past the cache bound triggers the code-space
    compaction (order-preserving remap kernel) instead of a cache
    clear; results stay host-identical across the remap."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode, gap=500, grace=0)
    exh = make_ex(aggs, device=False, gap=500, grace=0)
    exd._KEY_CACHE_MAX = 64  # force compaction quickly
    od, oh = [], []
    rng = np.random.default_rng(3)
    for b in range(8):
        # fresh key names every batch: cardinality grows past the bound
        ks = [f"k{b}_{int(i)}" for i in rng.integers(0, 40, 120)]
        ts = (BASE + b * 5000 + rng.integers(0, 400, 120)).tolist()
        rows = [{"k": k, "v": 1.0} for k in ks]
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is not None
    assert exd.session_stats["remap_dispatches"] >= 1
    assert_rows_close(od, oh)
    assert_rows_close(list(exd.peek()), list(exh.peek()))


@pytest.mark.parametrize("mode", MODES)
def test_snapshot_roundtrip_in_device_mode(mode):
    """Snapshot taken while sessions are device-resident restores into
    the host engine, re-activates lazily, and continues identically."""
    from types import SimpleNamespace

    from hstream_tpu.engine import snapshot as snap

    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v")),
            AggSpec(AggKind.APPROX_COUNT_DISTINCT, "d", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode)
    exh = make_ex(aggs, device=False)
    batches = gen(11, n_batches=5)
    for rows, ts in batches[:3]:
        exd.process(rows, ts)
        exh.process(rows, ts)
    assert exd._dev is not None
    blob = snap.snapshot_executor(exd)
    plan = SimpleNamespace(node=exd.node)  # restore only reads .node
    restored, _extra = snap.restore_executor(plan, blob)
    assert isinstance(restored, SessionExecutor)
    assert restored._dev is None  # restores host-side
    od, oh = [], []
    for rows, ts in batches[3:]:
        od.extend(restored.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert restored._dev is not None  # re-activated lazily
    assert_rows_close(od, oh)
    assert_rows_close(list(restored.peek()), list(exh.peek()))


@pytest.mark.parametrize("mode", MODES)
def test_watermark_close_parity(mode):
    """Sessions close at exactly wm >= end + 2*gap + grace on both
    engines — no earlier, no later."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    gap, grace = 1000, 300
    exd = make_ex(aggs, device=True, mode=mode, gap=gap, grace=grace)
    exh = make_ex(aggs, device=False, gap=gap, grace=grace)
    for ex in (exd, exh):
        ex.process([{"k": "a", "v": 1.0}], [BASE])
    # one below the close boundary: nothing closes
    boundary = BASE + 2 * gap + grace
    for ex in (exd, exh):
        out = ex.process([{"k": "z", "v": 0.0}], [boundary - 1])
        assert [r for r in out if r["k"] == "a"] == []
    # at the boundary: closes on both
    outs = []
    for ex in (exd, exh):
        out = ex.process([{"k": "z", "v": 0.0}], [boundary])
        outs.append([r for r in out if r["k"] == "a"])
    assert outs[0] == outs[1] and len(outs[0]) == 1


@pytest.mark.parametrize("mode", MODES)
def test_columnar_feed_equivalence(mode):
    """process_columnar (the server's _session_columns feed shape)
    matches the row path on both engines, nulls included."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode)
    exh = make_ex(aggs, device=False)
    rng = np.random.default_rng(5)
    od, oh = [], []
    for b in range(6):
        n = 200
        ks = np.array([f"u{int(i)}" for i in rng.integers(0, 10, n)])
        vs = rng.integers(0, 100, n).astype(np.float32)
        ts = BASE + b * 2500 + rng.integers(0, 4000, n)
        nulls = {"v": rng.random(n) < 0.1}
        od.extend(exd.process_columnar(ts, {"k": ks, "v": vs}, nulls))
        rows = [({"k": str(k)} if isnull else
                 {"k": str(k), "v": float(v)})
                for k, v, isnull in zip(ks, vs, nulls["v"])]
        oh.extend(exh.process(rows, ts.tolist()))
    assert exd._dev is not None
    assert_rows_close(od, oh)
    assert_rows_close(list(exd.peek()), list(exh.peek()))


@pytest.mark.parametrize("mode", MODES)
def test_one_dispatch_zero_fetch_ingest_contract(mode):
    """The session ingest contract: exactly ONE step dispatch per
    micro-batch and ZERO fetches outside close cycles; each close cycle
    is one extract dispatch + one fetch."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    ex = make_ex(aggs, device=True, mode=mode, gap=1000, grace=0)
    rng = np.random.default_rng(9)
    for b in range(10):
        n = 256
        rows = [{"k": f"u{int(i)}", "v": 1.0}
                for i in rng.integers(0, 20, n)]
        ts = (BASE + b * 10_000 + rng.integers(0, 900, n)).tolist()
        ex.process(rows, ts)
    st = ex.session_stats
    assert st["step_dispatches"] == st["batches"]
    assert st["close_dispatches"] == st["close_cycles"]
    assert st["close_fetches"] == st["close_cycles"]


@pytest.mark.parametrize("mode", MODES)
def test_deferred_close_drain_single_stacked_fetch(mode):
    """defer_close_decode holds packed closes as device values; one
    drain fetches every same-shape cycle in a single stacked transfer
    with rows identical to the synchronous path."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    exd = make_ex(aggs, device=True, mode=mode, gap=1000, grace=0)
    exs = make_ex(aggs, device=True, mode=mode, gap=1000, grace=0)
    exd.defer_close_decode = True
    rng = np.random.default_rng(13)
    sync_rows = []
    for b in range(6):
        n = 128
        rows = [{"k": f"u{int(i)}", "v": 1.0}
                for i in rng.integers(0, 8, n)]
        ts = (BASE + b * 10_000 + rng.integers(0, 900, n)).tolist()
        out = exd.process(rows, ts)
        assert list(out) == []  # all emission deferred
        sync_rows.extend(exs.process(rows, ts))
    assert exd.has_pending_closes()
    fetches_before = exd.session_stats["close_fetches"]
    drained = list(exd.drain_closed())
    # every same-shape cycle rode one stacked transfer
    assert exd.session_stats["close_fetches"] - fetches_before \
        <= len({tuple()})  # exactly one shape group here
    assert_rows_close(drained, sync_rows)
    assert not exd.has_pending_closes()


def test_emit_changes_and_topk_refuse_device():
    """Host-only configs never activate the device path (a refusal, not
    a counted failure)."""
    ex = make_ex([AggSpec(AggKind.COUNT_ALL, "c")], device=True,
                 emit_changes=True)
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    assert ex._dev is None and ex._device_refusal is not None
    assert ex.device_fallbacks == 0
    ex2 = make_ex([AggSpec(AggKind.TOPK, "t", input=Col("v"), k=3)],
                  device=True)
    ex2.process([{"k": "a", "v": 1.0}], [BASE])
    assert ex2._dev is None and "host-only" in ex2._device_refusal


def test_host_emission_is_columnar():
    """Satellite: peek() and close_due_sessions() ride ColumnarEmit on
    the HOST engine too (sessions were the last per-row-dict emitter)."""
    from hstream_tpu.common.columnar import ColumnarEmit

    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    ex = make_ex(aggs, device=False)
    ex.process([{"k": "a", "v": 1.0}, {"k": "b", "v": 2.0}],
               [BASE, BASE + 10])
    peeked = ex.peek()
    assert isinstance(peeked, ColumnarEmit)
    assert {r["k"] for r in peeked} == {"a", "b"}
    out = ex.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    # the lone close batch stays columnar end-to-end (extend_rows)
    assert isinstance(out, ColumnarEmit)
    assert {r["k"] for r in out} == {"a", "b", "z"} - {"z"} or \
        {r["k"] for r in out} <= {"a", "b", "z"}


def test_device_emission_is_columnar():
    from hstream_tpu.common.columnar import ColumnarEmit

    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, device=True, gap=1000, grace=0)
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    assert isinstance(ex.peek(), ColumnarEmit)
    out = ex.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    assert isinstance(out, ColumnarEmit)


@pytest.mark.parametrize("mode", MODES)
def test_having_and_projections_parity(mode):
    """HAVING + projections evaluate columnwise on both engines with
    the same drop semantics."""
    from hstream_tpu.engine.expr import BinOp, Lit

    aggs = [AggSpec(AggKind.COUNT_ALL, "c"),
            AggSpec(AggKind.SUM, "s", input=Col("v"))]
    having = BinOp(">", Col("c"), Lit(2))
    projections = [("key", Col("k")), ("total", Col("s"))]
    exd = make_ex(aggs, device=True, mode=mode, having=having,
                  projections=projections)
    exh = make_ex(aggs, device=False, having=having,
                  projections=projections)
    od, oh = [], []
    for rows, ts in gen(17, n_batches=5):
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is not None
    assert len(oh) > 0  # HAVING actually filtered a nonempty set
    assert_rows_close(od, oh)


@pytest.mark.parametrize("mode", MODES)
def test_where_filter_parity(mode):
    from hstream_tpu.engine.expr import BinOp, Lit
    from hstream_tpu.engine.plan import FilterNode

    schema = SCHEMA
    pred = BinOp(">", Col("v"), Lit(100.0))
    node = AggregateNode(
        child=FilterNode(child=SourceNode("s", schema), predicate=pred),
        group_keys=[Col("k")],
        window=SessionWindow(1000, grace_ms=500),
        aggs=[AggSpec(AggKind.COUNT_ALL, "c"),
              AggSpec(AggKind.SUM, "s", input=Col("v"))])
    exd = SessionExecutor(node, schema)
    exd.device_session_mode = mode
    exh = SessionExecutor(node, schema)
    exh.use_device_sessions = False
    od, oh = [], []
    for rows, ts in gen(21, n_batches=6):
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is not None
    assert_rows_close(od, oh)
    # watermark advances on filtered-out records too (pre-filter max)
    assert exd.watermark == exh.watermark


def test_pinned_anchor_span_degrades_to_host_not_crash():
    """Review finding (ISSUE 10): an ancient open session pins the
    rebase anchor; once relative time reaches the device range the
    executor must DEGRADE to the host engine (which has no int32
    bound) instead of desyncing the mirror and crash-looping."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    # a ~9h grace keeps every session open across the whole run, so the
    # FIRST session pins the rebase anchor at BASE while stream time
    # advances 500s per batch past the (shrunk) relative range
    exd = make_ex(aggs, device=True, mode="segment", gap=1000,
                  grace=1 << 25)
    exh = make_ex(aggs, device=False, gap=1000, grace=1 << 25)
    exd.REBASE_THRESHOLD = 1 << 22  # ~70 min, keeps the test fast
    od, oh = [], []
    for b in range(12):
        rows = [{"k": "pin", "v": 1.0},
                {"k": f"s{b}", "v": 1.0}]
        ts = [BASE + b * 500_000, BASE + b * 500_000 + 10]
        od.extend(exd.process(rows, ts))
        oh.extend(exh.process(rows, ts))
    assert exd._dev is None and exd.device_fallbacks == 1
    assert exd.use_device_sessions is False
    assert_rows_close(od, oh)
    assert_rows_close(list(exd.peek()), list(exh.peek()))


def test_huge_gap_grace_refuses_device():
    """2*gap + grace past the int32 relative budget is a plan-time
    refusal (the close rule would not fit the device time range)."""
    ex = make_ex([AggSpec(AggKind.COUNT_ALL, "c")], device=True,
                 gap=1 << 29, grace=1 << 29)
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    assert ex._dev is None
    assert "relative-time range" in ex._device_refusal
    assert ex.device_fallbacks == 0


def test_peek_does_not_skew_close_accounting():
    """Review finding: pull-query peeks must not count into the
    close-path dispatch/fetch budget the bench asserts on."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, device=True, gap=1000, grace=0)
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    for _ in range(3):
        ex.peek()
    st = ex.session_stats
    assert st["peek_dispatches"] == 3
    assert st["close_dispatches"] == st["close_cycles"]
    assert st["close_fetches"] == st["close_cycles"]


def test_snapshot_guard_requires_drained_closes():
    """Deferred session closes block a snapshot until drained (the
    packed device buffers are the only copy of those rows)."""
    from hstream_tpu.common.errors import SQLCodegenError
    from hstream_tpu.engine import snapshot as snap

    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, device=True, gap=1000, grace=0)
    ex.defer_close_decode = True
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    ex.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    assert ex.has_pending_closes()
    with pytest.raises(SQLCodegenError, match="deferred session"):
        snap.snapshot_executor(ex)
    rows = ex.flush_changes()  # the task's pre-snapshot drain surface
    assert [r["k"] for r in rows] == ["a"]
    snap.snapshot_executor(ex)  # drained: snapshot proceeds


def test_close_extract_dispatch_failure_degrades_not_dies():
    """Review finding: a kernel failure at the close-extract DISPATCH
    (mirror not yet retired) degrades to the host engine, which closes
    the same due set — instead of killing the query."""
    from hstream_tpu.common.faultinject import FAULTS

    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    exd = make_ex(aggs, device=True, gap=1000, grace=0)
    exh = make_ex(aggs, device=False, gap=1000, grace=0)
    for ex in (exd, exh):
        ex.process([{"k": "a", "v": 1.0}], [BASE])
    try:
        # hit 1 = the closer batch's step dispatch (passes), hit 2 =
        # the close extract dispatch (fails)
        FAULTS.arm("device.session.dispatch", "fail:2")
        od = exd.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    finally:
        FAULTS.disarm()
    oh = exh.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    assert exd.device_fallbacks == 1 and exd._dev is None
    assert_rows_close(od, oh)
    assert any(r["k"] == "a" for r in od)  # the close still emitted


def test_peek_extract_dispatch_failure_degrades_not_dies():
    from hstream_tpu.common.faultinject import FAULTS

    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    exd = make_ex(aggs, device=True, gap=1000, grace=0)
    exh = make_ex(aggs, device=False, gap=1000, grace=0)
    for ex in (exd, exh):
        ex.process([{"k": "a", "v": 1.0}], [BASE])
    try:
        FAULTS.arm("device.session.dispatch", "fail:1")
        pd = list(exd.peek())
    finally:
        FAULTS.disarm()
    assert exd.device_fallbacks == 1 and exd._dev is None
    assert_rows_close(pd, list(exh.peek()))


def test_degrade_with_pending_deferred_closes_keeps_keys():
    """Review finding: pending deferred closes must resolve their key
    columns AT degrade time — a later host-mode key-cache clear rebuilds
    the code dictionary and lazy decode would read wrong keys."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, device=True, gap=1000, grace=0)
    ex.defer_close_decode = True
    ex.process([{"k": "a", "v": 1.0}], [BASE])
    ex.process([{"k": "closer", "v": 0.0}], [BASE + 100_000])
    assert ex.has_pending_closes()
    ex._degrade_to_host("test: simulate a mid-stream device loss")
    # host-mode cache bound clears the code dictionary wholesale
    ex._KEY_CACHE_MAX = 0
    ex.process([{"k": f"n{i}", "v": 1.0} for i in range(4)],
               [BASE + 200_000 + i for i in range(4)])
    rows = list(ex.drain_closed())
    assert [r["k"] for r in rows] == ["a"]  # the ORIGINAL key survives


def test_late_records_merge_into_open_sessions_on_device():
    """A late record that overlaps an open session merges (not drops) —
    the mirror's sequential late walk preserves the reference's
    record-at-a-time drop-vs-merge decisions."""
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    for mode in MODES:
        exd = make_ex(aggs, device=True, mode=mode, gap=1000, grace=0)
        exh = make_ex(aggs, device=False, gap=1000, grace=0)
        for ex in (exd, exh):
            ex.process([{"k": "a", "v": 1.0}], [BASE + 10_000])
            # late but overlapping "a"'s session: merges; late and far
            # from any session: drops
            ex.process(
                [{"k": "a", "v": 1.0}, {"k": "a", "v": 1.0}],
                [BASE + 9_500, BASE + 2_000])
        pd, ph = list(exd.peek()), list(exh.peek())
        assert pd == ph
        assert pd[0]["c"] == 2  # merged one, dropped one
