"""Co-compile query packing (ISSUE 17 satellite 4): compatible queries
share ONE compiled lattice program — RetraceGuard pins zero recompiles
for the 2nd..Nth attached member — incompatible plans refuse with a
typed reason that EXPLAIN surfaces, and demux is exact against
standalone executor references.

The zero-recompile contract rides the transport's sticky monotone width
discipline (engine/transport.py): batch widths bucket to pow2 and the
interned key-id span widens along _BIT_LADDER at most once per rung.
Tests hold the tagged input width in one pow2 bucket and warm the key
id span into a ladder rung with headroom, so a new member's fresh ids
never force a wider encoding — which is exactly the steady-state shape
discipline the bench gates.
"""

from __future__ import annotations

import time

import grpc

from hstream_tpu.common import records as rec
from hstream_tpu.common.tracing import RetraceGuard
from hstream_tpu.placer.packing import (
    PackMemberTask,
    PackPool,
    PackRefusal,
    pack_signature,
    signature_text,
)
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.context import ServerContext
from hstream_tpu.server.main import serve
from hstream_tpu.sql.codegen import explain_text, make_executor, stream_codegen
from hstream_tpu.store import open_store

BASE = 1_700_000_000_000

CSAS = ("CREATE STREAM {sink} AS SELECT k, COUNT(*) AS {c} FROM src "
        "GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")


def _plan(sql):
    return stream_codegen(sql)


# ---- signatures + typed refusals --------------------------------------------


def test_compatible_queries_share_a_signature():
    s1 = pack_signature(_plan(CSAS.format(sink="s1", c="c1")))
    s2 = pack_signature(_plan(CSAS.format(sink="s2", c="c2")))
    assert not isinstance(s1, PackRefusal)
    # aliases differ, the signature does not: renames are member-local
    assert s1 == s2
    assert "tumbling" in signature_text(s1)
    # a different window shape is a different pack
    s3 = pack_signature(_plan(
        "CREATE STREAM s3 AS SELECT k, COUNT(*) AS c FROM src "
        "GROUP BY k, TUMBLING (INTERVAL 20 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
    assert s3 != s1
    # ... and so is a different agg set or source stream
    s4 = pack_signature(_plan(
        "CREATE STREAM s4 AS SELECT k, SUM(x) AS s FROM src "
        "GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
        "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
    assert s4 != s1


def test_typed_refusals():
    cases = {
        "join": ("SELECT s1.x, s2.y FROM s1 INNER JOIN s2 "
                 "WITHIN (INTERVAL 10 SECOND) ON s1.k = s2.k "
                 "EMIT CHANGES;"),
        "stateless": "SELECT k FROM s EMIT CHANGES;",
        "filter": ("SELECT COUNT(*) FROM s WHERE x > 0 GROUP BY k, "
                   "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"),
        "unwindowed": "SELECT COUNT(*) FROM s GROUP BY k EMIT CHANGES;",
        "session-window": ("SELECT COUNT(*) FROM s GROUP BY k, "
                           "SESSION (INTERVAL 30 SECOND) EMIT CHANGES;"),
        "having": ("SELECT k, COUNT(*) AS c FROM s GROUP BY k, "
                   "TUMBLING (INTERVAL 10 SECOND) "
                   "HAVING COUNT(*) >= 2 EMIT CHANGES;"),
        "projection": ("SELECT k, COUNT(*) + 1 AS c FROM s GROUP BY k, "
                       "TUMBLING (INTERVAL 10 SECOND) EMIT CHANGES;"),
        "computed-agg-input": ("SELECT k, SUM(x + 1) AS s FROM s "
                               "GROUP BY k, TUMBLING (INTERVAL 10 "
                               "SECOND) EMIT CHANGES;"),
    }
    for code, sql in cases.items():
        out = pack_signature(_plan(sql))
        assert isinstance(out, PackRefusal), (code, out)
        assert out.code == code, (code, out)


def test_explain_surfaces_pack_verdict():
    packable = explain_text(_plan(CSAS.format(sink="s1", c="c1")))
    assert "PACK: packable with --pack-queries" in packable
    refused = explain_text(_plan(
        "SELECT COUNT(*) FROM s GROUP BY k, "
        "SESSION (INTERVAL 30 SECOND) EMIT CHANGES;"))
    assert "PACK: unpackable — session-window:" in refused


# ---- manual pack groups: zero recompiles + exact demux ----------------------


def _manual_pool():
    store = open_store("mem://")
    ctx = ServerContext(store, owns_store=False)
    ctx.streams.create_stream("src")
    return store, ctx, PackPool(ctx, manual=True)


def test_attach_never_lands_on_a_torn_down_group():
    """Regression pin (review): try_attach holds the pool lock across
    lookup+attach, so a concurrent detach of the group's last member
    can never pop the group (and stop its runner) between the two —
    which would strand the new member on a torn-down group that feeds
    nobody. Invariant: right after attach, the member's group IS the
    pool's registered group for its signature."""
    import threading

    store, ctx, pool = _manual_pool()
    try:
        plan_churn = _plan(CSAS.format(sink="sc", c="c"))
        plan_main = _plan(CSAS.format(sink="sq", c="c"))
        sink = lambda rows: None  # noqa: E731
        stop = threading.Event()

        def churn():
            i = 0
            while not stop.is_set():
                pool.try_attach(f"churn-{i}", plan_churn, sink)
                pool.detach(f"churn-{i}")
                i += 1

        t = threading.Thread(target=churn, daemon=True)
        t.start()
        try:
            for i in range(300):
                task = pool.try_attach(f"q-{i}", plan_main, sink)
                assert isinstance(task, PackMemberTask)
                # while q-i is attached the group cannot empty, so a
                # registered-group mismatch means attach raced a
                # teardown
                assert pool.member_of(f"q-{i}") is task.group
                assert pool.groups.get(task.group.sig) is task.group
                assert f"q-{i}" in task.group.members
                pool.detach(f"q-{i}")
        finally:
            stop.set()
            t.join(timeout=10)
    finally:
        ctx.shutdown()
        store.close()


def test_second_and_third_member_compile_nothing():
    """The headline: once the group's lattice is warm, attaching the
    2nd..Nth compatible query and streaming through it compiles ZERO
    new XLA executables — N queries, one program, one dispatch chain."""
    store, ctx, pool = _manual_pool()
    try:
        out1, out2, out3 = [], [], []
        t1 = pool.try_attach("q1", _plan(CSAS.format(sink="s1", c="c1")),
                             out1.extend)
        assert isinstance(t1, PackMemberTask)
        g = pool.member_of("q1")
        # warm member 1 with 4-row batches anchored at k0 and sweeping
        # to k33: input cap stays in the width-4 bucket while the key
        # id span crosses 32 — the 6-bit ladder rung, leaving headroom
        # for the ids new members will mint
        for w in range(11):
            ks = ["k0"] + [f"k{3 * w + i}" for i in (1, 2, 3)]
            g.feed([{"k": k} for k in ks], BASE + w * 10_000, lsn=10 + w)
        assert out1, "warm windows must have closed and emitted"

        t2 = pool.try_attach("q2", _plan(CSAS.format(sink="s2", c="c2")),
                             out2.extend)
        assert isinstance(t2, PackMemberTask)
        with RetraceGuard() as guard:
            # 2 members x 2 rows = tagged width 4: same pow2 bucket
            for w in range(11, 15):
                g.feed([{"k": "k1"}, {"k": "k2"}],
                       BASE + w * 10_000, lsn=100 + w)
        assert guard.count == 0, \
            f"2nd member recompiled {guard.count}x"
        assert out2, "2nd member demuxed no rows"

        t3 = pool.try_attach("q3", _plan(CSAS.format(sink="s3", c="c3")),
                             out3.extend)
        with RetraceGuard() as guard:
            # 3 members x 1 row = tagged width 3, pads into the 4 bucket
            for w in range(15, 19):
                g.feed([{"k": "k1"}], BASE + w * 10_000, lsn=200 + w)
        assert guard.count == 0, \
            f"3rd member recompiled {guard.count}x"
        assert out3, "3rd member demuxed no rows"

        st = g.status()
        assert st["members"] == ["q1", "q2", "q3"]
        assert st["compiled"] and st["batches"] >= 19
        # every member rode the SAME executor object
        assert pool.member_of("q2") is g and pool.member_of("q3") is g
    finally:
        ctx.shutdown()
        store.close()


def test_demux_exact_vs_standalone_executors():
    """Each member's packed output must equal a standalone executor fed
    the identical row/ts sequence — including its own SELECT-list
    renames (c1 vs c2)."""
    store, ctx, pool = _manual_pool()
    try:
        p1 = _plan(CSAS.format(sink="s1", c="c1"))
        p2 = _plan(CSAS.format(sink="s2", c="c2"))
        out1, out2 = [], []
        pool.try_attach("q1", p1, out1.extend)
        pool.try_attach("q2", p2, out2.extend)
        g = pool.member_of("q1")

        batches = []
        for w in range(6):
            rows = [{"k": k} for k in ("a", "b", "a", "c")]
            batches.append((rows, [BASE + w * 10_000 + i
                                   for i in range(4)]))
        for i, (rows, ts) in enumerate(batches):
            g.feed(rows, ts, lsn=10 + i)

        def reference(plan):
            ex = make_executor(plan.select,
                               sample_rows=[{"k": "a"}])
            out = []
            for rows, ts in batches:
                out.extend(ex.process(rows, ts))
            return out

        key = lambda r: (r.get("winStart"), sorted(r.items()))  # noqa: E731
        ref1 = reference(p1)
        assert ref1, "reference emitted nothing; test is vacuous"
        assert sorted(out1, key=key) == sorted(ref1, key=key)
        ref2 = reference(p2)
        assert sorted(out2, key=key) == sorted(ref2, key=key)
        # the two members' rows really differ only by the rename
        assert {"c1"} == {k for r in out1 for k in r} - \
            {"k", "winStart", "winEnd"}
        assert {"c2"} == {k for r in out2 for k in r} - \
            {"k", "winStart", "winEnd"}
    finally:
        ctx.shutdown()
        store.close()


def test_attach_lsn_gates_late_members_and_detach_tears_down():
    store, ctx, pool = _manual_pool()
    try:
        out1, out2 = [], []
        pool.try_attach("q1", _plan(CSAS.format(sink="s1", c="c1")),
                        out1.extend)
        g = pool.member_of("q1")
        m1_lsn = g.members["q1"].attach_lsn
        # rows BEFORE q2 attaches belong to q1 alone
        g.feed([{"k": "a"}], BASE, lsn=m1_lsn + 1)
        pool.try_attach("q2", _plan(CSAS.format(sink="s2", c="c2")),
                        out2.extend)
        g.members["q2"].attach_lsn = m1_lsn + 5  # attach point
        g.feed([{"k": "a"}], BASE + 1, lsn=m1_lsn + 2)   # pre-attach
        g.feed([{"k": "a"}], BASE + 2, lsn=m1_lsn + 6)   # post-attach
        g.feed([{"k": "z"}], BASE + 30_000, lsn=m1_lsn + 7)  # closer
        c1 = max(r["c1"] for r in out1 if r["k"] == "a")
        c2 = max(r["c2"] for r in out2 if r["k"] == "a")
        assert c1 == 3      # saw all three rows
        assert c2 == 1      # only the post-attach row
        # detach: the pool forgets members; the group dies with the last
        pool.detach("q1")
        assert pool.member_of("q1") is None
        assert g.status()["members"] == ["q2"]
        pool.detach("q2")
        assert pool.groups == {} and pool.member_of("q2") is None
    finally:
        ctx.shutdown()
        store.close()


# ---- server-level packing: --pack-queries end to end ------------------------


def _wait(cond, timeout=20.0, step=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_server_packs_compatible_queries_one_group():
    server, ctx = serve("127.0.0.1", 0, "mem://", pack_queries=True)
    ch = None
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
        stub = HStreamApiStub(ch)
        stub.CreateStream(pb.Stream(stream_name="src"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk1", c="c1")))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text=CSAS.format(sink="snk2", c="c2")))
        tasks = list(ctx.running_queries.values())
        assert len(tasks) == 2
        assert all(getattr(t, "packed", False) for t in tasks)
        # ONE group, both members — the admin surface agrees
        packs = ctx.pack_pool.status()
        assert len(packs) == 1 and len(packs[0]["members"]) == 2
        resp = stub.SendAdminCommand(pb.AdminCommandRequest(
            command="placer", args=rec.dict_to_struct({})))
        import json

        assert len(json.loads(resp.result)["packs"]) == 1

        # stream rows through the shared runner; both sinks materialize
        req = pb.AppendRequest(stream_name="src")
        for i, t in enumerate([BASE, BASE + 1, BASE + 2]):
            req.records.append(rec.build_record({"k": "a", "i": i},
                                                publish_time_ms=t))
        stub.Append(req)
        closer = pb.AppendRequest(stream_name="src")
        closer.records.append(rec.build_record(
            {"k": "zz"}, publish_time_ms=BASE + 30_000))
        stub.Append(closer)

        def emitted(stream, col):
            rows = _read_sink(ctx, stream)
            return [r for r in rows if r.get("k") == "a"
                    and r.get(col) == 3]

        assert _wait(lambda: emitted("snk1", "c1") and
                     emitted("snk2", "c2"), timeout=30), \
            (_read_sink(ctx, "snk1"), _read_sink(ctx, "snk2"))
        # terminating one member leaves the other streaming
        qids = sorted(ctx.running_queries)
        stub.TerminateQueries(pb.TerminateQueriesRequest(
            query_ids=[qids[0]]))
        assert _wait(lambda: len(ctx.pack_pool.status()) == 1 and
                     len(ctx.pack_pool.status()[0]["members"]) == 1)
    finally:
        if ch is not None:
            ch.close()
        server.stop(grace=0.5)
        ctx.shutdown()


def _read_sink(ctx, stream):
    from hstream_tpu.common import columnar
    from hstream_tpu.store.api import DataBatch

    logid = ctx.streams.get_logid(stream)
    tail = ctx.store.tail_lsn(logid)
    out = []
    if not tail:
        return out
    r = ctx.store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, 1, tail)
    while True:
        items = r.read(256)
        if not items:
            break
        for it in items:
            if not isinstance(it, DataBatch):
                continue
            for p in it.payloads:
                pr = rec.parse_record(p)
                crows = columnar.payload_rows(pr.payload)
                if crows is not None:
                    out.extend(crows)
                    continue
                row = rec.record_to_dict(pr)
                if row is not None:
                    out.append(row)
    return out
