"""Native store durability: reopen, torn-tail recovery, corrupt-frame
truncation, meta-WAL compaction replay, and the async append path.

The recovery machinery (nstore.cpp: CRC-validated frames, truncate at
first bad frame on open, meta.wal replay + compaction) is the point of
having a native store — these tests kill/corrupt and reopen it.
Reference: the checkpointed-store durability the LogDevice layer gives
the reference for free (hs_checkpoint.cpp, hs_writer.cpp:29-51).
"""

import os

import pytest

from hstream_tpu.store.api import (
    Compression,
    DataBatch,
    GapRecord,
    LogAttrs,
    LSN_MIN,
)
from hstream_tpu.store.native import NativeLogStore


def read_all(store, logid):
    r = store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, LSN_MIN)
    out = []
    while True:
        got = r.read(256)
        if not got:
            return out
        out.extend(got)


def payloads_of(items):
    return [p for it in items if isinstance(it, DataBatch)
            for p in it.payloads]


def seg_files(root, logid):
    d = os.path.join(root, "logs", str(logid))
    return sorted(f for f in os.listdir(d) if f.startswith("seg."))


def test_reopen_preserves_everything(tmp_path):
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    store.create_log(7, LogAttrs(replication_factor=3,
                                 extras={"kind": "stream"}))
    lsns = [store.append_batch(7, [f"r{i}".encode(), b"x"])
            for i in range(10)]
    store.append_batch(7, [b"zlib" * 100], compression=Compression.ZLIB)
    store.meta_put("cfg/a", b"v1")
    store.meta_put("cfg/b", b"v2")
    store.meta_delete("cfg/b")
    tail = store.tail_lsn(7)
    store.close()

    re = NativeLogStore(root)
    assert re.log_exists(7) and re.tail_lsn(7) == tail
    attrs = re.log_attrs(7)
    assert attrs.replication_factor == 3
    assert attrs.extras == {"kind": "stream"}
    got = payloads_of(read_all(re, 7))
    assert got[:2] == [b"r0", b"x"] and got[-1] == b"zlib" * 100
    assert len(got) == 21
    assert re.meta_get("cfg/a") == b"v1"
    assert re.meta_get("cfg/b") is None
    # appends continue with increasing LSNs after reopen
    assert re.append_batch(7, [b"after"]) > tail
    re.close()


def test_torn_tail_truncated_on_open(tmp_path):
    """A crash mid-write leaves a partial frame at the segment tail; open
    must truncate it and keep every complete frame (nstore.cpp torn-tail
    validation)."""
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    store.create_log(9)
    for i in range(5):
        store.append_batch(9, [f"ok{i}".encode()])
    store.close()

    seg = os.path.join(root, "logs", "9", seg_files(root, 9)[-1])
    with open(seg, "ab") as f:  # torn frame: valid magic, then garbage
        f.write(b"NSBK" + b"\x01\x02\x03")

    re = NativeLogStore(root)
    got = payloads_of(read_all(re, 9))
    assert got == [f"ok{i}".encode() for i in range(5)]
    # the torn bytes are gone; new appends land cleanly and survive
    lsn = re.append_batch(9, [b"new"])
    assert lsn == re.tail_lsn(9)
    re.close()
    re2 = NativeLogStore(root)
    assert payloads_of(read_all(re2, 9))[-1] == b"new"
    re2.close()


def test_corrupt_frame_truncates_to_last_good(tmp_path):
    """Bit-rot inside the LAST frame fails its CRC; open truncates back
    to the previous good frame instead of serving corrupt data."""
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    store.create_log(11)
    for i in range(4):
        store.append_batch(11, [f"keep{i}".encode()])
    store.append_batch(11, [b"doomed-payload-xxxx"])
    store.close()

    seg = os.path.join(root, "logs", "11", seg_files(root, 11)[-1])
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:  # flip a byte near the end (payload/CRC)
        f.seek(size - 5)
        b = f.read(1)
        f.seek(size - 5)
        f.write(bytes([b[0] ^ 0xFF]))

    re = NativeLogStore(root)
    got = payloads_of(read_all(re, 11))
    assert got == [f"keep{i}".encode() for i in range(4)]
    re.close()


def test_meta_wal_compaction_replay(tmp_path):
    """Overwrites + deletes force the meta WAL through compaction; the
    replayed state after reopen is exactly the final KV contents."""
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    big = b"v" * 4096
    # ~16MB of WAL traffic, live set ~2MB: without compaction the WAL
    # ends ~16MB, with it well under the 4MB trigger + one round's worth
    for round_ in range(8):
        for i in range(500):
            store.meta_put(f"k{i}", big)
    for i in range(0, 500, 2):
        store.meta_delete(f"k{i}")
    store.meta_put("last", b"final")
    wal = os.path.getsize(os.path.join(root, "meta.wal"))
    assert wal < (4 << 20) + 3 * (1 << 20), \
        f"compaction never ran (wal={wal})"
    store.close()

    re = NativeLogStore(root)
    assert re.meta_get("last") == b"final"
    assert re.meta_get("k0") is None and re.meta_get("k2") is None
    assert re.meta_get("k1") == big
    assert len(re.meta_list("k")) == 250
    re.close()


def test_async_append_concurrent_first_use(tmp_path):
    """Many threads racing the FIRST append_async must share one
    appender (pre-fix: unlocked lazy init could build two appenders with
    colliding token counters on the one completion queue)."""
    import threading

    store = NativeLogStore(str(tmp_path / "st"))
    store.create_log(21)
    results: list[list[int]] = [[] for _ in range(8)]
    errs: list[BaseException] = []
    start = threading.Barrier(8)

    def work(t):
        try:
            start.wait(5)
            futs = [store.append_async(21, [f"t{t}b{i}".encode()])
                    for i in range(25)]
            results[t] = [f.result(timeout=15) for f in futs]
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work, args=(t,)) for t in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(30)
    assert not errs, errs
    all_lsns = [lsn for r in results for lsn in r]
    assert len(all_lsns) == 200 and len(set(all_lsns)) == 200
    assert store.tail_lsn(21) == max(all_lsns)
    store.close()


def test_trim_survives_reopen(tmp_path):
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    store.create_log(13)
    lsns = [store.append_batch(13, [f"p{i}".encode()]) for i in range(6)]
    store.trim(13, lsns[2])
    store.close()
    re = NativeLogStore(root)
    assert re.trim_point(13) == lsns[2]
    items = read_all(re, 13)
    assert isinstance(items[0], GapRecord)
    assert payloads_of(items) == [b"p3", b"p4", b"p5"]
    re.close()


def test_async_append_durable_and_ordered(tmp_path):
    """append_async futures resolve to increasing LSNs once durable; a
    reopen sees every completed append (the reference's async writer
    path, hs_writer.cpp:29-51)."""
    root = str(tmp_path / "st")
    store = NativeLogStore(root)
    store.create_log(15)
    futs = [store.append_async(15, [f"a{i}".encode()]) for i in range(50)]
    lsns = [f.result(timeout=10) for f in futs]
    assert lsns == sorted(lsns) and len(set(lsns)) == 50
    assert store.tail_lsn(15) == lsns[-1]
    store.close()
    re = NativeLogStore(root)
    assert payloads_of(read_all(re, 15)) == [f"a{i}".encode()
                                             for i in range(50)]
    re.close()


def test_push_query_uses_async_sink_on_native_store(tmp_path):
    """End-to-end push query on the native store: emitted rows flow
    through the async append sink (stream_sink pending futures) and
    reach the subscriber."""
    import threading
    import time

    import grpc

    from hstream_tpu.common import records as rec
    from hstream_tpu.proto import api_pb2 as pb
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    BASE = 1_700_000_000_000
    server, ctx = serve("127.0.0.1", 0, str(tmp_path / "store"))
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="asink"))
        got = []
        started = threading.Event()

        def consume():
            call = stub.ExecutePushQuery(pb.CommandPushQuery(
                query_text="SELECT k, COUNT(*) AS c FROM asink "
                           "GROUP BY k, TUMBLING (INTERVAL 10 SECOND) "
                           "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;"))
            started.set()
            try:
                for s in call:
                    got.append(rec.struct_to_dict(s))
            except grpc.RpcError:
                pass

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        started.wait(5)
        from helpers import wait_any_attached
        wait_any_attached(ctx)  # fresh server: no pre-existing tasks
        req = pb.AppendRequest(stream_name="asink")
        for i in range(4):
            req.records.append(rec.build_record(
                {"k": "a" if i % 2 else "b"}, publish_time_ms=BASE + i))
        stub.Append(req)
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(r.get("c") == 2 for r in got):
                break
            time.sleep(0.2)
        assert any(r.get("c") == 2 for r in got), got
        stub.TerminateQueries(pb.TerminateQueriesRequest(all=True))
        t.join(10)
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_async_append_unknown_log_fails_future(tmp_path):
    store = NativeLogStore(str(tmp_path / "st"))
    fut = store.append_async(999, [b"x"])
    with pytest.raises(Exception):
        fut.result(timeout=10)
    store.close()
