"""AckWindow property tests — the successor/range-merge logic the
SURVEY flags as property-test-worthy (reference Common.hs:119-166)."""

import random

from hstream_tpu.server.subscriptions import AckWindow, RecId


def deliver(win, batches):
    for lsn, size in batches:
        win.note_batch(lsn, size)


def all_ids(batches):
    return [RecId(lsn, i) for lsn, size in batches for i in range(size)]


def test_in_order_acks_commit_everything():
    win = AckWindow()
    batches = [(1, 3), (2, 1), (3, 2)]
    deliver(win, batches)
    for rid in all_ids(batches):
        win.ack(rid)
    assert win.advance() == 3
    assert win.ranges == []


def test_out_of_order_acks_commit_only_prefix():
    win = AckWindow()
    deliver(win, [(1, 2), (2, 2)])
    win.ack(RecId(2, 0))
    win.ack(RecId(2, 1))
    assert win.advance() is None          # lower bound still at (1,0)
    win.ack(RecId(1, 1))
    assert win.advance() is None          # (1,0) still missing
    win.ack(RecId(1, 0))
    assert win.advance() == 2             # everything acked


def test_gap_counts_as_acked():
    win = AckWindow()
    win.note_batch(1, 1)
    win.ack(RecId(1, 0))
    win.note_gap(2, 5)                    # trim gap: auto-acked
    win.note_batch(6, 1)
    win.ack(RecId(6, 0))
    assert win.advance() == 6


def test_partial_batch_commits_previous_lsn():
    win = AckWindow()
    deliver(win, [(1, 1), (2, 3)])
    win.ack(RecId(1, 0))
    win.ack(RecId(2, 0))
    win.ack(RecId(2, 1))
    # batch 2 only partially acked -> ckp stops at lsn 1
    assert win.advance() == 1


def test_successor_across_unknown_lsn_defers():
    win = AckWindow()
    win.note_batch(1, 1)
    win.ack(RecId(1, 0))
    assert win.advance() == 1
    # next batch arrives later with a dense successor lsn
    win.note_batch(2, 2)
    win.ack(RecId(2, 1))
    assert win.advance() is None
    win.ack(RecId(2, 0))
    assert win.advance() == 2


def test_property_random_ack_orders():
    """Any ack permutation commits exactly the fully-acked prefix, and
    after all acks the checkpoint covers the whole delivery."""
    rng = random.Random(42)
    for trial in range(50):
        n_batches = rng.randint(1, 8)
        batches = [(lsn, rng.randint(1, 4))
                   for lsn, _ in enumerate(range(n_batches), start=1)]
        win = AckWindow()
        deliver(win, batches)
        ids = all_ids(batches)
        rng.shuffle(ids)
        committed = 0
        acked: set[RecId] = set()
        for rid in ids:
            win.ack(rid)
            acked.add(rid)
            got = win.advance()
            if got is not None:
                committed = got
            # invariant: committed == largest lsn L such that every
            # record of every batch <= L is acked
            expect = 0
            for lsn, size in batches:
                if all(RecId(lsn, i) in acked for i in range(size)):
                    expect = lsn
                else:
                    break
            assert committed == expect, (trial, rid, committed, expect)
        assert committed == batches[-1][0]
        assert win.ranges == []


def test_property_interleaved_delivery_and_acks():
    """Delivery interleaved with acks (batches become known over time)."""
    rng = random.Random(7)
    for trial in range(30):
        n_batches = rng.randint(2, 8)
        batches = [(lsn, rng.randint(1, 3))
                   for lsn in range(1, n_batches + 1)]
        win = AckWindow()
        committed = 0
        acked: set[RecId] = set()
        pending: list[RecId] = []
        delivered = 0
        while delivered < len(batches) or pending:
            if delivered < len(batches) and (not pending or rng.random() < 0.5):
                lsn, size = batches[delivered]
                win.note_batch(lsn, size)
                pending.extend(RecId(lsn, i) for i in range(size))
                rng.shuffle(pending)
                delivered += 1
            else:
                rid = pending.pop()
                win.ack(rid)
                acked.add(rid)
                got = win.advance()
                if got is not None:
                    committed = got
        assert committed == batches[-1][0], trial
