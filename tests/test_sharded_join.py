"""Sharded interval join vs single-chip equivalence (ISSUE 16).

With a mesh whose key axis has >1 devices, JoinExecutor key-shards
both side stores (`code % n_shards`), ownership-masks probe/insert
under shard_map, CONCATs the per-shard match buffers over the mesh,
and feeds the fused probe+insert step into the sharded downstream
aggregate lattice. These tests pin the sharded path to the single-chip
device path byte-for-byte through eviction, store growth, code
compaction, and snapshot migration across mesh sizes.
"""

import numpy as np
import pytest

from hstream_tpu.sql import stream_codegen
from hstream_tpu.sql.codegen import make_executor

BASE = 1_700_000_000_000
SQL = ("SELECT l.k, COUNT(*) AS c, SUM(l.x) AS s FROM l INNER JOIN r "
       "WITHIN (INTERVAL 10 SECOND) ON l.k = r.k "
       "GROUP BY l.k, TUMBLING (INTERVAL 10 SECOND) "
       "GRACE BY INTERVAL 0 SECOND EMIT CHANGES;")


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return Mesh(np.array(devs[:8]).reshape(1, 8), ("data", "key"))


def make_join(sql=SQL, mesh=None, **tune):
    ex = make_executor(stream_codegen(sql),
                       sample_rows=[{"k": "k0", "x": 1.0}], mesh=mesh)
    for k, v in tune.items():
        setattr(ex, k, v)
    return ex


def run_batches(ex, batches, compact_at=()):
    out = []
    for i, (rows, ts, side) in enumerate(batches):
        out.extend(ex.process(rows, ts, stream=side))
        if i in compact_at and ex._dev is not None:
            ex._compact_codes()
    out.extend(ex.flush_changes())
    assert not ex.has_pending_changes()
    return out


def final_changes(rows):
    """Last change per (key, winStart): EMIT CHANGES retracts and
    re-emits, so equivalence compares the settled value."""
    last = {}
    for r in rows:
        last[(r["l.k"], r["winStart"])] = (r["c"], round(r["s"], 3))
    return last


def gen_batches(seed=3, n_batches=18, n=400, stride=900, jitter=1400,
                key_lo_step=23, key_span=120):
    """Alternating-side traffic: rotating key population (code churn),
    span past retention (eviction), out-of-order within each batch."""
    rng = np.random.default_rng(seed)
    batches = []
    for b in range(n_batches):
        lo = b * key_lo_step
        rows = [{"k": f"k{int(i)}", "x": float(v)}
                for i, v in zip(rng.integers(lo, lo + key_span, n),
                                rng.normal(1, 1, n))]
        ts = (BASE + b * stride
              + rng.integers(0, jitter, n).astype(np.int64))
        batches.append((rows, ts.tolist(), "l" if b % 2 else "r"))
    return batches


def test_sharded_join_matches_single_chip(mesh):
    """Baseline: same batches, byte-identical settled rows, fused
    sharded dispatches actually taken (no silent degrade)."""
    batches = gen_batches(seed=7, n_batches=10, n=250, key_lo_step=0,
                          key_span=40)
    single = make_join()
    ref = final_changes(run_batches(single, batches))
    assert single._dev is not None, single._device_refusal

    ex = make_join(mesh=mesh)
    got = final_changes(run_batches(ex, batches))
    assert ex._dev is not None, ex._device_refusal
    assert ex._dev.get("sjl") is not None, "mesh did not shard stores"
    assert ex.sharded_dispatches > 0
    assert ex.device_fallbacks == 0
    assert ref == got


def test_sharded_join_evict_grow_compact(mesh):
    """Stress parity: store eviction, capacity growth (tiny initial
    store caps) and mid-run code compaction on BOTH paths; every
    settled row identical."""
    batches = gen_batches()
    single = make_join(DEVICE_STORE_CAPACITY=1024)
    ref = final_changes(run_batches(single, batches, (5, 11)))
    assert single.join_stats["evict_dispatches"] > 0
    assert single.join_stats["store_grows"] > 0

    ex = make_join(mesh=mesh, DEVICE_STORE_CAPACITY=256)
    got = final_changes(run_batches(ex, batches, (5, 11)))
    assert ex._dev is not None and ex._dev.get("sjl") is not None
    assert ex.join_stats["evict_dispatches"] > 0, "no sharded evict"
    assert ex.join_stats["store_grows"] > 0, "no sharded grow"
    miss = {k: (ref[k], got.get(k)) for k in ref if ref[k] != got.get(k)}
    assert ref == got, dict(list(miss.items())[:5])


def test_join_mesh_size_migration(mesh):
    """Snapshot under one mesh size, restore under another (1 <-> 8):
    the snapshot holds the gathered host view of both side stores and
    the inner lattice, the restore re-shards on activation — including
    the lazily built inner downstream aggregate."""
    from hstream_tpu.engine.snapshot import (
        restore_executor,
        snapshot_executor,
    )

    sql = SQL.replace("INTERVAL 10 SECOND)\n", "INTERVAL 10 SECOND)")
    plan = stream_codegen(sql)
    batches = gen_batches(seed=9, n_batches=12, n=200, stride=600,
                          jitter=800, key_lo_step=0, key_span=40)

    def run(mesh_a, mesh_b, cut=6):
        ex = make_executor(plan, sample_rows=[{"k": "k0", "x": 1.0}],
                           mesh=mesh_a)
        out = []
        for rows, ts, side in batches[:cut]:
            out.extend(ex.process(rows, ts, stream=side))
        out.extend(ex.flush_changes())
        blob = snapshot_executor(ex)
        ex2, _ = restore_executor(plan, blob, mesh=mesh_b)
        for rows, ts, side in batches[cut:]:
            out.extend(ex2.process(rows, ts, stream=side))
        out.extend(ex2.flush_changes())
        assert not ex2.has_pending_changes()
        return final_changes(out), ex, ex2

    base, _, _ = run(None, None)
    up, _, exu2 = run(None, mesh)
    assert exu2._dev is not None and exu2._dev.get("sjl") is not None, \
        "restore onto mesh did not shard the join stores"
    assert getattr(exu2._inner, "_sharded", None) is not None, \
        "inner aggregate did not re-shard on restore"
    down, exd, exd2 = run(mesh, None)
    assert exd._dev is not None and exd._dev.get("sjl") is not None
    assert exd2._dev is None or exd2._dev.get("sjl") is None
    assert base == up
    assert base == down
