"""Sharded execution through the PRODUCT path: a server started with
--mesh routes eligible aggregates through ShardedQueryExecutor; results
must equal the single-chip server's exactly (SURVEY §2.3). Runs on the
8-virtual-device CPU mesh from conftest."""

import time

import grpc
import numpy as np
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached
from hstream_tpu.server.tasks import QueryTask, snapshot_key

BASE = 1_700_000_000_000

SQL = ("CREATE VIEW v AS SELECT device, COUNT(*) AS c, SUM(temp) AS s, "
       "MIN(temp) AS lo FROM src WHERE temp > 0 GROUP BY device, "
       "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND;")


def _spawn(mesh_shape):
    server, ctx = serve("127.0.0.1", 0, "mem://", mesh_shape=mesh_shape)
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    return server, ctx, ch, HStreamApiStub(ch)


def _feed_and_read(ctx, stub, rows, ts):
    stub.CreateStream(pb.Stream(stream_name="src"))
    stub.ExecuteQuery(pb.CommandQuery(stmt_text=SQL))
    wait_attached(ctx, "view-v")
    req = pb.AppendRequest(stream_name="src")
    for row, t in zip(rows, ts):
        req.records.append(rec.build_record(row, publish_time_ms=t))
    stub.Append(req)
    req = pb.AppendRequest(stream_name="src")
    req.records.append(rec.build_record({"device": "zz", "temp": 1.0},
                                        publish_time_ms=BASE + 30_000))
    stub.Append(req)
    deadline = time.time() + 60
    out = []
    while time.time() < deadline:
        resp = stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="SELECT * FROM v;"))
        out = [rec.struct_to_dict(s) for s in resp.result_set]
        if len([r for r in out if r.get("winStart") == BASE]) >= 6:
            break
        time.sleep(0.2)
    return sorted(
        (tuple(sorted(r.items())))
        for r in out if r.get("winStart") == BASE)


def _rows_close(a, b, rel=1e-4):
    """Row-set equality with float tolerance: f32 summation order
    differs across shard layouts (non-associative)."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        da, db = dict(ra), dict(rb)
        if set(da) != set(db):
            return False
        for k, va in da.items():
            vb = db[k]
            if isinstance(va, float) or isinstance(vb, float):
                if vb != pytest.approx(va, rel=rel, abs=1e-6):
                    return False
            elif va != vb:
                return False
    return True


def _dataset():
    rng = np.random.default_rng(11)
    rows = [{"device": f"d{int(d)}",
             "temp": float(np.float32(t))}
            for d, t in zip(rng.integers(0, 6, 300),
                            np.abs(rng.normal(20, 5, 300)) + 0.1)]
    # sprinkle filtered-out rows
    for i in range(0, 300, 17):
        rows[i]["temp"] = -1.0
    ts = [BASE + i * 10 for i in range(300)]
    return rows, ts


def test_sharded_server_equals_single_chip():
    rows, ts = _dataset()
    s1, c1, ch1, stub1 = _spawn(None)
    s2, c2, ch2, stub2 = _spawn("2x2")
    try:
        single = _feed_and_read(c1, stub1, rows, ts)
        sharded = _feed_and_read(c2, stub2, rows, ts)
        task = c2.running_queries["view-v"]
        assert type(task.executor).__name__ == "ShardedQueryExecutor"
        assert _rows_close(single, sharded), (single, sharded)
        assert len(sharded) == 6
    finally:
        for ch, s, c in ((ch1, s1, c1), (ch2, s2, c2)):
            ch.close()
            s.stop(grace=1)
            c.shutdown()


def test_sharded_kill_restart_resumes():
    """Snapshot/restore of SHARDED state: partials merge to a canonical
    blob, restore scatters it back; a crashed sharded view resumes
    without undercount."""
    server, ctx, ch, stub = _spawn("4x1")
    QueryTask.snapshot_interval_ms = 50
    try:
        stub.CreateStream(pb.Stream(stream_name="src"))
        stub.ExecuteQuery(pb.CommandQuery(stmt_text=SQL))
        qid = "view-v"
        wait_attached(ctx, qid)
        req = pb.AppendRequest(stream_name="src")
        for i in range(20):
            req.records.append(rec.build_record(
                {"device": f"d{i % 3}", "temp": 2.0},
                publish_time_ms=BASE + i))
        stub.Append(req)
        deadline = time.time() + 20
        while time.time() < deadline:
            if ctx.store.meta_get(snapshot_key(qid)) is not None:
                task = ctx.running_queries.get(qid)
                if task is not None and task.executor is not None \
                        and task.executor.watermark_abs >= BASE + 19:
                    break
            time.sleep(0.05)
        assert ctx.store.meta_get(snapshot_key(qid)) is not None
        ctx.running_queries[qid].stop(crash=True)
        stub.RestartQuery(pb.RestartQueryRequest(id=qid))
        task = wait_attached(ctx, qid)
        req = pb.AppendRequest(stream_name="src")
        req.records.append(rec.build_record({"device": "d0", "temp": 2.0},
                                            publish_time_ms=BASE + 100))
        req.records.append(rec.build_record({"device": "zz", "temp": 1.0},
                                            publish_time_ms=BASE + 30_000))
        stub.Append(req)
        deadline = time.time() + 30
        closed = {}
        while time.time() < deadline:
            resp = stub.ExecuteQuery(pb.CommandQuery(
                stmt_text="SELECT * FROM v;"))
            rows = [rec.struct_to_dict(s) for s in resp.result_set]
            closed = {r["device"]: r["c"] for r in rows
                      if r.get("winStart") == BASE}
            if closed.get("d0") == 8:
                break
            time.sleep(0.2)
        # d0: 7 from the first batch (i%3==0 for 20) + 1 after restart
        assert closed.get("d0") == 8, closed
        assert closed.get("d1") == 7 and closed.get("d2") == 6, closed
        assert type(task.executor).__name__ == "ShardedQueryExecutor"
    finally:
        QueryTask.snapshot_interval_ms = 1000
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_sharded_snapshot_restores_single_chip():
    """Mesh portability: a blob captured from a sharded executor
    restores into a single-chip executor with identical results."""
    from hstream_tpu.engine.snapshot import (
        restore_executor,
        snapshot_executor,
    )
    from hstream_tpu.parallel import make_mesh
    from hstream_tpu.sql.codegen import make_executor, stream_codegen

    plan = stream_codegen(
        "SELECT k, COUNT(*) AS c, SUM(v) AS s FROM s GROUP BY k, "
        "TUMBLING (INTERVAL 10 SECOND) GRACE BY INTERVAL 0 SECOND "
        "EMIT CHANGES;")
    sample = [{"k": "a", "v": 1.0}]
    mesh = make_mesh(n_data=2, n_key=2)
    sh = make_executor(plan, sample_rows=sample, mesh=mesh)
    rows = [{"k": f"k{i % 5}", "v": 1.0} for i in range(40)]
    ts = [BASE + i for i in range(40)]
    out_sh = sh.process(rows, ts)
    blob = snapshot_executor(sh)
    single, _ = restore_executor(plan, blob)  # no mesh

    def norm(rs):
        return sorted(tuple(sorted(r.items())) for r in rs
                      if r.get("winStart") == BASE)

    # live (open-window) state must be identical across mesh layouts
    a = norm(sh.peek())
    b = norm(single.peek())
    assert a == b and len(b) == 5, (a, b)
    assert sum(dict(r)["c"] for r in b) == 40
    # and both continue identically after the restore point
    more = ([{"k": "k0", "v": 1.0}], [BASE + 1000])
    sh.process(*more)
    single.process(*more)
    assert norm(sh.peek()) == norm(single.peek())
