"""Sharded session lattice vs single-chip equivalence (ISSUE 16).

The key-sharded session arena (ShardedSessionLattice under shard_map
on the 8-virtual-device CPU mesh) must produce byte-identical rows to
the single-chip session kernels for BOTH device kernel modes (record
and segment), through every stateful edge: out-of-order records, late
drops, code compaction (device remap), arena growth, deferred stacked
close drains, the degrade-to-host view, and snapshot migration across
mesh sizes (1 chip <-> 8-device mesh, re-shard on restore).
"""

import numpy as np
import pytest

from hstream_tpu.engine import ColumnType, Schema
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.plan import (
    AggKind,
    AggregateNode,
    AggSpec,
    SourceNode,
)
from hstream_tpu.engine.session import SessionExecutor
from hstream_tpu.engine.window import SessionWindow

BASE = 1_700_000_000_000
SCHEMA = Schema.of(k=ColumnType.STRING, v=ColumnType.FLOAT)
AGGS = [AggSpec(AggKind.COUNT_ALL, "c"),
        AggSpec(AggKind.SUM, "sv", input=Col("v")),
        AggSpec(AggKind.MIN, "mn", input=Col("v")),
        AggSpec(AggKind.MAX, "mx", input=Col("v"))]


@pytest.fixture(scope="module")
def mesh():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return Mesh(np.array(devs[:8]).reshape(1, 8), ("data", "key"))


def node_of(gap_ms, grace_ms, aggs=AGGS):
    return AggregateNode(
        child=SourceNode("s", SCHEMA), group_keys=[Col("k")],
        window=SessionWindow(gap_ms, grace_ms=grace_ms), aggs=aggs,
        having=None, post_projections=[])


def to_rows(out):
    if out is None:
        return []
    return out if isinstance(out, list) else out.rows()


def canon(rows, names=("c", "sv", "mn", "mx")):
    return sorted(
        (r["k"], int(r["winStart"]), int(r["winEnd"]))
        + tuple(round(float(r[n]), 4) for n in names)
        for r in rows)


def gen_ooo(seed, n_batches=10, batch=300, keys=40, late_frac=0.15):
    """Out-of-order traffic with a late tail reaching past grace."""
    rng = np.random.default_rng(seed)
    batches, t = [], BASE
    for _ in range(n_batches):
        ks = rng.integers(0, keys, batch)
        ts = t + rng.integers(0, 4000, batch)
        late = rng.random(batch) < late_frac
        ts = np.where(late, ts - rng.integers(3000, 20_000, batch), ts)
        vs = rng.integers(0, 1000, batch)
        rows = [{"k": f"u{int(k)}", "v": float(v)}
                for k, v in zip(ks, vs)]
        batches.append((rows, ts.tolist()))
        t += 2500
    return batches


@pytest.mark.parametrize("mode", ["record", "segment"])
def test_sharded_sessions_match_single_chip(mesh, mode):
    """Baseline equivalence: out-of-order + late drops, both kernel
    modes, zero device fallbacks on either side."""
    def run(m):
        kw = {} if m is None else {"mesh": m}
        ex = SessionExecutor(node_of(1000, 500), SCHEMA, **kw)
        ex.device_session_mode = mode
        out = []
        for rows, ts in gen_ooo(3):
            out.extend(to_rows(ex.process(rows, ts)))
        out.extend(to_rows(ex.drain_closed()))
        out.extend(to_rows(ex.peek()))
        assert ex.device_fallbacks == 0, ex._device_refusal
        return out, ex

    ref, _ = run(None)
    got, ex = run(mesh)
    assert ex._dev is not None and ex._dev.get("ssl") is not None, \
        ex._device_refusal
    assert ex.sharded_dispatches > 0
    assert canon(got) == canon(ref)


@pytest.mark.parametrize("mode", ["record", "segment"])
@pytest.mark.parametrize("defer", [False, True])
def test_sharded_sessions_compaction_and_deferred(mesh, mode, defer):
    """Rotating key population forces code compaction (device remap
    with the residue-class-preserving LUT) mid-run; with deferral on,
    several close cycles stack before each drain so the deferred
    extract buffers cross a compaction epoch."""
    aggs = AGGS[:2] + [AggSpec(AggKind.MAX, "mx", input=Col("v"))]

    def gen(seed, n_batches=14, batch=250):
        rng = np.random.default_rng(seed)
        batches, t = [], BASE
        for b in range(n_batches):
            ks = rng.integers(b * 37, b * 37 + 90, batch)
            ts = t + rng.integers(0, 3000, batch)
            late = rng.random(batch) < 0.1
            ts = np.where(late, ts - rng.integers(3000, 15_000, batch),
                          ts)
            vs = rng.integers(0, 1000, batch)
            rows = [{"k": f"u{int(k)}", "v": float(v)}
                    for k, v in zip(ks, vs)]
            batches.append((rows, ts.tolist()))
            t += 2000
        return batches

    def run(m):
        kw = {} if m is None else {"mesh": m}
        ex = SessionExecutor(node_of(800, 400, aggs), SCHEMA, **kw)
        ex.device_session_mode = mode
        ex.defer_close_decode = defer
        ex._KEY_CACHE_MAX = 128   # force code compaction mid-run
        out = []
        for i, (rows, ts) in enumerate(gen(11)):
            out.extend(to_rows(ex.process(rows, ts)))
            if defer and i % 5 == 4:
                out.extend(to_rows(ex.drain_closed()))
        out.extend(to_rows(ex.drain_closed()))
        # degrade path: the gathered host view of the (sharded) arena
        # must round-trip into the host reference state
        if ex._dev is not None:
            ex._degrade_to_host("test: host view check")
        out.extend(to_rows(ex.peek()))
        return out, ex

    names = ("c", "sv", "mx")
    ref, _ = run(None)
    got, ex = run(mesh)
    assert ex.session_stats["remap_dispatches"] > 0, "no remap fired"
    assert canon(got, names) == canon(ref, names)


@pytest.mark.parametrize("mode", ["record", "segment"])
def test_sharded_sessions_arena_growth(mesh, mode):
    """A live key population past the initial arena capacity grows
    the per-shard arenas (doubling under put_arena) on both paths."""
    aggs = AGGS[:2]

    def gen(seed, n_batches=8, batch=900, keys=3000):
        rng = np.random.default_rng(seed)
        batches, t = [], BASE
        for _ in range(n_batches):
            ks = rng.integers(0, keys, batch)
            ts = t + rng.integers(0, 1500, batch)
            vs = rng.integers(0, 100, batch)
            rows = [{"k": f"u{int(k)}", "v": float(v)}
                    for k, v in zip(ks, vs)]
            batches.append((rows, ts.tolist()))
            t += 1200
        return batches

    def run(m):
        kw = {} if m is None else {"mesh": m}
        # gap >> span: nothing closes, the arena only accretes
        ex = SessionExecutor(node_of(60_000, 100, aggs), SCHEMA, **kw)
        ex.device_session_mode = mode
        out = []
        for rows, ts in gen(5):
            out.extend(to_rows(ex.process(rows, ts)))
        out.extend(to_rows(ex.peek()))
        assert ex.device_fallbacks == 0, ex._device_refusal
        return out, ex

    names = ("c", "sv")
    ref, exa = run(None)
    got, exb = run(mesh)
    assert exa.session_stats["grows"] > 0, "single-chip never grew"
    assert exb.session_stats["grows"] > 0, "sharded never grew"
    assert canon(got, names) == canon(ref, names)


@pytest.mark.parametrize("mode", ["record", "segment"])
def test_session_mesh_size_migration(mesh, mode):
    """Snapshot on one mesh size, restore on another (1 chip <-> 8):
    the snapshot serializes the gathered host view, the restore
    re-shards (or un-shards) on activation, rows stay identical."""
    from hstream_tpu.engine.snapshot import (
        restore_executor,
        snapshot_executor,
    )

    aggs = AGGS[:2]
    node = node_of(1000, 500, aggs)

    class P:  # restore_executor only reads .node off the plan
        pass

    P.node = node

    def gen(seed=2, n_batches=10, batch=250, keys=30):
        rng = np.random.default_rng(seed)
        out, t = [], BASE
        for _ in range(n_batches):
            ks = rng.integers(0, keys, batch)
            ts = t + rng.integers(0, 3000, batch)
            vs = rng.integers(0, 500, batch)
            rows = [{"k": f"u{int(k)}", "v": float(v)}
                    for k, v in zip(ks, vs)]
            out.append((rows, ts.tolist()))
            t += 2200
        return out

    def run(mesh_a, mesh_b, cut=5):
        kw = {} if mesh_a is None else {"mesh": mesh_a}
        ex = SessionExecutor(node, SCHEMA, **kw)
        ex.device_session_mode = mode
        out, bs = [], gen()
        for rows, ts in bs[:cut]:
            out.extend(to_rows(ex.process(rows, ts)))
        blob = snapshot_executor(ex)
        ex2, _ = restore_executor(P(), blob, mesh=mesh_b)
        ex2.device_session_mode = mode
        for rows, ts in bs[cut:]:
            out.extend(to_rows(ex2.process(rows, ts)))
        out.extend(to_rows(ex2.peek()))
        return canon(out, ("c", "sv")), ex2

    base, _ = run(None, None)
    up, sx = run(None, mesh)
    assert sx._dev is not None and sx._dev.get("ssl") is not None, \
        ("restore onto mesh did not shard", sx._device_refusal)
    down, dx = run(mesh, None)
    assert dx._dev is None or dx._dev.get("ssl") is None
    assert base == up
    assert base == down
