"""Step-tracing subsystem (SURVEY §5.1): per-stage rings on query
tasks, exposed via GetQueryTrace and the admin CLI."""

import time

import grpc
import pytest

from hstream_tpu.common import records as rec
from hstream_tpu.common.tracing import QueryTracer, trace_span
from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import HStreamApiStub
from hstream_tpu.server.main import serve

from helpers import wait_attached

BASE = 1_700_000_000_000


def test_tracer_summary():
    tr = QueryTracer(capacity=4)
    for ms in (1, 2, 3, 10):
        tr.record("step", ms / 1e3)
    s = tr.summary()["step"]
    assert s["count"] == 4
    assert s["total_ms"] == pytest.approx(16.0, rel=0.01)
    assert s["p50_ms"] == pytest.approx(3.0, rel=0.01)
    with trace_span(tr, "emit"):
        time.sleep(0.003)
    assert tr.summary()["emit"]["count"] == 1
    assert tr.summary()["emit"]["mean_ms"] >= 2.0
    with trace_span(None, "noop"):  # tracer-less spans are free
        pass


def test_query_trace_rpc_and_admin():
    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="trsrc"))
        stub.ExecuteQuery(pb.CommandQuery(
            stmt_text="CREATE VIEW trview AS SELECT k, COUNT(*) AS c "
                      "FROM trsrc GROUP BY k, "
                      "TUMBLING (INTERVAL 10 SECOND) "
                      "GRACE BY INTERVAL 0 SECOND;"))
        wait_attached(ctx, "view-trview")
        req = pb.AppendRequest(stream_name="trsrc")
        for i in range(10):
            req.records.append(rec.build_record(
                {"k": f"k{i % 2}"}, publish_time_ms=BASE + i))
        stub.Append(req)
        deadline = time.time() + 20
        summary = {}
        while time.time() < deadline:
            summary = rec.struct_to_dict(stub.GetQueryTrace(
                pb.GetQueryRequest(id="view-trview")))
            if "step" in summary and "decode" in summary:
                break
            time.sleep(0.1)
        assert summary["step"]["count"] >= 1
        assert summary["decode"]["mean_ms"] >= 0
        # admin CLI renders it
        from hstream_tpu import admin

        class A:
            id = "view-trview"

        rows = admin.cmd_trace(stub, A)
        assert any(r["stage"] == "step" for r in rows)
        # unknown query -> NOT_FOUND
        with pytest.raises(grpc.RpcError) as ei:
            stub.GetQueryTrace(pb.GetQueryRequest(id="nope"))
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_jax_profiler_writes_trace(tmp_path):
    """The deep-profile hook (HSTREAM_PROFILE_DIR in bench.py) captures
    a TensorBoard trace directory."""
    import jax.numpy as jnp

    from hstream_tpu.common.tracing import jax_profiler

    out = str(tmp_path / "prof")
    with jax_profiler(out):
        jnp.sum(jnp.arange(128)).block_until_ready()
    import os

    files = [os.path.join(dp, f) for dp, _, fs in os.walk(out) for f in fs]
    assert files, "profiler produced no trace files"
