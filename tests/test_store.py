import threading
import time

import pytest

from hstream_tpu.common.errors import LogNotFound, StreamExists, StreamNotFound
from hstream_tpu.store import (
    CheckpointedReader,
    DataBatch,
    FileCheckpointStore,
    GapRecord,
    GapType,
    LogCheckpointStore,
    MemCheckpointStore,
    MemLogStore,
    StreamApi,
    StreamType,
)


@pytest.fixture(params=["mem", "native"])
def store(request, tmp_path):
    """Every store test runs against BOTH backends: the in-memory mock
    and the durable C++ segment-log store."""
    if request.param == "mem":
        yield MemLogStore()
    else:
        from hstream_tpu.store.native import NativeLogStore

        st = NativeLogStore(str(tmp_path / "nstore"))
        yield st
        st.close()


def batches(results):
    return [r for r in results if isinstance(r, DataBatch)]


def test_append_read_roundtrip(store):
    store.create_log(7)
    lsn1 = store.append(7, b"one")
    lsn2 = store.append_batch(7, [b"two", b"three"])
    assert lsn2 > lsn1
    reader = store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(7)
    out = reader.read(10)
    assert [b.payloads for b in batches(out)] == [(b"one",), (b"two", b"three")]
    assert out[0].lsn == lsn1 and out[1].lsn == lsn2
    # nothing more to read
    assert reader.read(10) == []


def test_read_from_lsn_and_until(store):
    store.create_log(1)
    lsns = [store.append(1, f"r{i}".encode()) for i in range(5)]
    reader = store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(1, from_lsn=lsns[2], until_lsn=lsns[3])
    out = batches(reader.read(10))
    assert [b.payloads[0] for b in out] == [b"r2", b"r3"]
    assert reader.read(10) == []


def test_trim_surfaces_gap(store):
    store.create_log(1)
    lsns = [store.append(1, f"r{i}".encode()) for i in range(4)]
    store.trim(1, lsns[1])
    assert store.trim_point(1) == lsns[1]
    reader = store.new_reader()
    reader.set_timeout(0)
    reader.start_reading(1)
    out = reader.read(10)
    assert isinstance(out[0], GapRecord)
    assert out[0].gap_type == GapType.TRIM
    assert out[0].hi_lsn == lsns[1]
    assert [b.payloads[0] for b in batches(out)] == [b"r2", b"r3"]


def test_blocking_read_wakes_on_append(store):
    store.create_log(1)
    reader = store.new_reader()
    reader.set_timeout(5000)
    reader.start_reading(1)
    got = []

    def consume():
        got.extend(reader.read(10))

    t = threading.Thread(target=consume)
    t.start()
    time.sleep(0.05)
    store.append(1, b"wake")
    t.join(timeout=5)
    assert not t.is_alive()
    assert batches(got)[0].payloads == (b"wake",)


def test_read_timeout(store):
    store.create_log(1)
    reader = store.new_reader()
    reader.set_timeout(50)
    reader.start_reading(1)
    t0 = time.monotonic()
    assert reader.read(10) == []
    assert time.monotonic() - t0 >= 0.04


def test_find_time_and_tail(store):
    store.create_log(1)
    assert store.is_log_empty(1)
    lsn = store.append(1, b"x")
    assert store.tail_lsn(1) == lsn
    assert not store.is_log_empty(1)
    assert store.find_time(1, 0) == lsn
    assert store.find_time(1, int(time.time() * 1000) + 10_000) == lsn + 1


def test_missing_log(store):
    with pytest.raises(LogNotFound):
        store.append(99, b"x")
    reader = store.new_reader()
    with pytest.raises(LogNotFound):
        reader.start_reading(99)


# ---- streams namespace ----

def test_stream_api(store):
    api = StreamApi(store)
    logid = api.create_stream("s1", replication_factor=3)
    assert api.stream_exists("s1")
    assert api.get_logid("s1") == logid
    assert api.stream_meta("s1")["replication_factor"] == 3
    with pytest.raises(StreamExists):
        api.create_stream("s1")
    # distinct namespaces
    vlogid = api.create_stream("s1", stream_type=StreamType.VIEW)
    assert vlogid != logid
    assert api.find_streams() == ["s1"]
    assert api.find_streams(StreamType.VIEW) == ["s1"]
    api.append("s1", b"data")
    assert store.tail_lsn(logid) != 0
    api.delete_stream("s1")
    assert not api.stream_exists("s1")
    with pytest.raises(StreamNotFound):
        api.get_logid("s2")
    # cache invalidated on delete
    with pytest.raises(StreamNotFound):
        api.get_logid("s1")


# ---- checkpoint stores ----

@pytest.mark.parametrize("make", [
    lambda store, tmp_path: MemCheckpointStore(),
    lambda store, tmp_path: FileCheckpointStore(str(tmp_path / "ckp.json")),
    lambda store, tmp_path: LogCheckpointStore(store),
])
def test_checkpoint_store(store, tmp_path, make):
    cs = make(store, tmp_path)
    assert cs.get("c1", 1) is None
    cs.update("c1", 1, 100)
    cs.update_multi("c1", {2: 200, 3: 300})
    cs.update("c2", 1, 999)
    assert cs.get("c1", 1) == 100
    assert cs.all_for("c1") == {1: 100, 2: 200, 3: 300}
    cs.update("c1", 1, 150)
    assert cs.get("c1", 1) == 150
    cs.remove("c1")
    assert cs.all_for("c1") == {}
    assert cs.get("c2", 1) == 999


def test_file_checkpoint_persistence(tmp_path):
    path = str(tmp_path / "ckp.json")
    cs = FileCheckpointStore(path)
    cs.update("c1", 5, 42)
    cs2 = FileCheckpointStore(path)
    assert cs2.get("c1", 5) == 42


def test_log_checkpoint_replay_and_compaction(store):
    cs = LogCheckpointStore(store, compact_every=4)
    for i in range(10):
        cs.update("c1", 1, i)
    cs.update("c2", 7, 70)
    # fresh instance replays the log (incl. post-compaction snapshot)
    cs2 = LogCheckpointStore(store)
    assert cs2.get("c1", 1) == 9
    assert cs2.get("c2", 7) == 70


def test_checkpointed_reader(store):
    api = StreamApi(store)
    logid = api.create_stream("s")
    lsns = [store.append(logid, f"r{i}".encode()) for i in range(5)]
    cs = MemCheckpointStore()
    r1 = CheckpointedReader("task-1", store.new_reader(), cs)
    r1.set_timeout(0)
    start = r1.start_reading_from_checkpoint(logid)
    assert start == 1
    out = batches(r1.read(3))
    r1.write_checkpoints({logid: out[-1].lsn})
    # resume from checkpoint
    r2 = CheckpointedReader("task-1", store.new_reader(), cs)
    r2.set_timeout(0)
    r2.start_reading_from_checkpoint(logid)
    out2 = batches(r2.read(10))
    assert [b.payloads[0] for b in out2] == [b"r3", b"r4"]
