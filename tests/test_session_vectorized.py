"""Vectorized session segmentation must be record-for-record equivalent
to the per-record merge path: batch process() vs one-row-at-a-time
process() over randomized, out-of-order, late-record workloads."""
from __future__ import annotations

import numpy as np
import pytest

from hstream_tpu.engine import ColumnType, Schema
from hstream_tpu.engine.expr import Col
from hstream_tpu.engine.plan import AggKind, AggregateNode, AggSpec, SourceNode
from hstream_tpu.engine.session import SessionExecutor
from hstream_tpu.engine.window import SessionWindow

BASE = 1_700_000_000_000


def make_ex(aggs, gap=1000, grace=500, emit_changes=False):
    schema = Schema.of(k=ColumnType.STRING, v=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema), group_keys=[Col("k")],
        window=SessionWindow(gap, grace_ms=grace), aggs=aggs)
    ex = SessionExecutor(node, schema, emit_changes=emit_changes)
    # this file validates the HOST reference engine against the
    # per-record oracle (it inspects ex.sessions directly); device/host
    # equivalence has its own suite (tests/test_session_device.py)
    ex.use_device_sessions = False
    return ex


def gen(seed, n_batches=8, batch=300, keys=12, late_frac=0.15):
    rng = np.random.default_rng(seed)
    batches = []
    t = BASE
    for _ in range(n_batches):
        ks = rng.integers(0, keys, batch)
        # mostly-forward timestamps with jitter; some records far behind
        # the watermark to exercise the late policy
        ts = t + rng.integers(0, 4000, batch)
        late = rng.random(batch) < late_frac
        ts = np.where(late, ts - rng.integers(3000, 20_000, batch), ts)
        vs = np.abs(rng.normal(50, 20, batch))
        rows = [{"k": f"u{int(k)}", "v": float(v)}
                for k, v in zip(ks, vs)]
        batches.append((rows, ts.tolist()))
        t += 2500
    return batches


def canon_state(ex):
    out = {}
    for key, sess_list in ex.sessions.items():
        out[key] = [(s.start, s.end, _canon_accs(s.accs))
                    for s in sorted(sess_list, key=lambda s: s.start)]
    return out


def _canon_accs(accs):
    c = {}
    for k, v in accs.items():
        if isinstance(v, np.ndarray):
            c[k] = v.tolist()
        elif isinstance(v, tuple):
            c[k] = tuple(round(float(x), 9) for x in v)
        elif isinstance(v, float):
            c[k] = round(v, 9)
        elif isinstance(v, list):
            c[k] = [round(float(x), 9) for x in v]
        else:
            c[k] = v
    return c


def canon_rows(rows):
    return sorted(
        (tuple(sorted((k, round(v, 6) if isinstance(v, float) else
                       tuple(v) if isinstance(v, list) else v)
                      for k, v in r.items())))
        for r in rows)


AGG_SETS = [
    [AggSpec(AggKind.COUNT_ALL, "c"),
     AggSpec(AggKind.SUM, "s", input=Col("v")),
     AggSpec(AggKind.AVG, "a", input=Col("v"))],
    [AggSpec(AggKind.MIN, "lo", input=Col("v")),
     AggSpec(AggKind.MAX, "hi", input=Col("v")),
     AggSpec(AggKind.COUNT, "n", input=Col("v"))],
    [AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("v"), quantile=0.5),
     AggSpec(AggKind.APPROX_COUNT_DISTINCT, "d", input=Col("v"))],
    [AggSpec(AggKind.TOPK, "top", input=Col("v"), k=3)],
]


def oracle_process(ex, rows, ts):
    """The pre-vectorization batch semantics, verbatim: every record
    walks the per-record merge path in ts order under the pre-batch
    watermark; watermark advances and sessions close at batch end."""
    order = sorted(range(len(rows)), key=lambda i: ts[i])
    for i in order:
        ex._ingest_row(rows[i], int(ts[i]))
    new_wm = max(int(t) for t in ts)
    if new_wm > ex.watermark:
        ex.watermark = new_wm
    return ex.close_due_sessions()


@pytest.mark.parametrize("aggset", range(len(AGG_SETS)))
@pytest.mark.parametrize("seed", [0, 1])
def test_batch_matches_per_record_oracle(aggset, seed):
    aggs = AGG_SETS[aggset]
    ex_batch = make_ex(aggs)
    ex_oracle = make_ex(aggs)
    out_b, out_r = [], []
    for rows, ts in gen(seed):
        out_b.extend(ex_batch.process(rows, ts))
        out_r.extend(oracle_process(ex_oracle, rows, ts))
    assert canon_state(ex_batch) == canon_state(ex_oracle)
    assert canon_rows(out_b) == canon_rows(out_r)


def test_emit_changes_touched_keys():
    aggs = [AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, emit_changes=True)
    rows = [{"k": "a", "v": 1.0}, {"k": "b", "v": 2.0}]
    out = ex.process(rows, [BASE, BASE + 100])
    assert {r["k"] for r in out} == {"a", "b"}
    assert all(r["c"] == 1 for r in out)


def test_multi_column_group_key():
    schema = Schema.of(k=ColumnType.STRING, r=ColumnType.INT,
                       v=ColumnType.FLOAT)
    node = AggregateNode(
        child=SourceNode("s", schema),
        group_keys=[Col("k"), Col("r")],
        window=SessionWindow(1000, grace_ms=0),
        aggs=[AggSpec(AggKind.SUM, "s", input=Col("v"))])
    ex = SessionExecutor(node, schema)
    ex.use_device_sessions = False  # host engine: inspects ex.sessions
    rows = [{"k": "a", "r": 1, "v": 1.0}, {"k": "a", "r": 2, "v": 2.0},
            {"k": "a", "r": 1, "v": 3.0}]
    ex.process(rows, [BASE, BASE, BASE + 10])
    assert len(ex.sessions) == 2
    got = ex.process([{"k": "z", "v": 0.0}], [BASE + 100_000])
    # both (a,1) and (a,2) sessions closed with correct sums
    sums = {(r["k"], r["r"]): r["s"] for r in got}
    assert sums == {("a", 1): 4.0, ("a", 2): 2.0}


def test_non_numeric_input_skipped_both_paths():
    """A malformed value must be NULLed identically on the vectorized
    and late-segment per-record paths (not crash on one of them)."""
    aggs = [AggSpec(AggKind.SUM, "s", input=Col("v")),
            AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, gap=1000, grace=0)
    ex.process([{"k": "a", "v": 1.0}], [BASE + 50_000])  # wm forward
    # late batch (seg_t0 + gap <= wm) with a junk value -> per-record
    # fallback; on-time junk -> vectorized path. Neither may raise.
    out = ex.process(
        [{"k": "a", "v": "junk"}, {"k": "a", "v": 2.0},
         {"k": "b", "v": "junk"}],
        [BASE + 49_900, BASE + 49_950, BASE + 51_000])
    rows = ex.process([{"k": "z", "v": 0.0}], [BASE + 200_000])
    got = {r["k"]: (r["c"], r["s"]) for r in rows if r["k"] in "ab"}
    assert got["a"] == (3, 3.0), got   # junk counted, not summed
    assert got["b"] == (1, 0.0), got


def test_numeric_strings_null_both_paths():
    """NUMERIC strings ("42") must be NULLed exactly like junk strings
    on BOTH engines: np.asarray silently coerced an all-numeric-string
    batch to floats on the vectorized path while the per-record slow
    path NULLed it — the same record then aggregated differently
    depending on lateness (ISSUE 1 satellite, session.py NULL rule)."""
    aggs = [AggSpec(AggKind.SUM, "s", input=Col("v")),
            AggSpec(AggKind.COUNT, "n", input=Col("v")),
            AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, gap=1000, grace=0)
    ex.process([{"k": "a", "v": 1.0}], [BASE + 50_000])  # wm forward
    # same shape as the junk test: the late rows walk the per-record
    # fallback, the on-time row the vectorized path — but every string
    # here PARSES as a number, the case np.asarray used to coerce
    out = ex.process(
        [{"k": "a", "v": "7.5"}, {"k": "a", "v": "3"},
         {"k": "b", "v": "42"}],
        [BASE + 49_900, BASE + 49_950, BASE + 51_000])
    assert out == []
    rows = ex.process([{"k": "z", "v": 0.0}], [BASE + 200_000])
    got = {r["k"]: (r["c"], r["n"], r["s"])
           for r in rows if r["k"] in "ab"}
    assert got["a"] == (3, 1, 1.0), got  # strings counted, never summed
    assert got["b"] == (1, 0, 0.0), got


def test_ragged_sequence_values_nulled_not_crash():
    """List-valued (ragged) column cells must be NULLed on the
    vectorized path — np.asarray raises on inhomogeneous shapes and
    that must not kill the query."""
    aggs = [AggSpec(AggKind.SUM, "s", input=Col("v")),
            AggSpec(AggKind.COUNT_ALL, "c")]
    ex = make_ex(aggs, gap=1000, grace=0)
    ex.process([{"k": "a", "v": [1.0, 2.0]}, {"k": "a", "v": [3.0]},
                {"k": "a", "v": 5.0}], [BASE, BASE + 10, BASE + 20])
    rows = ex.process([{"k": "z", "v": 0.0}], [BASE + 200_000])
    got = {r["k"]: (r["c"], r["s"]) for r in rows if r["k"] == "a"}
    assert got["a"] == (3, 5.0), got
