"""Runtime lock-order witness unit tests (ISSUE 14).

GoodLock semantics on the TracedLock wrapper: cycle detection fires on
an order inversion WITHOUT needing the unlucky schedule, re-entrant
RLocks and same-name lock families never false-positive, a Condition
over a traced lock keeps the held-set truthful across waits, the
disarmed wrapper records nothing, and the seeded `yield:` perturber
replays deterministically.
"""

from __future__ import annotations

import threading
import time

import pytest

from hstream_tpu.common import locktrace
from hstream_tpu.common.faultinject import FAULTS
from hstream_tpu.common.locktrace import LOCKTRACE, TracedLock
from hstream_tpu.stats import StatsHolder
from hstream_tpu.stats.events import EventJournal


@pytest.fixture(autouse=True)
def _fresh_witness():
    """LOCKTRACE is process-global: every test starts and ends
    disarmed with no residual graph (and no armed fault sites)."""
    LOCKTRACE.disarm()
    FAULTS.disarm()
    yield
    LOCKTRACE.disarm()
    FAULTS.disarm()
    LOCKTRACE.bind(stats=None, events=None)


def test_cycle_detection_fires_on_inversion_without_deadlock():
    """A -> B in one section, B -> A in a later one: the second edge
    direction closes the ring and reports a POTENTIAL deadlock even
    though this single thread never deadlocks (the GoodLock point)."""
    events = EventJournal()
    LOCKTRACE.bind(events=events)
    LOCKTRACE.arm()
    a = locktrace.lock("t.a")
    b = locktrace.lock("t.b")
    with a:
        with b:
            pass
    assert LOCKTRACE.cycles() == []
    with b:
        with a:
            pass
    cycles = LOCKTRACE.cycles()
    assert len(cycles) == 1
    ring = cycles[0]["ring"]
    assert sorted(tuple(e) for e in ring) == [("t.a", "t.b"),
                                              ("t.b", "t.a")]
    # the witness names the thread and the full held stack per edge
    wit = cycles[0]["witness"]
    assert set(wit) == {"t.a->t.b", "t.b->t.a"}
    assert all("thread" in w and "holding" in w for w in wit.values())
    # journaled exactly once as a lock_cycle event
    kinds = [e["kind"] for e in events.query(limit=100)]
    assert kinds.count("lock_cycle") == 1
    # the SAME inversion again does not re-report (edge already known)
    with b:
        with a:
            pass
    assert len(LOCKTRACE.cycles()) == 1


def test_reentrant_rlock_no_false_positive():
    """Re-entering one RLock instance adds no edge (no self-cycle),
    and depth counting pairs releases correctly."""
    LOCKTRACE.arm()
    r = locktrace.rlock("t.r")
    other = locktrace.lock("t.o")
    with r:
        with r:           # re-entrant: depth only
            with other:
                pass
    assert LOCKTRACE.cycles() == []
    st = LOCKTRACE.status()
    assert st["edges"] == {"t.r": ["t.o"]}
    # fully released: a fresh thread can take (and release) it
    grabbed = []

    def grab():
        if r.acquire(timeout=1):
            grabbed.append(True)
            r.release()

    t = threading.Thread(target=grab)
    t.start()
    t.join()
    assert grabbed == [True]


def test_same_name_family_nesting_adds_no_edge():
    """Two instances of one lock ROLE nested (append-front lanes) add
    no self-edge — instance identity is not class identity."""
    LOCKTRACE.arm()
    lanes = locktrace.lock_list("t.lane", 2)
    with lanes[0]:
        with lanes[1]:
            pass
    assert LOCKTRACE.edge_count() == 0
    assert LOCKTRACE.cycles() == []


def test_disarmed_wrapper_records_nothing():
    """Disarmed contract: nested acquires leave NO graph, NO counts,
    NO cycles — the one-attribute-read + one-branch path."""
    a = locktrace.lock("t.da")
    b = locktrace.lock("t.db")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert LOCKTRACE.edge_count() == 0
    st = LOCKTRACE.status()
    assert st["locks"] == {} and st["cycles"] == []
    assert not st["armed"]


def test_wait_hold_histograms_and_contention_counter():
    """Bound StatsHolder: a contended acquire counts lock_contention
    and lands in lock_wait_ms; every release lands in lock_hold_ms."""
    stats = StatsHolder()
    LOCKTRACE.bind(stats=stats)
    LOCKTRACE.arm()
    lk = locktrace.lock("t.cont")
    held = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            held.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    assert held.wait(5)
    got = []

    def contender():
        with lk:
            got.append(True)

    t2 = threading.Thread(target=contender)
    t2.start()
    time.sleep(0.05)
    release.set()
    t.join(5)
    t2.join(5)
    assert got == [True]
    assert stats.stream_stat_get("lock_contention", "t.cont") == 1
    hists = stats.histograms_snapshot()
    assert ("lock_wait_ms", "t.cont") in hists
    hold = hists[("lock_hold_ms", "t.cont")]
    assert hold.count == 2  # holder + contender both released
    # the ledger surfaces percentiles when stats are bound
    row = LOCKTRACE.status()["locks"]["t.cont"]
    assert row["acquires"] == 2 and row["contentions"] == 1
    assert row["wait_p50_ms"] is not None
    assert row["hold_p50_ms"] is not None


def test_condition_over_traced_lock_releases_during_wait():
    """threading.Condition(TracedLock): wait() really releases the
    wrapper (another thread acquires it mid-wait), the held-set drops
    the entry, and notify wakes the waiter — semantics preserved."""
    LOCKTRACE.arm()
    lk = locktrace.lock("t.cv")
    cv = threading.Condition(lk)
    state = {"woke": False}
    waiting = threading.Event()

    def waiter():
        with cv:
            waiting.set()
            cv.wait(timeout=5)
            state["woke"] = True

    t = threading.Thread(target=waiter)
    t.start()
    assert waiting.wait(5)
    # the waiter is inside wait(): the lock must be takeable NOW
    assert lk.acquire(timeout=2)
    lk.release()
    with cv:
        cv.notify_all()
    t.join(5)
    assert state["woke"]
    assert LOCKTRACE.cycles() == []


def test_condition_over_traced_rlock_wait_notify():
    """The re-entrant wrapper forwards the Condition protocol
    (_release_save/_acquire_restore/_is_owned) to the inner RLock."""
    LOCKTRACE.arm()
    cv = threading.Condition(locktrace.rlock("t.rcv"))
    woke = threading.Event()

    def waiter():
        with cv:
            cv.wait(timeout=5)
            woke.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(5)
    assert woke.is_set()


def test_rearm_after_disarm_starts_fresh():
    LOCKTRACE.arm()
    a = locktrace.lock("t.fa")
    b = locktrace.lock("t.fb")
    with a:
        with b:
            pass
    assert LOCKTRACE.edge_count() == 1
    LOCKTRACE.disarm()
    LOCKTRACE.arm()
    assert LOCKTRACE.edge_count() == 0
    with b:
        with a:
            pass
    # the PRIOR direction was forgotten with the disarm: no cycle
    assert LOCKTRACE.cycles() == []


def test_disarm_straddling_acquire_leaves_no_stale_holder():
    """Review fix (ISSUE 14): a thread that passes the wrapper's armed
    gate just before a disarm must not leave a stale held-set entry —
    its release runs disarmed and would never pair up, and every lock
    the thread takes after a re-arm would appear falsely nested under
    the ghost holder. note_acquire re-checks `active`, and the
    generation bump discards any stack that straddled the boundary."""
    LOCKTRACE.arm()
    a = locktrace.lock("t.sa")
    b = locktrace.lock("t.sb")
    a.acquire()           # held entry recorded while armed
    LOCKTRACE.disarm()    # gen bump: the recorded stack is stale
    a.release()           # disarmed release: note_release skipped
    LOCKTRACE.arm()
    # the ghost holder must be gone: taking b then a in the "wrong"
    # order relative to the ghost must create NO edge from t.sa
    with b:
        pass
    st = LOCKTRACE.status()
    assert st["edges"] == {} and st["cycles"] == []
    # and the direct shape: note_acquire entered while disarmed
    # records nothing even if the gate was passed before the flip
    LOCKTRACE.disarm()
    LOCKTRACE.note_acquire(a, 0.0, contended=False)
    LOCKTRACE.arm()
    with b:
        pass
    st = LOCKTRACE.status()
    assert st["edges"] == {} and st["cycles"] == []


def test_yield_perturber_is_seeded_and_deterministic():
    """yield:N[:SEED] injects the same decision stream per seed; every
    traced acquire is a lock.acquire.<name> fault site."""
    lk = locktrace.lock("t.y")

    def run(seed):
        FAULTS.disarm()
        FAULTS.arm(lk.site, f"yield:3:{seed}")
        for _ in range(60):
            with lk:
                pass
        st = FAULTS.status()[lk.site]
        return st["hits"], st["injected"]

    h1, i1 = run(7)
    h2, i2 = run(7)
    h3, i3 = run(11)
    assert (h1, i1) == (h2, i2) == (60, i1)
    assert i1 > 0  # ~1/3 of 60 hits yield; a zero means the schedule
    #                never fired and the perturber is dead
    assert h3 == 60  # different seed: same hit count, its own stream


def test_yield_rejects_bad_n():
    with pytest.raises(ValueError):
        FAULTS.arm("x", "yield:0")
    with pytest.raises(ValueError):
        FAULTS.arm("x", "yield")
