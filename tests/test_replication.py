"""Multi-host store replication (SURVEY §2.3 "storage replication"):
real follower PROCESSES over gRPC, kill one mid-append, verify the
survivors hold everything and the rejoined replica converges.

Reference: the storage tier is a replicated LogDevice cluster
(hstream/app/server.hs:83-90 replicate-factor flags)."""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time

import grpc
import pytest

from hstream_tpu.proto import api_pb2 as pb
from hstream_tpu.proto.rpc import StoreReplicaStub
from hstream_tpu.store import open_store
from hstream_tpu.store.api import DataBatch
from hstream_tpu.store.replica import (
    OPLOG_ID,
    FollowerService,
    ReplicatedStore,
    serve_follower,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def spawn_follower(store_dir: str, port: int,
                   node_id: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [sys.executable, "-m", "hstream_tpu.store.replica",
         "--store", store_dir, "--listen", f"127.0.0.1:{port}",
         "--node-id", node_id],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)


def wait_follower_up(port: int, timeout: float = 90.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
                StoreReplicaStub(ch).ReplicaInfo(
                    pb.ReplicaInfoRequest(), timeout=1)
            return
        except grpc.RpcError:
            time.sleep(0.2)
    raise TimeoutError(f"follower on {port} never came up")


def follower_seq(port: int) -> int:
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        return StoreReplicaStub(ch).ReplicaInfo(
            pb.ReplicaInfoRequest(), timeout=2).applied_seq


def log_contents(store, logid: int) -> list[tuple[int, tuple[bytes, ...]]]:
    tail = store.tail_lsn(logid)
    if tail == 0:
        return []
    r = store.new_reader()
    r.set_timeout(0)
    r.start_reading(logid, 1, tail)
    out = []
    while True:
        items = r.read(512)
        if not items:
            break
        for it in items:
            if isinstance(it, DataBatch):
                out.append((it.lsn, it.payloads))
    return out


def wait_caught_up(leader: ReplicatedStore, port: int,
                   timeout: float = 90.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            if follower_seq(port) >= leader.oplog_seq:
                return
        except grpc.RpcError:
            pass
        time.sleep(0.2)
    raise TimeoutError("follower never converged")


def test_three_node_kill_and_rejoin(tmp_path):
    """Kill 1 of 3 replicas mid-append: appends keep succeeding, the
    survivors hold everything, the restarted replica converges to a
    byte-identical store."""
    dirs = {n: str(tmp_path / n) for n in ("a", "b", "c")}
    pb_port, pc_port = free_port(), free_port()
    proc_b = spawn_follower(dirs["b"], pb_port, "b")
    proc_c = spawn_follower(dirs["c"], pc_port, "c")
    leader = None
    try:
        wait_follower_up(pb_port)
        wait_follower_up(pc_port)
        leader = ReplicatedStore(
            open_store(dirs["a"]),
            [f"127.0.0.1:{pb_port}", f"127.0.0.1:{pc_port}"],
            replication_factor=3)
        LOG = 42
        leader.create_log(LOG)
        for i in range(50):
            leader.append(LOG, f"rec-{i}".encode())
        # kill follower c mid-stream; appends must keep succeeding
        proc_c.send_signal(signal.SIGKILL)
        proc_c.wait(10)
        for i in range(50, 100):
            leader.append(LOG, f"rec-{i}".encode())
        assert leader.tail_lsn(LOG) == 100
        wait_caught_up(leader, pb_port)

        # restart c: it must catch up from the leader's op-log
        proc_c = spawn_follower(dirs["c"], pc_port, "c")
        wait_follower_up(pc_port)
        wait_caught_up(leader, pc_port)

        want = log_contents(leader.local, LOG)
        assert len(want) == 100
        # stop everything and compare the on-disk stores directly
        for p in (proc_b, proc_c):
            p.send_signal(signal.SIGTERM)
            p.wait(10)
        for n in ("b", "c"):
            st = open_store(dirs[n])
            assert log_contents(st, LOG) == want, f"replica {n} diverged"
            assert st.tail_lsn(OPLOG_ID) == leader.oplog_seq
            st.close()
    finally:
        for p in (proc_b, proc_c):
            if p.poll() is None:
                p.kill()
        if leader is not None:
            leader.close()


def test_replication_in_process_all_ops(tmp_path):
    """Every op kind replicates (append/trim/create/remove/meta) — one
    in-process follower, mem stores."""
    follower_store = open_store("mem://")
    port = free_port()
    server, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{port}"],
                             replication_factor=2)
    try:
        leader.create_log(7)
        for i in range(10):
            leader.append_batch(7, [f"x{i}".encode(), b"y"])
        leader.trim(7, 3)
        leader.meta_put("k1", b"v1")
        leader.meta_put("k2", b"v2")
        leader.meta_delete("k2")
        leader.create_log(8)
        leader.remove_log(8)
        deadline = time.time() + 15
        while (time.time() < deadline
               and svc.applied_seq < leader.oplog_seq):
            time.sleep(0.05)
        assert svc.applied_seq == leader.oplog_seq
        assert log_contents(follower_store, 7) == \
            log_contents(leader.local, 7)
        assert follower_store.trim_point(7) == 3
        assert follower_store.meta_get("k1") == b"v1"
        assert follower_store.meta_get("k2") is None
        assert not follower_store.log_exists(8)
    finally:
        leader.close()
        server.stop(grace=1)


def test_degraded_append_when_follower_down(tmp_path):
    """No live follower: appends still succeed (availability over
    strict durability, logged as degraded)."""
    dead_port = free_port()
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{dead_port}"],
                             replication_factor=2)
    try:
        leader.create_log(1)
        t0 = time.time()
        lsn = leader.append(1, b"solo")
        assert lsn == 1
        assert time.time() - t0 < 6.0
    finally:
        leader.close()


def test_replication_factor_roundtrips_through_stream_api():
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    server, ctx = serve("127.0.0.1", 0, "mem://")
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="rf", replication_factor=3))
        got = {s.stream_name: s.replication_factor
               for s in stub.ListStreams(pb.ListStreamsRequest()).streams}
        assert got["rf"] == 3
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()


def test_server_leader_mode_replicates_streams():
    """serve(replicate=...) wraps the store: stream creates + appends
    through the public API land on the follower replica."""
    from hstream_tpu.common import records as rec
    from hstream_tpu.proto.rpc import HStreamApiStub
    from hstream_tpu.server.main import serve

    follower_store = open_store("mem://")
    fport = free_port()
    fsrv, svc = serve_follower(follower_store, f"127.0.0.1:{fport}")
    server, ctx = serve("127.0.0.1", 0, "mem://",
                        replicate=f"127.0.0.1:{fport}",
                        replication_factor=2)
    ch = grpc.insecure_channel(f"127.0.0.1:{ctx.port}")
    stub = HStreamApiStub(ch)
    try:
        stub.CreateStream(pb.Stream(stream_name="rs"))
        req = pb.AppendRequest(stream_name="rs")
        for i in range(5):
            req.records.append(rec.build_record({"i": i}))
        stub.Append(req)
        deadline = time.time() + 15
        while (time.time() < deadline
               and svc.applied_seq < ctx.store.oplog_seq):
            time.sleep(0.05)
        logid = ctx.streams.get_logid("rs")
        assert log_contents(follower_store, logid) == \
            log_contents(ctx.store.local, logid)
        assert follower_store.meta_list("") != []
    finally:
        ch.close()
        server.stop(grace=1)
        ctx.shutdown()
        fsrv.stop(grace=1)


def test_apply_idempotent_and_reconcile():
    """Crash in the log/apply window: re-applying the last op-log entry
    is a no-op (appends guarded by expect_lsn), and _reconcile applies
    a logged-but-unapplied tail entry."""
    from hstream_tpu.store.replica import _apply, _encode_entry, _reconcile

    st = open_store("mem://")
    st.create_log(OPLOG_ID)
    st.create_log(5)
    e = pb.LogEntry(op=pb.OP_APPEND, logid=5, payloads=[b"a"],
                    expect_lsn=1, append_time_ms=123)
    # leader order: log first, crash before apply -> reconcile applies
    st.append(OPLOG_ID, _encode_entry(e))
    _reconcile(st)
    assert st.tail_lsn(5) == 1
    # re-applying the same entry must be a no-op
    _apply(st, e)
    assert st.tail_lsn(5) == 1
    assert st.find_time(5, 123) == 1


def test_append_time_replicates():
    """Replicas answer find_time identically: the leader's stamp rides
    the entry."""
    follower_store = open_store("mem://")
    port = free_port()
    server, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{port}"],
                             replication_factor=2)
    try:
        leader.create_log(9)
        leader.append_batch(9, [b"x"], append_time_ms=1000)
        leader.append_batch(9, [b"y"], append_time_ms=2000)
        deadline = time.time() + 15
        while (time.time() < deadline
               and svc.applied_seq < leader.oplog_seq):
            time.sleep(0.05)
        assert follower_store.find_time(9, 1500) == \
            leader.local.find_time(9, 1500) == 2
    finally:
        leader.close()
        server.stop(grace=1)


def test_degraded_ack_status_surfaces(tmp_path):
    """An ack that returned because followers are down/dead must record
    a degraded durability status — callers can no longer mistake it for
    full replication (ISSUE 1 satellite)."""
    dead_port = free_port()
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{dead_port}"],
                             replication_factor=2)
    try:
        leader.create_log(1)
        leader.append(1, b"solo")
        assert leader.last_ack_status == "degraded:followers_down"
        assert leader.degraded_appends >= 1
        st = leader.follower_status()
        assert st[0]["last_ack_status"] == "degraded:followers_down"
        assert st[0]["behind"] >= 1
    finally:
        leader.close()


def test_slow_follower_ack_times_out_degraded(monkeypatch):
    """A follower that is LIVE but never applies (stalled disk, wedged
    process) must degrade the ack at the timeout, not report success."""
    from hstream_tpu.store import replica as repl

    monkeypatch.setattr(repl, "_ACK_TIMEOUT_S", 0.4)
    leader = ReplicatedStore(open_store("mem://"), [],
                             replication_factor=2)

    class _SlowFollower:
        addr = "slow:1"
        alive = True
        acked_seq = 0

    try:
        leader.create_log(1)          # before injection: clean ack
        assert leader.last_ack_status == "replicated"
        leader._followers = [_SlowFollower()]
        lsn = leader.append_batch(1, [b"x"])
        assert lsn == 1               # availability kept...
        assert leader.last_ack_status == "degraded:timeout"  # ...honestly
        assert leader.degraded_appends == 1
    finally:
        leader._followers = []
        leader.close()


def test_follower_leader_binding_survives_restart():
    """The accepted leader id persists in store meta: a RESTARTED
    follower keeps rejecting a stale/second leader instead of accepting
    whichever connects first (ISSUE 1 satellite)."""
    follower_store = open_store("mem://")
    port = free_port()
    server, _svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            stub = StoreReplicaStub(ch)
            e = pb.LogEntry(seq=1, op=pb.OP_CREATE_LOG, logid=3)
            stub.Replicate(pb.ReplicateRequest(entries=[e],
                                               leader_id="L1"), timeout=5)
    finally:
        server.stop(grace=1)
    assert follower_store.meta_get("replica/leader_id") == b"L1"
    # "restart": a fresh service over the same store must reload the
    # binding and reject a different leader BEFORE applying anything
    port2 = free_port()
    server2, svc2 = serve_follower(follower_store, f"127.0.0.1:{port2}")
    try:
        assert svc2._leader_id == "L1"
        with grpc.insecure_channel(f"127.0.0.1:{port2}") as ch:
            stub = StoreReplicaStub(ch)
            try:
                stub.Replicate(pb.ReplicateRequest(
                    entries=[pb.LogEntry(seq=2, op=pb.OP_CREATE_LOG,
                                         logid=4)],
                    leader_id="L2"), timeout=5)
                raise AssertionError("stale-leader bind accepted")
            except grpc.RpcError as err:
                assert err.code() == grpc.StatusCode.FAILED_PRECONDITION
            assert not follower_store.log_exists(4)
            # the ORIGINAL leader still replicates after the restart
            stub.Replicate(pb.ReplicateRequest(
                entries=[pb.LogEntry(seq=2, op=pb.OP_CREATE_LOG,
                                     logid=5)],
                leader_id="L1"), timeout=5)
            assert follower_store.log_exists(5)
    finally:
        server2.stop(grace=1)


def test_follower_rejects_second_leader():
    follower_store = open_store("mem://")
    port = free_port()
    server, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
            stub = StoreReplicaStub(ch)
            e = pb.LogEntry(seq=1, op=pb.OP_CREATE_LOG, logid=3)
            stub.Replicate(pb.ReplicateRequest(entries=[e],
                                               leader_id="L1"), timeout=5)
            try:
                stub.Replicate(pb.ReplicateRequest(
                    entries=[], leader_id="L2"), timeout=5)
                raise AssertionError("second leader accepted")
            except grpc.RpcError as err:
                assert err.code() == grpc.StatusCode.FAILED_PRECONDITION
    finally:
        server.stop(grace=1)


def test_leader_restart_keeps_feeding_followers(tmp_path):
    """A restarted leader keeps its persisted node id, so live
    followers accept its entries instead of pinning the old identity."""
    follower_store = open_store("mem://")
    port = free_port()
    server, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    d = str(tmp_path / "lead")
    try:
        leader = ReplicatedStore(open_store(d), [f"127.0.0.1:{port}"],
                                 replication_factor=2)
        nid = leader.node_id
        leader.create_log(11)
        leader.append(11, b"one")
        deadline = time.time() + 15
        while (time.time() < deadline
               and svc.applied_seq < leader.oplog_seq):
            time.sleep(0.05)
        leader.close()
        # restart on the same store dir: same node id, follower accepts
        leader = ReplicatedStore(open_store(d), [f"127.0.0.1:{port}"],
                                 replication_factor=2)
        assert leader.node_id == nid
        leader.append(11, b"two")
        deadline = time.time() + 15
        while (time.time() < deadline
               and svc.applied_seq < leader.oplog_seq):
            time.sleep(0.05)
        assert svc.applied_seq == leader.oplog_seq
        assert log_contents(follower_store, 11) == \
            log_contents(leader.local, 11)
        leader.close()
    finally:
        server.stop(grace=1)


# ---- ISSUE 9: epoch-fenced failover + idempotent appends --------------------


def test_dedup_window_semantics_single_store():
    """Window contract (store/dedup.py): new seq appends + records,
    remembered seq answers the ORIGINAL (lsn, n), and a seq at/below
    the watermark but evicted from the bounded window refuses loudly
    (DuplicateAppend) instead of silently re-appending."""
    import threading

    import pytest

    from hstream_tpu.common.errors import DuplicateAppend
    from hstream_tpu.store import dedup
    from hstream_tpu.store.api import Compression

    st = open_store("mem://")
    st.create_log(3)
    lock = threading.Lock()

    def app(seq, payloads):
        return dedup.guarded_append(st, lock, 3, payloads,
                                    Compression.NONE, "p1", seq)

    lsn1, n1, dup1 = app(1, [b"a", b"b"])
    assert (n1, dup1) == (2, False)
    # retry: original ids, nothing re-stored
    assert app(1, [b"a", b"b"]) == (lsn1, 2, True)
    assert st.tail_lsn(3) == lsn1
    # fill past the window; seq 1 falls off
    for seq in range(2, dedup.DEDUP_WINDOW + 3):
        app(seq, [b"x"])
    with pytest.raises(DuplicateAppend):
        app(1, [b"a", b"b"])
    # independent producers keep independent windows
    assert dedup.guarded_append(st, lock, 3, [b"y"], Compression.NONE,
                                "p2", 1)[2] is False
    assert dedup.window_size(st) == dedup.DEDUP_WINDOW + 1
    st.close()


def test_dedup_window_replicates_with_the_oplog():
    """The producer stamp rides the replicated LogEntry: after
    convergence the follower's dedup window is byte-identical to the
    leader's — a promoted follower can answer a producer's retry with
    the original LSN (the exactly-once-across-failover invariant)."""
    from hstream_tpu.store import dedup
    from hstream_tpu.store.api import Compression

    follower_store = open_store("mem://")
    port = free_port()
    server, svc = serve_follower(follower_store, f"127.0.0.1:{port}")
    leader = ReplicatedStore(open_store("mem://"),
                             [f"127.0.0.1:{port}"],
                             replication_factor=2)
    try:
        leader.create_log(4)
        lsn, n, dup = leader.append_batch_dedup(
            4, [b"r1", b"r2"], Compression.NONE,
            producer_id="pp", producer_seq=1)
        assert (n, dup) == (2, False)
        # a racing retry on the SAME leader is answered from the window
        assert leader.append_batch_dedup(
            4, [b"r1", b"r2"], Compression.NONE,
            producer_id="pp", producer_seq=1) == (lsn, 2, True)
        wait_caught_up(leader, port)
        assert follower_store.meta_get("dedup/pp") == \
            leader.local.meta_get("dedup/pp")
        # the follower (a promotion candidate) would answer the retry
        # with the original lsn, straight from its replicated window
        assert dedup.lookup(follower_store, "pp", 1) == (lsn, 2)
    finally:
        leader.close()
        server.stop(grace=1)


def test_planned_handoff_fences_seals_and_demoted_rejoins():
    """admin promote --target end-to-end at the store layer: the old
    leader fences itself (typed NotLeaderError + hint), the OTHER
    follower is sealed at the new epoch in the same verb, and the
    demoted leader rejoins as a follower of the new leader through the
    ordinary catch-up path — every store converges identically."""
    from hstream_tpu.common.errors import NotLeaderError

    f1_store, f2_store = open_store("mem://"), open_store("mem://")
    p1, p2, pr = free_port(), free_port(), free_port()
    s1, svc1 = serve_follower(f1_store, f"127.0.0.1:{p1}",
                              node_id="hand-f1")
    s2, svc2 = serve_follower(f2_store, f"127.0.0.1:{p2}",
                              node_id="hand-f2")
    leader = ReplicatedStore(
        open_store("mem://"),
        [f"127.0.0.1:{p1}", f"127.0.0.1:{p2}"], replication_factor=3)
    new_leader = None
    rejoin_srv = None
    try:
        leader.create_log(6)
        for i in range(3):
            leader.append(6, f"pre-{i}".encode())
        wait_caught_up(leader, p1)
        wait_caught_up(leader, p2)

        res = leader.promote_follower(f"127.0.0.1:{p1}",
                                      leader_addr="client-new:1")
        assert res["ok"] and res["node_id"] == "hand-f1"
        assert res["sealed"] == [f"127.0.0.1:{p2}"]
        assert svc1.is_leader and svc1.epoch == 1
        assert svc2.epoch == 1 and not svc2.is_leader
        # the demoted leader refuses mutations with the typed hint
        try:
            leader.append(6, b"stale")
            raise AssertionError("fenced leader accepted an append")
        except NotLeaderError as e:
            assert e.leader_hint == "client-new:1"
        assert leader.fenced_appends == 1
        assert leader.leader_status()["fenced"] is True

        # the demoted node rejoins as a FOLLOWER over its own store;
        # the new leader (over f1's store, same persisted identity)
        # catches it up through the normal path
        rejoin_srv, rejoin_svc = serve_follower(
            leader.local, f"127.0.0.1:{pr}", node_id="demoted")
        new_leader = ReplicatedStore(
            f1_store, [f"127.0.0.1:{p2}", f"127.0.0.1:{pr}"],
            replication_factor=3, client_addr="client-new:1")
        assert new_leader.epoch == 1
        assert new_leader.node_id == "hand-f1"
        for i in range(3):
            new_leader.append(6, f"post-{i}".encode())
        wait_caught_up(new_leader, p2)
        wait_caught_up(new_leader, pr)
        want = log_contents(new_leader.local, 6)
        assert len(want) == 6
        assert log_contents(f2_store, 6) == want
        assert log_contents(leader.local, 6) == want
        rejoin_srv.stop(grace=1)
        rejoin_svc.close()
        rejoin_srv = None
    finally:
        if new_leader is not None:
            # new_leader shares f1_store; close only the replication
            # machinery of the original leader afterwards
            new_leader._stop.set()
            for f in new_leader._followers:
                f._thread.join(timeout=2)
            new_leader._async_pool.shutdown(wait=True)
        if rejoin_srv is not None:
            rejoin_srv.stop(grace=1)
        leader.close()
        svc1.close()
        svc2.close()
        s1.stop(grace=1)
        s2.stop(grace=1)
        f1_store.close()
        f2_store.close()
