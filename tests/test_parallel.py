"""Multi-chip lattice sharding tests on the 8-virtual-device CPU mesh.

Checks that the sharded executor is semantically identical to the
single-chip one: partial lattices over the data axis plus key sharding
must merge to the exact same aggregates.
"""

import numpy as np
import pytest

from hstream_tpu.engine import (
    AggKind,
    AggSpec,
    AggregateNode,
    ColumnType,
    QueryExecutor,
    Schema,
    SourceNode,
    TumblingWindow,
    HoppingWindow,
)
from hstream_tpu.engine.expr import BinOp, Col, Lit
from hstream_tpu.parallel import ShardedQueryExecutor, make_mesh

SCHEMA = Schema.of(device=ColumnType.STRING, temp=ColumnType.FLOAT)
BASE = 1_700_000_000_000


def node_of(aggs, window, child=None):
    return AggregateNode(
        child=child or SourceNode("s", SCHEMA),
        group_keys=[Col("device")], window=window, aggs=aggs)


def gen_rows(n, n_keys=13, seed=0):
    rng = np.random.default_rng(seed)
    rows = [{"device": f"d{int(rng.integers(n_keys))}",
             "temp": float(rng.normal(10.0, 5.0))} for _ in range(n)]
    ts = [BASE + int(t) for t in np.sort(rng.integers(0, 25_000, size=n))]
    return rows, ts


AGGS = [
    AggSpec(AggKind.COUNT_ALL, "cnt"),
    AggSpec(AggKind.SUM, "total", input=Col("temp")),
    AggSpec(AggKind.MIN, "mn", input=Col("temp")),
    AggSpec(AggKind.MAX, "mx", input=Col("temp")),
    AggSpec(AggKind.AVG, "avg", input=Col("temp")),
]


def run_both(mesh, aggs, window, *, emit_changes=False, n=600):
    ref = QueryExecutor(node_of(aggs, window), SCHEMA,
                        emit_changes=emit_changes, initial_keys=16,
                        batch_capacity=256)
    sh = ShardedQueryExecutor(node_of(aggs, window), SCHEMA, mesh=mesh,
                              emit_changes=emit_changes, initial_keys=16,
                              batch_capacity=256)
    rows, ts = gen_rows(n)
    out_ref, out_sh = [], []
    for i in range(0, n, 200):
        out_ref.extend(ref.process(rows[i:i + 200], ts[i:i + 200]))
        out_sh.extend(sh.process(rows[i:i + 200], ts[i:i + 200]))
    closer = [{"device": "d0", "temp": 0.0}], [BASE + 80_000]
    out_ref.extend(ref.process(*closer))
    out_sh.extend(sh.process(*closer))
    return out_ref, out_sh


def keyed(rows):
    return {(r["device"], r.get("winStart")):
            {k: v for k, v in r.items() if k not in ("device", "winStart")}
            for r in rows}


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(n_data=4, n_key=2)


def assert_same(out_ref, out_sh):
    ref_k, sh_k = keyed(out_ref), keyed(out_sh)
    assert set(ref_k) == set(sh_k)
    for key, vals in ref_k.items():
        for name, v in vals.items():
            assert sh_k[key][name] == pytest.approx(v, rel=1e-5), \
                (key, name)


def test_sharded_tumbling_matches_single_chip(mesh):
    out_ref, out_sh = run_both(mesh, AGGS, TumblingWindow(10_000,
                                                          grace_ms=0))
    assert len(out_ref) > 0
    assert_same(out_ref, out_sh)


def test_sharded_hopping_matches_single_chip(mesh):
    out_ref, out_sh = run_both(
        mesh, AGGS[:2], HoppingWindow(20_000, 10_000, grace_ms=0))
    assert len(out_ref) > 0
    assert_same(out_ref, out_sh)


def test_sharded_emit_changes_matches(mesh):
    out_ref, out_sh = run_both(mesh, AGGS[:2],
                               TumblingWindow(10_000, grace_ms=0),
                               emit_changes=True)
    # changelogs have per-batch granularity; the FINAL value per
    # (key, window) must agree
    ref_last, sh_last = {}, {}
    for r in out_ref:
        ref_last[(r["device"], r.get("winStart"))] = r
    for r in out_sh:
        sh_last[(r["device"], r.get("winStart"))] = r
    assert set(ref_last) == set(sh_last)
    for k in ref_last:
        assert sh_last[k]["cnt"] == ref_last[k]["cnt"]
        assert sh_last[k]["total"] == pytest.approx(ref_last[k]["total"],
                                                    rel=1e-5)


def test_sharded_sketches_match(mesh):
    aggs = [AggSpec(AggKind.APPROX_COUNT_DISTINCT, "u", input=Col("temp")),
            AggSpec(AggKind.APPROX_QUANTILE, "p50", input=Col("temp"),
                    quantile=0.5)]
    out_ref, out_sh = run_both(mesh, aggs, TumblingWindow(10_000,
                                                          grace_ms=0))
    # sketch registers are deterministic: shard merge must be bit-exact
    assert_same(out_ref, out_sh)


def test_sharded_filter_and_key_growth(mesh):
    from hstream_tpu.engine import FilterNode

    child = FilterNode(SourceNode("s", SCHEMA),
                       BinOp(">", Col("temp"), Lit(0.0)))
    node = AggregateNode(child=child, group_keys=[Col("device")],
                         window=TumblingWindow(10_000, grace_ms=0),
                         aggs=[AggSpec(AggKind.COUNT_ALL, "cnt")])
    sh = ShardedQueryExecutor(node, SCHEMA, mesh=mesh, emit_changes=False,
                              initial_keys=8, batch_capacity=256)
    ref = QueryExecutor(node, SCHEMA, emit_changes=False, initial_keys=8,
                        batch_capacity=256)
    rows, ts = gen_rows(400, n_keys=40)  # forces growth past 8 keys
    out_ref = ref.process(rows, ts)
    out_sh = sh.process(rows, ts)
    closer = [{"device": "d0", "temp": 1.0}], [BASE + 80_000]
    out_ref += ref.process(*closer)
    out_sh += sh.process(*closer)
    assert_same(out_ref, out_sh)
